"""Benchmark harness — prints ONE JSON line.

Primary metric: tokens/sec/chip training the flagship LLaMA-style decoder
(fwd+bwd+adamw update, bf16 compute, jit, donated state) on the available
accelerator. ``vs_baseline`` compares against the reference stack's realistic
ceiling on its own hardware: an A100 at 40% MFU running the same model
(BASELINE.md north star is "matching A100 Spark-executor throughput"; the
reference repo publishes no absolute numbers, BASELINE.json published={}).

Secondary fields (inside "extra"): achieved MFU on this chip and an ASHA
trials/hour measurement over the full lagom() control plane with a fast
synthetic train_fn (the reference's own primary metric, BASELINE.json).

Usage: python bench.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Every real-hardware run persists its numbers here; when the accelerator
# tunnel is wedged at round end (it dies if any client is killed mid-compile)
# the CPU-fallback record still carries the round's real measurement under
# extra.last_real_tpu — labeled as such, never substituted for the headline.
SNAPSHOT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_TPU_SNAPSHOT.json")


def ensure_live_backend(probe_timeout: float = 120.0) -> bool:
    """The TPU tunnel can wedge so that jax.devices() hangs forever; probe it
    in a subprocess first and fall back to CPU so the bench always completes
    and reports what it ran on. Returns True when the fallback engaged.
    An explicit JAX_PLATFORMS=cpu request pins through force_cpu (the tunnel
    plugin can hang even env-pinned processes at backend init) and counts as
    the CPU fallback — the full accelerator geometry makes no sense there."""
    from maggy_tpu.util import backend_alive, force_cpu, pin_cpu_if_requested

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        pin_cpu_if_requested()
        return True
    if backend_alive(probe_timeout):
        return False
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    force_cpu()
    print(
        "WARNING: accelerator backend unreachable; benchmarking on a CPU "
        "fallback mesh with a reduced geometry",
        file=sys.stderr,
    )
    return True


def count_params(tree) -> int:
    import flax.linen as nn
    import jax

    total = 0
    for leaf in jax.tree.leaves(
        tree, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    ):
        val = leaf.value if isinstance(leaf, nn.Partitioned) else leaf
        total += val.size
    return total


def bench_training_throughput(quick: bool = False, cpu_fallback: bool = False):
    import jax
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.data import synthetic_lm_batches

    n_chips = len(jax.devices())
    if cpu_fallback:
        # accelerator unreachable: record *something* comparable round-over-round
        cfg = DecoderConfig.tiny()
        batch_size, seq_len, n_steps = 8, 64, 5
    else:
        # ~260M-param geometry: saturates one v5e chip's MXU without blowing
        # HBM; scales to more chips via fsdp automatically. remat_policy="dots"
        # keeps matmul outputs and recomputes only elementwise work — measured
        # fastest (BENCH_NOTES round 2: dots 58.5k vs nothing 42.6k tok/s at
        # bs=8). head_dim=128 (8 heads) is the MXU-native layout (Llama-3
        # itself uses head_dim 128), which lets auto_attention route to the
        # Pallas flash kernel with its auto-tuned 512-row tiles — measured
        # fastest at every S once the tiles are right (66.9k vs dense 60.7k
        # tok/s at S=1024; the old 128x128 tiles LOST to dense, BENCH_NOTES).
        # bs=16/chip was the best of {8, 16, 32}.
        cfg = DecoderConfig(
            vocab_size=32_000,
            d_model=1024,
            n_layers=8 if quick else 12,
            n_heads=8,
            n_kv_heads=8,
            d_ff=4096,
            max_seq_len=1024,
            remat=True,
        )
        batch_size = 16 * max(1, n_chips)
        seq_len = 1024
        n_steps = 5 if quick else 20

    ctx = TrainContext.create("fsdp" if n_chips > 1 else "dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, batch_size, seq_len, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    n_params = count_params(state.params)

    # warmup (compile) then timed steps; float() forces a device->host transfer
    # as the timing barrier — block_until_ready alone is not a reliable sync on
    # every PJRT transport
    batch = trainer.shard_batch(next(data))
    state, m = trainer.step(state, batch)
    float(m["loss"])

    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, m = trainer.step(state, batch)
    float(m["loss"])
    dt = time.perf_counter() - t0

    tokens = n_steps * batch_size * seq_len
    tok_per_sec = tokens / dt
    tok_per_sec_chip = tok_per_sec / n_chips

    flops_per_token = 6 * n_params  # fwd+bwd matmul estimate
    achieved_flops = tok_per_sec_chip * flops_per_token
    # chip peak (bf16): v5e 197 TFLOPs, v5p 459; detect loosely, default v5e
    kind = str(jax.devices()[0]).lower()
    peak = 459e12 if "v5p" in kind or "p5" in kind else 197e12
    mfu = achieved_flops / peak

    # reference stack ceiling: A100 (312 TFLOPs bf16) at 40% MFU, same model
    a100_tok_per_sec = 312e12 * 0.40 / flops_per_token
    vs_a100 = tok_per_sec_chip / a100_tok_per_sec
    # economics: public on-demand list prices, USD/chip-hour (us-central):
    # a2-highgpu A100 40GB ~$3.67, v5e ~$1.20, v5p ~$4.20
    chip_price = 4.20 if peak > 400e12 else 1.20
    return {
        "tok_per_sec_chip": tok_per_sec_chip,
        "vs_a100_40mfu": vs_a100,
        # hardware-specific derived metrics are meaningless on the CPU fallback
        "vs_a100_per_dollar": None if cpu_fallback else vs_a100 * 3.67 / chip_price,
        "mfu": None if cpu_fallback else mfu,
        "cpu_fallback": cpu_fallback,
        "n_params": n_params,
        "n_chips": n_chips,
        "device": str(jax.devices()[0]),
        "step_ms": dt / n_steps * 1e3,
    }


def bench_ring_microbench(quick: bool = False):
    """Ring-attention microbench: XLA ppermute ring vs the Pallas RDMA kernel
    on whatever >=2-device mesh exists (VERDICT r3 item 6 — the kernel stays
    gated off `auto` until this records a win on real ICI). On non-TPU meshes
    the Pallas kernel only runs under the interpret machine, whose timing is
    meaningless, so only the XLA ring is timed there."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from maggy_tpu.parallel.ringattention import ring_attention

    devs = jax.devices()
    if len(devs) < 2:
        return None
    n = 4 if len(devs) >= 4 else 2
    mesh = Mesh(np.array(devs[:n]), ("seq",))
    on_tpu = devs[0].platform == "tpu"
    # S>=8k is where sequence parallelism is actually used; CPU meshes get a
    # small geometry purely to prove the path runs end-to-end
    B, S, H, KH, D = (1, 8192, 8, 8, 128) if on_tpu else (1, 512, 4, 4, 32)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    q = jax.random.normal(jax.random.key(1), (B, S, H, D), dtype)
    k = jax.random.normal(jax.random.key(2), (B, S, KH, D), dtype)
    v = jax.random.normal(jax.random.key(3), (B, S, KH, D), dtype)

    def timed(impl):
        fn = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=True, impl=impl)
        )
        with jax.set_mesh(mesh):
            fn(q, k, v).block_until_ready()  # compile
            reps = 3 if quick else 10
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn(q, k, v)
            out.block_until_ready()
        return (time.perf_counter() - t0) / reps * 1e3

    result = {"mesh": n, "seq_len": S, "xla_ms": round(timed("xla"), 2)}
    if on_tpu:
        try:
            result["pallas_ms"] = round(timed("pallas"), 2)
            result["pallas_speedup"] = round(
                result["xla_ms"] / result["pallas_ms"], 3
            )
        except Exception as e:  # noqa: BLE001 - kernel loss is data, not fatal
            result["pallas_error"] = f"{type(e).__name__}: {e}"
    else:
        result["pallas_ms"] = None  # interpret-only off TPU; timing meaningless
    return result


def bench_asha_trials_per_hour(quick: bool = False):
    """Trials/hour through the full control plane (driver+RPC+executors) with a
    near-zero-cost train_fn — measures scheduling overhead, the quantity the
    reference's async design optimizes (BASELINE.json primary metric)."""
    import tempfile

    from maggy_tpu import Searchspace, experiment
    from maggy_tpu.config import HyperparameterOptConfig
    from maggy_tpu.core import env as env_mod
    from maggy_tpu.core.env.base import BaseEnv

    tmp = tempfile.mkdtemp(prefix="maggy_bench_")
    env_mod.set_instance(BaseEnv(tmp))
    try:
        def train(hparams, reporter, budget):
            for step in range(int(budget)):
                reporter.broadcast(hparams["x"], step=step)
            return hparams["x"]

        num_trials = 32 if quick else 64
        cfg = HyperparameterOptConfig(
            num_trials=num_trials,
            optimizer="asha",
            searchspace=Searchspace(
                x=("DOUBLE", [0.0, 1.0]), y=("DOUBLE", [0.0, 1.0])
            ),
            direction="max",
            num_executors=8,
            es_policy="none",
            hb_interval=0.05,
            seed=0,
        )
        t0 = time.perf_counter()
        result = experiment.lagom(train, cfg)
        dt = time.perf_counter() - t0
        total = result["num_trials"]
        return {"asha_trials_per_hour": total / dt * 3600, "asha_wall_s": dt}
    finally:
        env_mod.set_instance(None)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args()

    cpu_fallback = ensure_live_backend()
    train_stats = bench_training_throughput(quick=args.quick, cpu_fallback=cpu_fallback)
    asha_stats = bench_asha_trials_per_hour(quick=args.quick)
    try:
        ring_stats = bench_ring_microbench(quick=args.quick)
    except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
        ring_stats = {"error": f"{type(e).__name__}: {e}"}

    def rnd(v, digits):
        return None if v is None else round(v, digits)

    out = {
        "metric": "tokens_per_sec_per_chip",
        "value": round(train_stats["tok_per_sec_chip"], 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(train_stats["vs_a100_40mfu"], 3),
        "extra": {
            "cpu_fallback": train_stats["cpu_fallback"],
            "mfu": rnd(train_stats["mfu"], 4),
            "vs_a100_per_dollar": rnd(train_stats["vs_a100_per_dollar"], 3),
            "n_params": train_stats["n_params"],
            "n_chips": train_stats["n_chips"],
            "device": train_stats["device"],
            "step_ms": round(train_stats["step_ms"], 2),
            "asha_trials_per_hour": round(asha_stats["asha_trials_per_hour"], 1),
            "asha_wall_s": round(asha_stats["asha_wall_s"], 2),
            "ring_microbench": ring_stats,
        },
    }
    if not train_stats["cpu_fallback"]:
        try:
            with open(SNAPSHOT_PATH, "w") as f:
                json.dump({**out, "snapshot_time": time.time()}, f)
        except OSError:
            pass
    else:
        try:
            with open(SNAPSHOT_PATH) as f:
                out["extra"]["last_real_tpu"] = json.load(f)
        except (OSError, ValueError):
            pass
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
