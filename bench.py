"""Benchmark harness — prints ONE JSON line.

Primary metric: tokens/sec/chip training the flagship LLaMA-style decoder
(fwd+bwd+adamw update, bf16 compute, jit, donated state) on the available
accelerator. ``vs_baseline`` compares against the reference stack's realistic
ceiling on its own hardware: an A100 at 40% MFU running the same model
(BASELINE.md north star is "matching A100 Spark-executor throughput"; the
reference repo publishes no absolute numbers, BASELINE.json published={}).

Secondary fields (inside "extra"): achieved MFU on this chip and an ASHA
trials/hour measurement over the full lagom() control plane with a fast
synthetic train_fn (the reference's own primary metric, BASELINE.json).

Usage: python bench.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Every real-hardware run persists its numbers here; when the accelerator
# tunnel is wedged at round end (it dies if any client is killed mid-compile)
# the CPU-fallback record still carries the round's real measurement under
# extra.last_real_tpu — labeled as such, never substituted for the headline.
SNAPSHOT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_TPU_SNAPSHOT.json")


def ensure_live_backend(probe_timeout: float = 120.0) -> bool:
    """The TPU tunnel can wedge so that jax.devices() hangs forever; probe it
    in a subprocess first and fall back to CPU so the bench always completes
    and reports what it ran on. Returns True when the fallback engaged.
    An explicit JAX_PLATFORMS=cpu request pins through force_cpu (the tunnel
    plugin can hang even env-pinned processes at backend init) and counts as
    the CPU fallback — the full accelerator geometry makes no sense there."""
    from maggy_tpu.util import backend_alive, force_cpu, pin_cpu_if_requested

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        pin_cpu_if_requested()
        return True
    if backend_alive(probe_timeout):
        return False
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    force_cpu()
    print(
        "WARNING: accelerator backend unreachable; benchmarking on a CPU "
        "fallback mesh with a reduced geometry",
        file=sys.stderr,
    )
    return True


TUNED_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "tuned_bench.json")

# one table drives both applying tools/tuned_bench.json and recording the
# in-effect provenance — add new tunables here only
TUNED_KNOBS = (
    ("MAGGY_TPU_BENCH_BS", "batch_size"),
    ("MAGGY_TPU_FLASH_BWD_Q", "bwd_block_q"),
    ("MAGGY_TPU_FLASH_BWD_K", "bwd_block_k"),
)


def apply_tuned_config() -> dict:
    """Fold in hardware-measured tuning from the watchdog playbook
    (tools/tpu_playbook.py writes tools/tuned_bench.json after sweeping
    batch size and flash backward tiles on live silicon). Explicit env vars
    win over the file so a human sweep is never silently overridden. Returns
    the full in-effect provenance (file-applied AND env-provided), for the
    bench record."""
    try:
        with open(TUNED_PATH) as f:
            tuned = json.load(f)
    except (OSError, ValueError):
        tuned = {}
    for env, key in TUNED_KNOBS:
        if key in tuned and not os.environ.get(env):
            os.environ[env] = str(int(tuned[key]))
    return {
        key: int(os.environ[env])
        for env, key in TUNED_KNOBS
        if os.environ.get(env, "").isdigit()
    }


def _bench_bs() -> int:
    try:
        return max(1, int(os.environ.get("MAGGY_TPU_BENCH_BS", "")))
    except ValueError:
        return 16


def count_params(tree) -> int:
    import flax.linen as nn
    import jax

    total = 0
    for leaf in jax.tree.leaves(
        tree, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    ):
        val = leaf.value if isinstance(leaf, nn.Partitioned) else leaf
        total += val.size
    return total


def bench_geometry(cpu_fallback: bool, quick: bool = False):
    """The flagship bench configuration: (DecoderConfig, global batch,
    seq_len, mesh kind). Shared with tools/profile_step.py so the profiler
    trace always matches the model/sharding/batch the record was set on."""
    import jax

    from maggy_tpu.models import DecoderConfig

    n_chips = len(jax.devices())
    mesh_kind = "fsdp" if n_chips > 1 else "dp"
    if cpu_fallback:
        # accelerator unreachable: record *something* comparable round-over-round
        return DecoderConfig.tiny(), 8, 64, mesh_kind
    # ~260M-param geometry: saturates one v5e chip's MXU without blowing
    # HBM; scales to more chips via fsdp automatically. remat_policy="dots"
    # keeps matmul outputs and recomputes only elementwise work — measured
    # fastest (BENCH_NOTES round 2: dots 58.5k vs nothing 42.6k tok/s at
    # bs=8). head_dim=128 (8 heads) is the MXU-native layout (Llama-3
    # itself uses head_dim 128), which lets auto_attention route to the
    # Pallas flash kernel with its auto-tuned 512-row tiles — measured
    # fastest at every S once the tiles are right (66.9k vs dense 60.7k
    # tok/s at S=1024; the old 128x128 tiles LOST to dense, BENCH_NOTES).
    # bs=16/chip was the best of {8, 16, 32} in round 2 (overridable via
    # MAGGY_TPU_BENCH_BS / tools/tuned_bench.json for the playbook sweep).
    cfg = DecoderConfig(
        vocab_size=32_000,
        d_model=1024,
        n_layers=8 if quick else 12,
        n_heads=8,
        n_kv_heads=8,
        d_ff=4096,
        max_seq_len=1024,
        remat=True,
    )
    return cfg, _bench_bs() * max(1, n_chips), 1024, mesh_kind


def bench_setup(cpu_fallback: bool, quick: bool = False):
    """Build the compiled flagship train step exactly as the record measures
    it: (trainer, warmed state, sharded batch, cfg, batch_size, seq_len).
    Shared with tools/profile_step.py so the profiler trace cannot drift
    from the benched step (sharding, optimizer, data, compile warmup)."""
    import jax
    import optax

    from maggy_tpu.models import Decoder
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.data import synthetic_lm_batches

    cfg, batch_size, seq_len, mesh_kind = bench_geometry(cpu_fallback, quick)
    ctx = TrainContext.create(mesh_kind)
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, batch_size, seq_len, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))

    # warmup (compile) before anyone times; float() forces a device->host
    # transfer as the barrier — block_until_ready alone is not a reliable
    # sync on every PJRT transport
    batch = trainer.shard_batch(next(data))
    state, m = trainer.step(state, batch)
    float(m["loss"])
    return trainer, state, batch, cfg, batch_size, seq_len


def measure_telemetry_overhead(trainer, state, batch, n_steps: int):
    """A/B the per-step telemetry cost on the already-compiled step: the same
    loop instrumented exactly the way ``Trainer.fit`` instruments it (one
    span + one gauge per step), with the live recorder vs the null recorder.
    Tracks the <1% overhead budget (ISSUE 1) precisely across rounds; the
    loose CI assertion lives in tests/test_telemetry.py. Returns the final
    state too so the caller's donated-state chain stays intact."""
    from maggy_tpu.telemetry.recorder import NullTelemetry, Telemetry

    def timed(tel):
        nonlocal state
        t0 = time.perf_counter()
        for i in range(n_steps):
            s0 = time.perf_counter()
            with tel.span("train_step", step=i):
                state, m = trainer.step(state, batch)
            tel.gauge("step_time_ms", (time.perf_counter() - s0) * 1e3)
        float(m["loss"])
        return (time.perf_counter() - t0) / n_steps * 1e3

    off = timed(NullTelemetry())
    on = timed(Telemetry(worker="bench"))
    return state, {
        "step_ms_on": round(on, 3),
        "step_ms_off": round(off, 3),
        "overhead_pct": round((on - off) / off * 100, 3) if off else None,
    }


def bench_training_throughput(quick: bool = False, cpu_fallback: bool = False):
    import jax

    n_chips = len(jax.devices())
    n_steps = 5 if (quick or cpu_fallback) else 20
    trainer, state, batch, cfg, batch_size, seq_len = bench_setup(
        cpu_fallback, quick
    )
    n_params = count_params(state.params)

    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, m = trainer.step(state, batch)
    float(m["loss"])
    dt = time.perf_counter() - t0

    state, telemetry_overhead = measure_telemetry_overhead(
        trainer, state, batch, n_steps
    )

    tokens = n_steps * batch_size * seq_len
    tok_per_sec = tokens / dt
    tok_per_sec_chip = tok_per_sec / n_chips

    flops_per_token = 6 * n_params  # fwd+bwd matmul estimate
    achieved_flops = tok_per_sec_chip * flops_per_token
    # chip peak (bf16): v5e 197 TFLOPs, v5p 459; detect loosely, default v5e
    kind = str(jax.devices()[0]).lower()
    peak = 459e12 if "v5p" in kind or "p5" in kind else 197e12
    mfu = achieved_flops / peak

    # reference stack ceiling: A100 (312 TFLOPs bf16) at 40% MFU, same model
    a100_tok_per_sec = 312e12 * 0.40 / flops_per_token
    vs_a100 = tok_per_sec_chip / a100_tok_per_sec
    # economics: public on-demand list prices, USD/chip-hour (us-central):
    # a2-highgpu A100 40GB ~$3.67, v5e ~$1.20, v5p ~$4.20
    chip_price = 4.20 if peak > 400e12 else 1.20
    return {
        "tok_per_sec_chip": tok_per_sec_chip,
        "vs_a100_40mfu": vs_a100,
        # hardware-specific derived metrics are meaningless on the CPU fallback
        "vs_a100_per_dollar": None if cpu_fallback else vs_a100 * 3.67 / chip_price,
        "mfu": None if cpu_fallback else mfu,
        "cpu_fallback": cpu_fallback,
        "n_params": n_params,
        "n_chips": n_chips,
        "device": str(jax.devices()[0]),
        "step_ms": dt / n_steps * 1e3,
        "telemetry_overhead": telemetry_overhead,
    }


def bench_ring_microbench(quick: bool = False):
    """Ring-attention microbench: XLA ppermute ring vs the Pallas RDMA kernel
    on whatever >=2-device mesh exists (VERDICT r3 item 6 — the kernel stays
    gated off `auto` until this records a win on real ICI). On non-TPU meshes
    the Pallas kernel only runs under the interpret machine, whose timing is
    meaningless, so only the XLA ring is timed there."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from maggy_tpu.parallel.ringattention import ring_attention
    from maggy_tpu.util import set_mesh

    devs = jax.devices()
    if len(devs) < 2:
        return None
    n = 4 if len(devs) >= 4 else 2
    mesh = Mesh(np.array(devs[:n]), ("seq",))
    on_tpu = devs[0].platform == "tpu"
    # S>=8k is where sequence parallelism is actually used; CPU meshes get a
    # small geometry purely to prove the path runs end-to-end
    B, S, H, KH, D = (1, 8192, 8, 8, 128) if on_tpu else (1, 512, 4, 4, 32)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    q = jax.random.normal(jax.random.key(1), (B, S, H, D), dtype)
    k = jax.random.normal(jax.random.key(2), (B, S, KH, D), dtype)
    v = jax.random.normal(jax.random.key(3), (B, S, KH, D), dtype)

    def timed(impl):
        fn = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=True, impl=impl)
        )
        with set_mesh(mesh):
            fn(q, k, v).block_until_ready()  # compile
            reps = 3 if quick else 10
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn(q, k, v)
            out.block_until_ready()
        return (time.perf_counter() - t0) / reps * 1e3

    result = {"mesh": n, "seq_len": S, "xla_ms": round(timed("xla"), 2)}
    if on_tpu:
        try:
            result["pallas_ms"] = round(timed("pallas"), 2)
            result["pallas_speedup"] = round(
                result["xla_ms"] / result["pallas_ms"], 3
            )
        except Exception as e:  # noqa: BLE001 - kernel loss is data, not fatal
            result["pallas_error"] = f"{type(e).__name__}: {e}"
    else:
        result["pallas_ms"] = None  # interpret-only off TPU; timing meaningless
    return result


def bench_serving(quick: bool = False):
    """Continuous-batching serving engine (maggy_tpu/serve) at a fixed
    offered load: N requests arriving at a fixed rate into B=4 slots on a
    tiny decoder; reports end-to-end token throughput and TTFT p50/p95 —
    the serving-tier quantities the monitor panel renders live."""
    import jax
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.serve import Engine, SamplingParams, Scheduler

    cfg = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    model = Decoder(cfg)
    params = unbox(
        model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    engine = Engine(cfg, params, num_slots=4)
    scheduler = Scheduler(engine)
    scheduler.start()
    n_requests = 8 if quick else 24
    offered_rps = 20.0  # fixed offered load
    max_new = 16
    try:
        t0 = time.perf_counter()
        reqs = []
        for i in range(n_requests):
            reqs.append(
                scheduler.submit(
                    [1 + (i % 40), 2, 3, 4 + (i % 7)],
                    SamplingParams(max_new=max_new),
                )
            )
            time.sleep(1.0 / offered_rps)
        deadline = time.time() + 120
        while time.time() < deadline and any(
            r.state not in ("done", "failed") for r in reqs
        ):
            time.sleep(0.01)
        wall = time.perf_counter() - t0
        stats = scheduler.stats()
    finally:
        scheduler.stop()
    done = sum(r.state == "done" for r in reqs)
    return {
        "n_requests": n_requests,
        "offered_rps": offered_rps,
        "completed": done,
        "wall_s": round(wall, 3),
        "tok_per_sec": round(done * max_new / wall, 1),
        "ttft_ms_p50": round(stats["ttft_ms_p50"], 1) if stats["ttft_ms_p50"] else None,
        "ttft_ms_p95": round(stats["ttft_ms_p95"], 1) if stats["ttft_ms_p95"] else None,
        "decode_compiles": stats["compile_counts"]["decode"],
    }


def bench_paging(quick: bool = False):
    """extra.paging: the paged-KV concurrency-at-fixed-HBM gate
    (docs/serving.md "Paged KV cache").

    Both engines get the SAME simulated KV budget — ``dense_slots`` full
    ``max_seq_len`` rows, i.e. ``dense_slots * S/P`` pages. The dense
    engine can hold ``dense_slots`` requests, full stop; the paged engine
    may open many more slots because a typical request only touches
    ``ceil(tokens/P)`` pages. Gates:

    * admissible concurrency (peak resident requests) must be >= 2x the
      dense slot count — the memory-as-scheduling-resource claim;
    * paged tok/s within 10% of dense at equal offered load — the
      indirection must not tax the decode hot loop;
    * prefix aliasing on a shared-system-prompt workload records
      pages_shared > 0 (the alias-not-copy counter).
    """
    import jax
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.serve import Engine, SamplingParams, Scheduler

    cfg = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    model = Decoder(cfg)
    params = unbox(
        model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    dense_slots = 4
    page_size = 16
    pages_budget = dense_slots * (cfg.max_seq_len // page_size)  # equal HBM
    n_requests = 12 if quick else 24
    max_new = 8
    # short requests (prompt 4 + 8 new = 12 tokens -> 1 page of 16): the
    # typical-length traffic whose headroom paging reclaims
    prompts = [[1 + (i % 40), 2, 3, 4 + (i % 7)] for i in range(n_requests)]

    def run(paged, num_slots, num_pages=None):
        engine = Engine(
            cfg, params, num_slots=num_slots, paged=paged,
            num_pages=(num_pages + 1) if num_pages else None,
        )
        scheduler = Scheduler(engine)
        scheduler.start()
        peak = 0
        try:
            t0 = time.perf_counter()
            reqs = [
                scheduler.submit(p, SamplingParams(max_new=max_new))
                for p in prompts
            ]
            deadline = time.time() + 120
            while time.time() < deadline and any(
                r.state not in ("done", "failed") for r in reqs
            ):
                peak = max(peak, engine.slots.active_count)
                time.sleep(0.002)
            wall = time.perf_counter() - t0
            done = sum(r.state == "done" for r in reqs)
            stats = scheduler.stats()
        finally:
            scheduler.stop()
        return {
            "completed": done,
            "peak_concurrency": peak,
            "tok_per_sec": round(done * max_new / wall, 1),
            "stats": stats,
        }

    dense = run(False, dense_slots)
    # speed leg: identical geometry (same slots, same load) so the only
    # delta is the page-table indirection in the decode hot loop
    paged_same = run(True, dense_slots)
    # concurrency leg: same page budget, 4x the slots — admissions are now
    # bounded by pages, not by row reservations
    paged = run(True, dense_slots * 4, num_pages=pages_budget)

    # prefix aliasing leg: shared system prompt across every request
    sys_prompt = list(range(100, 100 + 2 * page_size + 5))
    engine = Engine(cfg, params, num_slots=8, paged=True)
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        reqs = [
            scheduler.submit(
                sys_prompt + [60 + i], SamplingParams(max_new=4)
            )
            for i in range(6)
        ]
        deadline = time.time() + 60
        while time.time() < deadline and any(
            r.state not in ("done", "failed") for r in reqs
        ):
            time.sleep(0.005)
        alias_stats = scheduler.stats()
    finally:
        scheduler.stop()

    speed_ratio = (
        paged_same["tok_per_sec"] / dense["tok_per_sec"]
        if dense["tok_per_sec"]
        else None
    )
    concurrency_x = paged["peak_concurrency"] / max(1, dense_slots)
    return {
        "dense_slots": dense_slots,
        "page_size": page_size,
        "pages_budget": pages_budget,
        "dense_tok_per_sec": dense["tok_per_sec"],
        "paged_tok_per_sec": paged_same["tok_per_sec"],
        "paged_budget_tok_per_sec": paged["tok_per_sec"],
        "speed_ratio": round(speed_ratio, 3) if speed_ratio else None,
        "dense_peak_concurrency": dense["peak_concurrency"],
        "paged_peak_concurrency": paged["peak_concurrency"],
        "concurrency_x": round(concurrency_x, 2),
        "preemptions": paged["stats"].get("preemptions", 0),
        "prefix_alias_hits": alias_stats.get("prefix_hits", 0),
        "pages_aliased": (alias_stats.get("paging") or {}).get(
            "pages_aliased_total", 0
        ),
        "decode_compiles": paged["stats"]["compile_counts"]["decode"],
        # the gate: >= 2x admissible concurrency at equal simulated HBM,
        # tok/s within 10%, and aliasing actually sharing pages
        "gate_concurrency_2x": concurrency_x >= 2.0,
        "gate_speed_within_10pct": bool(speed_ratio and speed_ratio >= 0.9),
        "gate_alias_shares_pages": (alias_stats.get("paging") or {}).get(
            "pages_aliased_total", 0
        )
        > 0,
    }


def bench_input_pipeline(quick: bool = False):
    """Host-overlap benchmark (ISSUE 5, docs/performance.md): steps/sec
    through ``Trainer.fit`` with a deliberately slow host loader, prefetch
    off vs on. The loader sleeps ~one step time per batch, so the
    synchronous path pays loader+step serially while the DevicePrefetcher
    path should approach max(loader, step) — the acceptance target is
    >= 1.6x. Runs identically on CPU fallback and silicon."""
    import time as _time

    import jax
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.data import synthetic_lm_batches

    # sized so the CPU-mesh step lands in the tens of ms — the acceptance
    # geometry (loader sleep == step time) where overlap can show its full
    # ~2x; with a step much smaller than the sleep the ratio caps early
    cfg = DecoderConfig.tiny(n_layers=4, d_model=128, n_heads=4, d_ff=256)
    ctx = TrainContext.create("dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    batch = trainer.shard_batch(next(data))
    state, m = trainer.step(state, batch)  # compile
    float(m["loss"])
    t0 = _time.perf_counter()
    for _ in range(5):
        state, m = trainer.step(state, batch)
    float(m["loss"])
    step_s = (_time.perf_counter() - t0) / 5
    # sleep ~= step time maximizes the visible overlap win (and matches the
    # ISSUE's 20ms/20ms acceptance geometry on the CPU mesh)
    sleep_s = max(0.02, step_s)

    def slow(src):
        while True:
            _time.sleep(sleep_s)
            yield next(src)

    n = 10 if quick else 20
    state, off = trainer.fit(state, slow(data), num_steps=n, prefetch=0)
    state, on = trainer.fit(state, slow(data), num_steps=n, prefetch=2)
    return {
        "loader_sleep_ms": round(sleep_s * 1e3, 2),
        "step_ms": round(step_s * 1e3, 2),
        "steps_per_sec_sync": round(off["steps_per_sec"], 3),
        "steps_per_sec_prefetch": round(on["steps_per_sec"], 3),
        "speedup": round(on["steps_per_sec"] / off["steps_per_sec"], 3),
    }


def bench_serve_drain(quick: bool = False):
    """Async-decode drain benchmark (ISSUE 5): decode tok/s with the engine
    driven flat out, synchronous per-token host drain vs the async double
    buffer (decode i+1 dispatched before host-reading step i, steady-state
    inputs carried device-resident). Asserts byte-identical greedy streams
    between the two modes; the acceptance target is >= 1.2x."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.serve import Engine, Request, SamplingParams

    cfg = DecoderConfig.tiny(max_seq_len=256, dtype=jnp.float32)
    params = unbox(
        Decoder(cfg).init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    max_new = 60 if quick else 150
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10, 11]]

    def run(async_decode):
        eng = Engine(cfg, params, num_slots=4, async_decode=async_decode)
        streams = {}
        for p in prompts:
            slot, first = eng.admit(
                Request(prompt=p, params=SamplingParams(max_new=max_new + 5))
            )
            streams[slot] = [first]
        out = eng.step()  # warm the decode compile before timing
        for s, t in out.tokens.items():
            streams[s].append(t)
        t0 = _time.perf_counter()
        counted = 0
        while any(len(v) < max_new for v in streams.values()):
            out = eng.step()
            for s, t in out.tokens.items():
                if len(streams[s]) < max_new:
                    streams[s].append(t)
                    counted += 1
        dt = _time.perf_counter() - t0
        for s in list(streams):
            eng.release(s)
        eng.flush()
        return streams, counted / dt

    sync_streams, tps_sync = run(False)
    async_streams, tps_async = run(True)
    return {
        "tok_per_sec_sync": round(tps_sync, 1),
        "tok_per_sec_async": round(tps_async, 1),
        "speedup": round(tps_async / tps_sync, 3),
        "greedy_match": sync_streams == async_streams,
    }


def bench_trace_overhead(quick: bool = False):
    """Request-tracing overhead (ISSUE 7): decode tok/s with the full
    observability stack live — recorder spans/gauges, ambient trace id
    tagged onto every record, drain histograms, flight-ring tee — vs the
    null recorder. Two views: the wall-clock A/B (`overhead_pct_ab`,
    noisy on a shared CPU box) and the deterministic model
    (`overhead_pct` = measured per-step record-set cost / step time) that
    gates the ~2% budget; the CI assertion (tests/test_tracing.py)
    mirrors the latter."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.serve import Engine, Request, SamplingParams
    from maggy_tpu.telemetry import tracing
    from maggy_tpu.telemetry.recorder import NullTelemetry, Telemetry

    cfg = DecoderConfig.tiny(max_seq_len=256, dtype=jnp.float32)
    params = unbox(
        Decoder(cfg).init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    max_new = 60 if quick else 150
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10, 11]]

    engines = {
        mode: Engine(
            cfg,
            params,
            num_slots=4,
            telemetry_recorder=(
                Telemetry(worker="bench-trace") if mode == "on" else NullTelemetry()
            ),
        )
        for mode in ("off", "on")
    }

    def run(mode):
        eng = engines[mode]
        trace = tracing.new_trace_id() if mode == "on" else None
        with tracing.scope(trace):
            streams = {}
            for p in prompts:
                slot, first = eng.admit(
                    Request(prompt=p, params=SamplingParams(max_new=max_new + 5))
                )
                streams[slot] = [first]
            out = eng.step()  # warm the decode dispatch before timing
            for s, t in out.tokens.items():
                streams[s].append(t)
            t0 = _time.perf_counter()
            counted = 0
            while any(len(v) < max_new for v in streams.values()):
                out = eng.step()
                for s, t in out.tokens.items():
                    if len(streams[s]) < max_new:
                        streams[s].append(t)
                        counted += 1
            dt = _time.perf_counter() - t0
            for s in list(streams):
                eng.release(s)
            eng.flush()
        return counted / dt

    # interleaved best-of-N: CPU-box scheduling noise between two single
    # runs easily exceeds the ~2% effect being measured
    reps = 2 if quick else 3
    best = {"off": 0.0, "on": 0.0}
    for _ in range(reps):
        for mode in ("off", "on"):
            best[mode] = max(best[mode], run(mode))
    tps_off, tps_on = best["off"], best["on"]
    overhead_pct = (tps_off - tps_on) / tps_off * 100 if tps_off else None

    # deterministic budget check: the wall-clock A/B above cannot resolve
    # 2% under CPU scheduling jitter (run-to-run step variance is larger
    # than the effect), so the gate is the directly measured per-step
    # record-set cost against the decode step it rides on
    tel = Telemetry(worker="bench-trace-model")
    n = 5000
    with tracing.scope(tracing.new_trace_id()):
        t0 = _time.perf_counter()
        for _ in range(n):
            with tel.span("serve.decode_step", active=4):
                pass
            tel.gauge("serve.drain_ms", 0.1)
            tel.histogram("serve.drain_ms", 0.1)
        recorder_us = (_time.perf_counter() - t0) / n * 1e6
    # tokens/sec -> steps/sec: every step decodes one token per slot (4)
    step_us = 4.0 / tps_on * 1e6 if tps_on else None
    modeled_pct = recorder_us / step_us * 100 if step_us else None
    return {
        "tok_per_sec_tracing_off": round(tps_off, 1),
        "tok_per_sec_tracing_on": round(tps_on, 1),
        "overhead_pct_ab": (
            round(overhead_pct, 2) if overhead_pct is not None else None
        ),
        "recorder_us_per_step": round(recorder_us, 2),
        "overhead_pct": round(modeled_pct, 2) if modeled_pct is not None else None,
        "within_budget": modeled_pct is not None and modeled_pct <= 2.0,
    }


def bench_timeseries(quick: bool = False):
    """extra.timeseries: sampler + alert-evaluator overhead gate (ISSUE 13).

    The worker's metrics tick (``SeriesStore.sample`` over a recorder
    populated like a busy serving process, plus ``AlertEvaluator.evaluate``
    and ``RecompileSentinel.observe``) runs once per ``interval_s`` (1 s)
    regardless of the step rate, so its wall-clock share IS tick cost /
    tick interval — a deterministic model with no A/B noise, same rationale
    as extra.trace_overhead. Budget: <= 2% of step/decode time, i.e. the
    tick must cost <= 20 ms of every second."""
    import time as _time

    from maggy_tpu.telemetry.alerts import AlertEvaluator, RecompileSentinel
    from maggy_tpu.telemetry.recorder import Telemetry
    from maggy_tpu.telemetry.timeseries import SeriesStore

    tel = Telemetry(worker="bench-timeseries")
    # populate like a busy serving worker: ~30 gauges, 10 counters, 4 hists
    for i in range(30):
        tel.gauge(f"serve.g{i}", float(i))
    for i in range(10):
        tel.count(f"serve.c{i}", i)
    for name in ("serve.ttft_ms", "serve.tpot_ms", "serve.e2e_ms", "serve.queue_ms"):
        for ms in (3.0, 8.0, 21.0, 55.0, 144.0):
            tel.histogram(name, ms)

    store = SeriesStore()
    alerts = AlertEvaluator(store, tel, scope="worker")
    sentinel = RecompileSentinel(store, tel, steady=("decode",))
    compile_counts = {"decode": 1, "prefill": 3, "admit": 1}

    n = 200 if quick else 600
    base = 1_000_000.0
    # warm allocation paths (first tick creates every Series object)
    store.sample(tel, base)
    t0 = _time.perf_counter()
    for tick in range(n):
        now = base + 1.0 + tick  # 1 Hz, matching the scheduler's flush cadence
        store.sample(tel, now)
        sentinel.observe(compile_counts, now)
        alerts.evaluate(now)
    tick_us = (_time.perf_counter() - t0) / n * 1e6
    # one tick per interval_s of wall clock -> share of step/decode time
    overhead_pct = tick_us / (store.interval_s * 1e6) * 100
    return {
        "tick_us": round(tick_us, 1),
        "series_tracked": len(store.names()),
        "interval_s": store.interval_s,
        "overhead_pct": round(overhead_pct, 3),
        "within_budget": overhead_pct <= 2.0,
    }


def bench_capacity(quick: bool = False):
    """extra.capacity: capacity-observability overhead gate (ISSUE 16).

    The capacity slice of the metrics tick — MemoryLedger reconcile+export,
    page-heat buckets, fragmentation scan, prefix residency stats — rides
    the same once-per-``interval_s`` cadence as extra.timeseries, so its
    wall-clock share IS tick cost / tick interval: a deterministic model
    with no A/B noise. Budget: <= 2% of every second."""
    import time as _time

    from maggy_tpu.serve.paging.allocator import BlockAllocator
    from maggy_tpu.serve.prefix import PrefixIndex
    from maggy_tpu.telemetry.memtrack import MemoryLedger
    from maggy_tpu.telemetry.recorder import Telemetry
    from maggy_tpu.telemetry.timeseries import SeriesStore

    # a mid-size serving worker: 256-page pool, half resident with mixed
    # heat, a ledger with the standard accounts, a few resident prefixes
    alloc = BlockAllocator(num_pages=256, page_size=16)
    held = [alloc.alloc(4) for _ in range(32)]
    for i, pages in enumerate(held):
        alloc.touch(pages, gen=i * 4)  # spread last-access over generations

    ledger = MemoryLedger()
    ledger.register("params", 512 << 20)
    ledger.register("kv_pages", 256 << 20)
    ledger.register("workspace", 64 << 20)
    ledger.register("prefetch", 32 << 20)

    index = PrefixIndex()
    index.bytes_per_token = 4096
    for slot in range(8):
        index.insert(slot, [slot * 13 + t for t in range(24)], gen=slot)
        index.match([slot * 13 + t for t in range(24)], gen=slot + 64)

    tel = Telemetry(worker="bench-capacity")
    store = SeriesStore()

    n = 200 if quick else 600
    base = 1_000_000.0
    gen = 128
    # warm allocation paths (first tick creates every Series object)
    ledger.tick(store=store, telemetry=tel, now=base)
    t0 = _time.perf_counter()
    for tick in range(n):
        now = base + 1.0 + tick  # 1 Hz, matching the scheduler's flush cadence
        mem = ledger.tick(store=store, telemetry=tel, now=now)
        heat = alloc.heat_buckets(gen + tick)
        frag = alloc.fragmentation()
        res = index.residency_stats(gen=gen + tick)
        tel.gauge("serve.pages_hot", heat["hot"])
        tel.gauge("serve.pages_warm", heat["warm"])
        tel.gauge("serve.pages_cold", heat["cold"])
        tel.gauge("serve.fragmentation", frag["frag_ratio"])
        tel.gauge("serve.prefix_resident_bytes", res["resident_bytes"])
        tel.gauge("serve.prefix_resident_count", res["resident_prefixes"])
    tick_us = (_time.perf_counter() - t0) / n * 1e6
    # one tick per interval_s of wall clock -> share of step/decode time
    overhead_pct = tick_us / (store.interval_s * 1e6) * 100
    return {
        "tick_us": round(tick_us, 1),
        "mem_headroom_pct": round(mem["headroom_pct"], 4),
        "accounts": len(mem.get("accounts", {})),
        "interval_s": store.interval_s,
        "overhead_pct": round(overhead_pct, 3),
        "within_budget": overhead_pct <= 2.0,
    }


def bench_fleet(quick: bool = False):
    """Serving fleet (maggy_tpu/serve/fleet, ISSUE 6): aggregate tok/s and
    TTFT p50/p95 at a FIXED offered load through the router with N=1 vs N=2
    replicas, on a shared-system-prompt workload so prefix-KV reuse fires —
    the prefix-hit ratio is the single-engine win, the N=2/N=1 throughput
    ratio is the scale-out win. CPU-mesh safe (tiny decoder, in-process
    replicas)."""
    import jax
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.serve import ServeClient
    from maggy_tpu.serve.fleet import ReplicaSpec, launch_fleet

    cfg = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    params = unbox(
        Decoder(cfg).init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    # offered load chosen to SATURATE the per-replica slots (tiny-decoder
    # service time ~tens of ms): requests must overlap or there is nothing
    # for prefix reuse to hit and no queue for admission to manage
    n_requests = 8 if quick else 16
    offered_rps = 100.0
    max_new = 32
    system_prompt = [7, 3, 9, 4, 2, 8, 6, 1, 5, 9, 3, 7]  # shared prefix

    def run(n_replicas):
        spec = ReplicaSpec(cfg, params, num_slots=2)
        router = launch_fleet(spec, replicas=n_replicas)
        host, port = router.start(host="127.0.0.1")
        try:
            with ServeClient((host, port), router.secret) as client:
                # warm every replica's compiles before the measured window
                # (round-robin tie-break spreads the warmups across the fleet)
                warm = [
                    client.submit(system_prompt + [99, 98], max_new=2)
                    for _ in range(n_replicas)
                ]
                for r in warm:
                    client.result(r, timeout=180)
                t0 = time.perf_counter()
                rids = []
                for i in range(n_requests):
                    rids.append(
                        client.submit(
                            system_prompt + [10 + i, 11 + (i % 5)],
                            max_new=max_new,
                        )
                    )
                    time.sleep(1.0 / offered_rps)
                snaps = [client.result(r, timeout=180) for r in rids]
                wall = time.perf_counter() - t0
                stats = client.stats()
        finally:
            router.stop()
        done = sum(s["state"] == "done" for s in snaps)
        admits = max(1, stats.get("prefix_hits", 0) + stats.get("prefill_calls", 0))
        return {
            "completed": done,
            "wall_s": round(wall, 3),
            "tok_per_sec": round(done * max_new / wall, 1),
            "ttft_ms_p50": stats.get("ttft_ms_p50"),
            "ttft_ms_p95": stats.get("ttft_ms_p95"),
            "prefix_hit_ratio": round(stats.get("prefix_hits", 0) / admits, 3),
            "prefix_tokens_saved": stats.get("prefix_tokens_saved", 0),
            "requeued": stats["routing"]["requeued"],
        }

    one = run(1)
    two = run(2)
    return {
        "n_requests": n_requests,
        "offered_rps": offered_rps,
        "max_new": max_new,
        "n1": one,
        "n2": two,
        "scaleout_speedup": round(
            two["tok_per_sec"] / max(one["tok_per_sec"], 1e-9), 3
        ),
    }


def bench_qos(quick: bool = False):
    """extra.qos: overload-robustness gate (ISSUE 15). A seeded 2-class
    replay (premium trickle + best-effort flood) is driven through the
    fleet twice: unloaded (premium only, trickle rate) and overloaded
    (flood at ~2x capacity). Reports per-class TTFT p50/p95, shed and
    preemption counts, and the no-cliff bit: premium's overloaded TTFT p95
    must stay within 1.5x its unloaded p95 — QoS admission + priority
    preemption + the brownout ladder are what hold that line while
    best-effort degrades. CPU-safe (tiny decoder, in-process replicas)."""
    import jax
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.serve import ServeClient, TenantMix, TrafficReplay, TrafficSpec
    from maggy_tpu.serve.fleet import ReplicaSpec, RouterConfig, launch_fleet
    from maggy_tpu.serve.loadgen import generate as gen_schedule
    from maggy_tpu.serve.loadgen import summarize
    from maggy_tpu.serve.qos import BEST_EFFORT, PREMIUM, STANDARD

    cfg = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    params = unbox(
        Decoder(cfg).init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    duration_s = 3.0 if quick else 6.0
    premium_mix = TenantMix(
        "acme", qos=PREMIUM, weight=1.0, prompt_len=14, prefix_len=14,
        n_prefixes=3, max_new=6,
    )

    def run(flood: bool):
        router = launch_fleet(
            ReplicaSpec(cfg, params, num_slots=3, paged=True, num_pages=6),
            replicas=2,
            config=RouterConfig(
                slo_ttft_ms=1000.0,
                admission="queue",
                brownout_escalate_s=0.3,
                brownout_recover_s=1.0,
            ),
        )
        host, port = router.start(host="127.0.0.1")
        tenants = (premium_mix,)
        base_rps = 4.0
        if flood:
            tenants = (
                premium_mix,
                TenantMix("bulk", qos=BEST_EFFORT, weight=11.0,
                          prompt_len=14, max_new=16),
            )
            base_rps = 30.0 if quick else 60.0
        spec = TrafficSpec(
            seed=11, duration_s=duration_s, base_rps=base_rps, tenants=tenants
        )
        try:
            with ServeClient((host, port), router.secret) as client:
                # warm every storm shape on both replicas (fresh prefill,
                # resume-prefill bucket, batched decode) so first-use
                # compiles never masquerade as overload latency
                for i in range(4):
                    client.generate(list(range(1 + i, 15 + i)), max_new=2,
                                    qos=STANDARD, timeout=240)
                warm = [
                    client.submit(list(range(2 + i, 26 + i)), max_new=4,
                                  qos=STANDARD)
                    for i in range(8)
                ]
                for rid in warm:
                    client.result(rid, timeout=240)
                deadline = time.time() + 60
                while time.time() < deadline and (
                    router.brownout.level() != 0 or router.alerts.firing()
                ):
                    time.sleep(0.2)
                outcomes = TrafficReplay(
                    client, gen_schedule(spec), result_timeout_s=25.0
                ).run(timeout=120.0)
                stats = client.stats()
            preempted = sum(
                r.server.scheduler.preemptions
                for r in router.replicas
                if r.server is not None
            )
        finally:
            router.stop()
        by_class = summarize(outcomes)
        return by_class, stats, preempted

    unloaded, _, _ = run(flood=False)
    overload, stats, preempted = run(flood=True)
    prem_base = (unloaded.get(PREMIUM) or {}).get("ttft_p95_ms")
    prem_over = (overload.get(PREMIUM) or {}).get("ttft_p95_ms")
    no_cliff = (
        prem_base is not None
        and prem_over is not None
        and prem_over <= 1.5 * prem_base
    )
    return {
        "duration_s": duration_s,
        "premium_ttft_p95_unloaded_ms": prem_base,
        "premium_ttft_p95_overload_ms": prem_over,
        "unloaded": unloaded,
        "overload": overload,
        "shed": stats["routing"]["shed"],
        "preempted": preempted,
        "brownout_peak": max(
            [lvl for _, lvl in stats["brownout"]["history"]], default=0
        ),
        "no_cliff": bool(no_cliff),
    }


def bench_fleetkv(quick: bool = False):
    """extra.fleetkv: fleet-global KV gate (ISSUE 18). The same seeded
    prefix-heavy workload (6 long stems cycling over 2 small replicas, more
    stems than either device pool holds) runs twice: affinity-blind with
    the host tier off, then with prefix-affinity routing + the host-DRAM
    page tier on. The gate: prefill compute (engine prefill_tokens summed
    over replicas) drops >= 2x with affinity+tiering at no worse SLO
    attainment (within 0.05), and a spilled stream swapped back in resumes
    byte-identically. CPU-safe (tiny decoder, in-process replicas)."""
    import jax
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.serve import Engine, Request, SamplingParams, ServeClient
    from maggy_tpu.serve.fleet import ReplicaSpec, RouterConfig, launch_fleet
    from maggy_tpu.serve.qos import STANDARD

    cfg = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    params = unbox(
        Decoder(cfg).init(jax.random.key(3), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    stems = [
        [(7 * i + 3 * j) % 97 + 2 for j in range(24)] for i in range(6)
    ]
    rounds = 3 if quick else 5

    def run(assisted: bool):
        router = launch_fleet(
            ReplicaSpec(
                cfg, params, num_slots=3, paged=True, page_size=16,
                num_pages=12, tier=assisted, tier_host_pages=64,
            ),
            replicas=2,
            config=RouterConfig(
                slo_ttft_ms=2500.0,
                admission="queue",
                affinity_weight_ms=50.0 if assisted else 0.0,
            ),
        )
        host, port = router.start(host="127.0.0.1")

        def prefill_tokens():
            return sum(
                r.server.scheduler.engine.prefill_tokens
                for r in router.replicas
                if r.server is not None
            )

        try:
            with ServeClient((host, port), router.secret) as client:
                # warm every bucket shape on both replicas so first-use
                # compiles never count as prefill-compute or SLO misses
                for i in range(4):
                    client.generate(list(range(1 + i, 29 + i)), max_new=2,
                                    qos=STANDARD, timeout=240)
                # rounds 0-1 are warm rounds for BOTH runs: round 0 seeds
                # residency (full prefills, spills on release), round 1 is
                # the first affinity-routed wave and compiles the
                # suffix-bucket swap-in programs — so first-use compiles
                # never masquerade as prefill compute or SLO misses;
                # measurement (prefill tokens + client-side TTFT
                # attainment) covers rounds 2..N+1 only
                base = None
                done = 0
                ttfts = []
                for rnd_i in range(rounds + 2):
                    rids = [
                        client.submit(stem + [200 + rnd_i, 201, 202, 203],
                                      max_new=4, qos=STANDARD)
                        for stem in stems
                    ]
                    for rid in rids:
                        out = client.result(rid, timeout=120)
                        if rnd_i < 2:
                            continue
                        done += out.get("state") == "done"
                        if out.get("ttft_ms") is not None:
                            ttfts.append(float(out["ttft_ms"]))
                    if rnd_i == 1:
                        base = prefill_tokens()
                    # one metrics tick between rounds so each replica's
                    # residency sample lands in the fleet prefix map
                    # before the next wave routes
                    time.sleep(1.2)
                stats = client.stats()
            spent = prefill_tokens() - base
            fills = sum(
                (r.server.scheduler.engine.tier_stats or {}).get("fills", 0)
                for r in router.replicas
                if r.server is not None
            )
        finally:
            router.stop()
        return {
            "done": done,
            "prefill_tokens": spent,
            "slo_attainment": (
                sum(t <= 2500.0 for t in ttfts) / len(ttfts)
                if ttfts
                else None
            ),
            "ttft_p95_ms": (
                sorted(ttfts)[max(0, int(0.95 * len(ttfts)) - 1)]
                if ttfts
                else None
            ),
            "affinity_hits": stats["routing"].get("affinity_hits", 0),
            "tier_fills": fills,
        }

    blind = run(assisted=False)
    assisted = run(assisted=True)

    # byte-identity subcheck: spill -> swap-in resumes the exact stream a
    # never-preempted engine produces (sampled, seeded — not just greedy)
    prompt = list(range(3, 40))
    sp = SamplingParams(max_new=8, temperature=0.7, seed=5)

    def free_run():
        eng = Engine(cfg, params, num_slots=2, num_pages=24, tier=False)
        r = Request(id="a", prompt=list(prompt), params=sp)
        slot, first = eng.admit(r)
        toks = [first]
        while len(toks) < sp.max_new:
            out = eng.step()
            if slot in out.tokens:
                toks.append(out.tokens[slot])
        return toks

    eng = Engine(cfg, params, num_slots=2, num_pages=24, tier=True)
    r = Request(id="a", prompt=list(prompt), params=sp)
    slot, first = eng.admit(r)
    r.tokens.append(first)
    for _ in range(3):
        out = eng.step()
        if slot in out.tokens:
            r.tokens.append(out.tokens[slot])
    out = eng.flush()
    if slot in out.tokens:
        r.tokens.append(out.tokens[slot])
    eng.spill_stream(slot)
    eng.release(slot)
    slot2, first2 = eng.admit(r)
    toks = list(r.tokens) + [first2]
    while len(toks) < sp.max_new:
        out = eng.step()
        if slot2 in out.tokens:
            toks.append(out.tokens[slot2])
    swap_identical = toks == free_run()

    ratio = blind["prefill_tokens"] / max(assisted["prefill_tokens"], 1)
    att_blind = blind["slo_attainment"]
    att_assisted = assisted["slo_attainment"]
    slo_held = (
        att_blind is None
        or att_assisted is None
        or att_assisted >= att_blind - 0.05
    )
    return {
        "rounds": rounds,
        "blind": blind,
        "assisted": assisted,
        "prefill_compute_ratio": round(ratio, 3),
        "swap_identical": bool(swap_identical),
        "within_budget": bool(ratio >= 2.0 and slo_held and swap_identical),
    }


def bench_autoscale(quick: bool = False):
    """extra.autoscale: capacity-loop gate (ISSUE 19). The canned
    diurnal+burst replay (quiet shoulders, a crest, a correlated burst on
    the crest) is driven through three fleets under the identical offered
    load: static n=1, static n=2, and an autoscaled fleet bounded
    min=1/max=2. Each run scores SLO attainment per replica-hour —
    attainment is the fraction of arrivals that complete within the TTFT
    SLO, replica-hours integrate the live replica count over the run
    (reconstructed from the fleet.scale.* journal for the autoscaled
    fleet). The gate: the autoscaled fleet's score strictly beats the
    best static fleet AND zero requests fail across its scale events —
    elasticity must pay for itself without dropping anything on the
    floor. The per-request service time is pinned by a fleet-wide
    ``replica_slow`` admission floor (a sleep, not compute), so capacity
    is slot arithmetic — the burst saturates exactly one replica and a
    second replica genuinely doubles throughput on any host, single-core
    included. CPU-safe (tiny decoder, in-process replicas)."""
    import jax
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.serve import ServeClient, TrafficReplay
    from maggy_tpu.serve.fleet import (
        AutoscaleConfig,
        ReplicaSpec,
        RouterConfig,
        launch_fleet,
    )
    from maggy_tpu.resilience import chaos as chaos_mod
    from maggy_tpu.serve.loadgen import diurnal_burst_spec
    from maggy_tpu.serve.loadgen import generate as gen_schedule
    from maggy_tpu.serve.qos import STANDARD

    cfg = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    params = unbox(
        Decoder(cfg).init(jax.random.key(5), jnp.zeros((1, 8), jnp.int32))["params"]
    )

    # Pin the per-request service time with the replica_slow chaos seam
    # (a per-admission sleep on every replica — no replica= key, so the
    # rule matches the whole fleet). The sleep holds the admission path
    # but not the CPU, so capacity is slot arithmetic: two replicas are
    # genuinely twice the throughput even on a single-core host, and the
    # same numbers saturate exactly one replica on any machine. Every
    # fleet replays the identical schedule under the identical floor.
    service_floor_ms = 500.0  # >> tiny-model decode, so the floor dominates
    slo_ms = 5.0 * service_floor_ms  # a queue ~5 deep is an SLO miss
    # one replica serves ~1.8/s against the floor. The diurnal crest
    # (base x1.5 = ~1.95/s) saturates one replica on the swell itself,
    # so the sustained-utilization clock scales out before the burst
    # lands on the crest at ~1.8x one replica — well inside two — with
    # the brownout ladder as the backstop trigger. The quiet shoulders
    # are where a static 2-replica fleet burns replica-hours for
    # nothing. The shape was chosen by simulating this exact schedule
    # through a FIFO queue: it keeps the autoscaled fleet's score above
    # both statics across a wide band of detection lag and service-time
    # jitter.
    base_rps = 1.3
    spec = diurnal_burst_spec(
        seed=7,
        duration_s=56.0,
        base_rps=base_rps,
        burst_mult=1.8,
        diurnal_amp=0.5,
        max_new=6,
    )
    schedule = gen_schedule(spec)

    def run(replicas: int, autoscale):
        # fresh fault budget per fleet so every run pays the same floor
        chaos_mod.install(chaos_mod.Chaos.parse(
            f"replica_slow:ms={service_floor_ms},times=1000000"
        ))
        router = launch_fleet(
            ReplicaSpec(cfg, params, num_slots=1, paged=True, num_pages=8),
            replicas=replicas,
            config=RouterConfig(
                slo_ttft_ms=slo_ms,
                admission="queue",
                brownout_escalate_s=0.3,
                brownout_recover_s=1.0,
            ),
            autoscale=autoscale,
        )
        host, port = router.start(host="127.0.0.1")
        try:
            with ServeClient((host, port), router.secret) as client:
                # warm every storm shape on the starting replicas so
                # first-use compiles never masquerade as overload latency
                # (a scale-up's compile happens inside its warm gate)
                # sequential warms only: a parallel storm against the
                # service floor would queue deep enough to trip the
                # brownout ladder — and a pre-replay scale-up — before
                # the clock even starts
                for i in range(4):
                    client.generate(list(range(1 + i, 15 + i)), max_new=2,
                                    qos=STANDARD, timeout=240)
                for i in range(2):
                    client.generate(list(range(2 + i, 14 + i)), max_new=6,
                                    qos=STANDARD, timeout=240)
                deadline = time.time() + 60
                while time.time() < deadline and (
                    router.brownout.level() != 0
                    or router.alerts.firing()
                    or len(router.replicas) != replicas
                    or (
                        router.autoscaler is not None
                        and router.autoscaler.snapshot()["phase"] != "steady"
                    )
                ):
                    time.sleep(0.2)
                t0 = time.time()
                outcomes = TrafficReplay(
                    client, schedule, result_timeout_s=45.0
                ).run(timeout=240.0)
                t1 = time.time()
            snap = (
                router.autoscaler.snapshot()
                if router.autoscaler is not None
                else None
            )
            counters = dict(router.counters)
        finally:
            router.stop()
            chaos_mod.reset()

        # replica-seconds: integrate live replica count over [t0, t1].
        # Static fleets are flat; the autoscaled fleet steps at each
        # admitted (+1) / retired (-1) journal entry.
        steps = []
        if snap is not None:
            for ev in snap["events"]:
                if ev["event"] == "fleet.scale.admitted":
                    steps.append((ev["ts"], +1))
                elif ev["event"] == "fleet.scale.retired":
                    steps.append((ev["ts"], -1))
        n, t, replica_s = replicas, t0, 0.0
        for ts, delta in sorted(steps):
            ts = min(max(ts, t0), t1)
            replica_s += n * (ts - t)
            n, t = n + delta, ts
        replica_s += n * (t1 - t)

        ok = sum(
            o["status"] == "done"
            and (o.get("snapshot") or {}).get("ttft_ms") is not None
            and float(o["snapshot"]["ttft_ms"]) <= slo_ms
            for o in outcomes
        )
        failed = sum(
            o["status"] in ("failed", "submit_error") for o in outcomes
        )
        attainment = ok / max(len(outcomes), 1)
        replica_h = replica_s / 3600.0
        return {
            "attainment": round(attainment, 4),
            "failed": failed,
            "n_arrivals": len(outcomes),
            "replica_s": round(replica_s, 2),
            "score": round(attainment / max(replica_h, 1e-9), 2),
            "scale_events": (
                sum(
                    ev["event"] in ("fleet.scale.up", "fleet.scale.down")
                    for ev in snap["events"]
                )
                if snap is not None
                else 0
            ),
            # backlog shed to the shared queue when capacity came online
            "requeued": counters.get("requeued", 0),
            # full journal (ts/reason included) — the scale story is the
            # point of this bench, so keep it inspectable in the summary
            "events": list(snap["events"]) if snap else [],
        }

    static1 = run(1, autoscale=None)
    static2 = run(2, autoscale=None)
    auto = run(
        1,
        autoscale=AutoscaleConfig(
            min_replicas=1,
            max_replicas=2,
            scale_cooldown_s=5.0,
            target_util=0.75,
            # single-slot replicas quantize util to {0, 0.5, 1}: 0.6 lets
            # a half-busy sample keep the idle clock alive so the quiet
            # tail can actually scale back in
            low_util=0.6,
            escalate_hold_s=0.5,
            # long enough that a comfortable shoulder (and the sequential
            # warmup burst) never sustains it, short enough that the
            # saturated crest fires it before SLO misses even complete
            high_hold_s=5.0,
            # a momentary lull between the crest ramp and the burst must
            # not retire the capacity the crest just paid to warm, but a
            # long hold bleeds replica-seconds on the post-crest shoulder
            low_hold_s=2.5,
            guard_window_s=1.5,
            drain_grace_s=1.0,
            warm_timeout_s=240.0,
            # match the warmed prefill bucket (schedule prompts are
            # 10-12 tokens): a shorter probe would compile a fresh
            # bucket inside the warm gate and stretch every scale-up
            probe_prompt=tuple(range(2, 14)),
        ),
    )
    best_static = max(static1["score"], static2["score"])
    return {
        "service_floor_ms": service_floor_ms,
        "base_rps": base_rps,
        "slo_ttft_ms": round(slo_ms, 1),
        "static1": static1,
        "static2": static2,
        "autoscaled": auto,
        "best_static_score": best_static,
        "gate": bool(auto["score"] > best_static and auto["failed"] == 0),
    }


def bench_autotune(quick: bool = False):
    """Autotune provenance (maggy_tpu/tune): run the static AOT stage over a
    small mesh/batch grid for the tiny decoder and record what the tuner
    decided — cache hit/miss, chosen config, static-prune counts — so
    BENCH_*.json carries the tuning lineage round over round. Static-only
    (measure=False): the measured ASHA stage is exercised by tests/test_tune;
    here a compile-only pass keeps the bench budget flat. Uses the ambient
    experiment root, so the SECOND bench run on the same machine reports
    cache_hit=true with zero compiles."""
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.tune import TuneConfig, tune

    model = Decoder(DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32))
    tune_cfg = TuneConfig(
        presets=("dp", "fsdp"),
        batch_sizes=(16,) if quick else (16, 64),
        seq_len=64,
        measure=False,  # AOT analysis + flops/bytes ranking only
        steps_per_unit=1,
    )
    result = tune(model, tune_cfg)
    best = result.best
    return {
        "cache_hit": result.cache_hit,
        "candidates": result.candidates,
        "pruned_oom": result.pruned_oom,
        "pruned_infeasible": result.pruned_infeasible,
        "compiled": result.compiled,
        "chosen": {
            "mesh_axes": {
                k: v
                for k, v in zip(
                    ("pp", "dp", "fsdp", "ep", "sp", "tp"), best.spec.axis_sizes()
                )
                if v > 1
            },
            "batch_size": best.batch_size,
            "remat_policy": best.remat_policy,
            "source": best.source,
        },
        "cache_key": result.key,
    }


def bench_autopilot(quick: bool = False):
    """Autopilot gate (maggy_tpu/autopilot, ISSUE 8), two parts. (a)
    Controller overhead: the full per-sample cost — window aggregation plus
    the amortized diagnose+plan at each window close — measured directly
    and modeled against the measured train step (the ≤2% budget the CI
    assertion in tests/test_autopilot.py mirrors). (b) The input-bound →
    prefetch-raise scenario: ``Trainer.fit`` against a bursty loader
    (every 4th batch stalls ~3 step times), fixed depth-1 prefetch vs the
    same run with the autopilot attached — the controller must diagnose
    input_bound, raise ``train.prefetch_depth`` behind its guard, and the
    measured steps/sec must improve."""
    import time as _time

    import jax
    import optax

    from maggy_tpu.autopilot import AutopilotConfig, Controller
    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.data import synthetic_lm_batches

    # ---- (b) setup: same overlap-friendly geometry as extra.input_pipeline
    cfg = DecoderConfig.tiny(n_layers=4, d_model=128, n_heads=4, d_ff=256)
    ctx = TrainContext.create("dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    batch = trainer.shard_batch(next(data))
    state, m = trainer.step(state, batch)  # compile
    float(m["loss"])
    t0 = _time.perf_counter()
    for _ in range(5):
        state, m = trainer.step(state, batch)
    float(m["loss"])
    step_s = (_time.perf_counter() - t0) / 5
    burst_s = max(0.02, step_s) * 3.0

    def bursty(src):
        i = 0
        while True:
            if i % 4 == 3:
                _time.sleep(burst_s)  # periodic input stall: bursty loader
            yield next(src)
            i += 1

    # enough steps that the controller's learning phase (a window to
    # diagnose + a window to prove each raise) amortizes into the mean
    n = 28 if quick else 48
    ap_cfg = AutopilotConfig(window=4, cooldown_windows=0)
    state, off = trainer.fit(state, bursty(data), num_steps=n, prefetch=1)
    state, on = trainer.fit(
        state, bursty(data), num_steps=n, prefetch=1, autopilot=ap_cfg
    )

    # ---- (a) controller overhead: direct per-sample cost vs the step
    class _NullTarget:
        scope = "train"
        guard_metric = "steps_per_sec"

        def current(self):
            return {"train.prefetch_depth": 2, "train.metrics_window": 2}

        def apply(self, knob, value):
            return True

        def pending(self):
            return False

        def sample(self):
            return {}

    controller = Controller(
        _NullTarget(), AutopilotConfig(window=16, cooldown_windows=0)
    )
    sample = {
        "step_time_ms": step_s * 1e3,
        "input_wait_ms": 0.1,
        "metrics_drain_ms": 0.05,
        "steps_per_sec": 1.0 / step_s,
    }
    n_obs = 2000 if quick else 5000
    t0 = _time.perf_counter()
    for _ in range(n_obs):
        controller.observe(dict(sample))
    observe_us = (_time.perf_counter() - t0) / n_obs * 1e6
    overhead_pct = observe_us / (step_s * 1e6) * 100
    return {
        "observe_us_per_step": round(observe_us, 2),
        "step_ms": round(step_s * 1e3, 2),
        "overhead_pct": round(overhead_pct, 3),
        "within_budget": overhead_pct <= 2.0,
        "burst_ms": round(burst_s * 1e3, 1),
        "steps_per_sec_fixed": round(off["steps_per_sec"], 3),
        "steps_per_sec_autopilot": round(on["steps_per_sec"], 3),
        "speedup": round(on["steps_per_sec"] / off["steps_per_sec"], 3),
        "improved": on["steps_per_sec"] > off["steps_per_sec"],
    }


def bench_elastic(quick: bool = False):
    """extra.elastic: checkpoint-consistent mesh-reshape recovery time
    (docs/resilience.md "Elastic membership"). Trains the tiny decoder on a
    2-slice simulated mesh with periodic checkpoints, then plays a slice-1
    preemption: rebuild the mesh over the survivor, restore the latest
    complete checkpoint (cross-width reshard), and run the first step at
    the new width. ``reshape_recovery_s`` is that whole wall — mesh build,
    state init, resharding restore, recompile — and the gate holds it under
    ``MAGGY_TPU_ELASTIC_BUDGET_S`` (default 60s; the CPU-mesh compile
    dominates). Also reports the post-recovery loss delta vs an
    uninterrupted run as a checkpoint-consistency check."""
    import tempfile

    import jax
    import numpy as np
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train.checkpoint import Checkpointer
    from maggy_tpu.train.data import synthetic_lm_batches
    from maggy_tpu.train.trainer import TrainContext

    budget_s = float(os.environ.get("MAGGY_TPU_ELASTIC_BUDGET_S", "60"))
    n_devices = len(jax.devices())
    if n_devices < 2 or n_devices % 2:
        # a 2-slice mesh needs an even device count >= 2; an env-pinned
        # JAX_PLATFORMS=cpu run sees the host's single CPU device (only the
        # backend-probe fallback path forces the 8-device mesh)
        return {
            "skipped": f"needs an even device count >= 2 for the 2-slice "
            f"mesh (have {n_devices})"
        }
    cfg = DecoderConfig.tiny()
    steps_before, steps_total = 4, 6

    def make(ctx):
        trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
        data = synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=5)
        state = trainer.make_state(
            jax.random.key(0),
            next(synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=5)),
        )
        return trainer, state, data

    # uninterrupted reference at full width (consistency target)
    trainer, state, data = make(TrainContext.create_sliced("fsdp", total_slices=2))
    _, ref = trainer.fit(state, data, num_steps=steps_total, prefetch=0)

    with tempfile.TemporaryDirectory() as td:
        trainer, state, data = make(
            TrainContext.create_sliced("fsdp", total_slices=2)
        )
        ck = Checkpointer(td, async_save=False)
        state, _ = trainer.fit(
            state, data, num_steps=steps_before, checkpointer=ck,
            checkpoint_every=2, prefetch=0,
        )
        # slice 1 preempted here: everything from mesh rebuild to the first
        # completed step at the new width is recovery
        t0 = time.perf_counter()
        trainer2, state2, data2 = make(
            TrainContext.create_sliced("fsdp", total_slices=2, active=(0,))
        )
        state2, out = trainer2.fit(
            state2, data2, num_steps=steps_total, checkpointer=ck,
            resume="auto", prefetch=0,
        )
        recovery_s = time.perf_counter() - t0
        ck.close()

    loss_delta = abs(out["loss"] - ref["loss"]) / max(abs(ref["loss"]), 1e-9)
    return {
        "reshape_recovery_s": round(recovery_s, 2),
        "budget_s": budget_s,
        "recovery_ok": recovery_s <= budget_s,
        "loss_rel_delta_vs_uninterrupted": round(loss_delta, 6),
        "consistency_ok": loss_delta < 1e-2,
        "slices": {"before": 2, "after": 1},
    }


def bench_overlap(quick: bool = False):
    """extra.overlap: device-side comm/compute overlap A/B
    (docs/distributed.md "Gradient overlap & ZeRO") on a 2-axis
    slice x data mesh — the outer ``slice`` axis stands in for DCN, the
    inner ``data`` axis for ICI. Times four step variants on the tiny
    decoder: ``dense`` (unbucketed GSPMD reduction), ``bucketed``
    (parallel/overlap.py step), ``nocomm`` (bucketed step with every
    reduction stripped — pure compute), and per-axis probes (reduction
    over one axis only). From those: total comm = dense - nocomm,
    exposed = bucketed - nocomm, overlapped = total - exposed, plus
    per-axis exposure gauges. Gates: the bucketed step is no slower than
    dense (within timing noise), and ZeRO-1 shrinks optimizer-state bytes
    per device by ~1/data_width (AOT accounting from the shardings;
    ``memory_analysis`` reported when the backend provides it)."""
    import jax
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel import overlap as ovl
    from maggy_tpu.parallel.spec import AXIS_DATA, AXIS_SLICE
    from maggy_tpu.train.data import synthetic_lm_batches
    from maggy_tpu.train.trainer import TrainContext

    n_devices = len(jax.devices())
    if n_devices < 4 or n_devices % 2:
        return {
            "skipped": f"needs an even device count >= 4 for the "
            f"slice x data mesh (have {n_devices})"
        }
    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create_sliced("dp", total_slices=2)
    model = Decoder(cfg)
    batch = next(synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=11))
    bucket_mb = 0.25  # tiny model: small buckets so several collectives exist

    def variant(trainer, fn):
        state = trainer.make_state(jax.random.key(0), batch)
        return fn, state

    dense = ctx.trainer(model, optax.adamw(3e-3))
    bucketed = ctx.trainer(model, optax.adamw(3e-3), bucket_mb=bucket_mb)
    sharded = dense.shard_batch(batch)
    with ctx.mesh:
        entries = {
            "dense": variant(dense, dense._build_train_step()),
            "bucketed": variant(bucketed, bucketed._build_train_step()),
            "nocomm": variant(bucketed, bucketed.overlap_step_variant(())),
            f"only_{AXIS_DATA}": variant(
                bucketed, bucketed.overlap_step_variant((AXIS_DATA,))
            ),
            f"only_{AXIS_SLICE}": variant(
                bucketed, bucketed.overlap_step_variant((AXIS_SLICE,))
            ),
        }
        times = ovl.measure_step_times(
            entries, sharded, repeats=3 if quick else 6
        )
    comm = ovl.record_overlap_gauges(times, (AXIS_DATA, AXIS_SLICE))

    # ZeRO-1 optimizer-memory check: AOT accounting from shapes+shardings
    zero = ctx.trainer(
        model, optax.adamw(3e-3), zero_stage=1, bucket_mb=bucket_mb
    )
    data_width = dict(ctx.mesh.shape)[AXIS_DATA]

    def opt_bytes(trainer):
        shardings = trainer.state_shardings_for(batch)
        abstract = jax.eval_shape(
            trainer._init_fn(), jax.random.key(0), batch["tokens"]
        )
        return ovl.opt_state_bytes_per_device(abstract, shardings)

    dense_opt = opt_bytes(dense)
    zero_opt = opt_bytes(zero)
    # compiled-program peak, when the backend exposes it (TPU; CPU returns
    # no per-device stats) — the shardings-based accounting is the gate
    aot_peak = None
    try:
        state = zero.make_state(jax.random.key(0), batch)
        with ctx.mesh:
            step = zero._build_overlap_train_step(
                *zero._overlap_mode(), donate=False
            )
            compiled = step.lower(state, sharded).compile()
        mem = compiled.memory_analysis()
        if mem is not None:
            aot_peak = int(getattr(mem, "temp_size_in_bytes", 0)) or None
    except Exception:  # noqa: BLE001 - CPU backends lack memory_analysis
        aot_peak = None

    ratio = zero_opt / max(dense_opt, 1)
    return {
        "mesh": {"slice": 2, "data": data_width},
        "bucket_mb": bucket_mb,
        "step_ms": {k: round(v, 3) for k, v in times.items()},
        "comm_total_ms": round(comm["comm_total_ms"], 3),
        "comm_exposed_ms": round(comm["comm_exposed_ms"], 3),
        "comm_overlapped_ms": round(comm["comm_overlapped_ms"], 3),
        "comm_exposed_ms_data": round(
            comm.get("comm_exposed_ms_data", 0.0), 3
        ),
        "comm_exposed_ms_slice": round(
            comm.get("comm_exposed_ms_slice", 0.0), 3
        ),
        "gate_bucketed_no_worse": times["bucketed"]
        <= times["dense"] * 1.10,
        "gate_overlap_occurring": comm["comm_exposed_ms"]
        < comm["comm_total_ms"],
        "opt_bytes_per_device": {"dense": dense_opt, "zero1": zero_opt},
        "opt_bytes_ratio": round(ratio, 4),
        "gate_zero1_shrinks_opt": ratio <= 1.0 / data_width + 0.10,
        "aot_temp_bytes_zero1": aot_peak,
    }


def bench_asha_trials_per_hour(quick: bool = False):
    """Trials/hour through the full control plane (driver+RPC+executors) with a
    near-zero-cost train_fn — measures scheduling overhead, the quantity the
    reference's async design optimizes (BASELINE.json primary metric)."""
    import tempfile

    from maggy_tpu import Searchspace, experiment
    from maggy_tpu.config import HyperparameterOptConfig
    from maggy_tpu.core import env as env_mod
    from maggy_tpu.core.env.base import BaseEnv

    tmp = tempfile.mkdtemp(prefix="maggy_bench_")
    env_mod.set_instance(BaseEnv(tmp))
    try:
        def train(hparams, reporter, budget):
            for step in range(int(budget)):
                reporter.broadcast(hparams["x"], step=step)
            return hparams["x"]

        num_trials = 32 if quick else 64
        cfg = HyperparameterOptConfig(
            num_trials=num_trials,
            optimizer="asha",
            searchspace=Searchspace(
                x=("DOUBLE", [0.0, 1.0]), y=("DOUBLE", [0.0, 1.0])
            ),
            direction="max",
            num_executors=8,
            es_policy="none",
            hb_interval=0.05,
            seed=0,
        )
        t0 = time.perf_counter()
        result = experiment.lagom(train, cfg)
        dt = time.perf_counter() - t0
        total = result["num_trials"]
        return {"asha_trials_per_hour": total / dt * 3600, "asha_wall_s": dt}
    finally:
        env_mod.set_instance(None)


def write_run_summary(out) -> str:
    """Persist one compact BENCH_<n>.json per run: headline tok/s, serving
    TTFT p50/p95, training steps/sec, and every gate bit the extras carry.
    n is the next free integer — driver-written BENCH_r01.json-style records
    use a letter prefix and are never scanned or clobbered."""
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    taken = [
        int(m.group(1))
        for f in os.listdir(here)
        if (m := re.fullmatch(r"BENCH_(\d+)\.json", f))
    ]
    n = max(taken, default=0) + 1
    extra = out.get("extra", {})

    def _get(block, key):
        v = extra.get(block)
        return v.get(key) if isinstance(v, dict) else None

    step_ms = extra.get("step_ms")
    gates = {}
    for block, key in (
        ("trace_overhead", "within_budget"),
        ("timeseries", "within_budget"),
        ("capacity", "within_budget"),
        ("paging", "within_budget"),
        ("overlap", "within_budget"),
        ("qos", "no_cliff"),
        ("fleetkv", "within_budget"),
        ("autoscale", "gate"),
    ):
        bit = _get(block, key)
        if bit is not None:
            gates[block] = bool(bit)
    summary = {
        "n": n,
        "time": time.time(),
        "tok_per_sec_per_chip": out.get("value"),
        "serve_tok_per_sec": _get("serving", "tok_per_sec"),
        "ttft_ms_p50": _get("serving", "ttft_ms_p50"),
        "ttft_ms_p95": _get("serving", "ttft_ms_p95"),
        "steps_per_sec": round(1000.0 / step_ms, 3) if step_ms else None,
        "mem_headroom_pct": _get("capacity", "mem_headroom_pct"),
        "gates": gates,
        "cpu_fallback": extra.get("cpu_fallback"),
    }
    path = os.path.join(here, f"BENCH_{n}.json")
    with open(path, "w") as f:
        json.dump(summary, f, indent=2)
    return path


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument(
        "--train-only", action="store_true",
        help="skip the ASHA control-plane and ring microbenches (used by the "
             "playbook's batch-size sweep to conserve tunnel-alive minutes)",
    )
    args = parser.parse_args()

    cpu_fallback = ensure_live_backend()
    tuned = apply_tuned_config()
    train_stats = bench_training_throughput(quick=args.quick, cpu_fallback=cpu_fallback)
    if args.train_only:
        asha_stats = {"asha_trials_per_hour": None, "asha_wall_s": None}
        ring_stats = None
        serving_stats = None
        autotune_stats = None
        input_pipeline_stats = None
        serve_drain_stats = None
        fleet_stats = None
        qos_stats = None
        fleetkv_stats = None
        autoscale_stats = None
        trace_overhead_stats = None
        autopilot_stats = None
        elastic_stats = None
        paging_stats = None
        overlap_stats = None
        timeseries_stats = None
        capacity_stats = None
    else:
        asha_stats = bench_asha_trials_per_hour(quick=args.quick)
        try:
            ring_stats = bench_ring_microbench(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            ring_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            serving_stats = bench_serving(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            serving_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            autotune_stats = bench_autotune(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            autotune_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            input_pipeline_stats = bench_input_pipeline(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            input_pipeline_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            serve_drain_stats = bench_serve_drain(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            serve_drain_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            fleet_stats = bench_fleet(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            fleet_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            qos_stats = bench_qos(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            qos_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            fleetkv_stats = bench_fleetkv(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            fleetkv_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            autoscale_stats = bench_autoscale(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            autoscale_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            trace_overhead_stats = bench_trace_overhead(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            trace_overhead_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            autopilot_stats = bench_autopilot(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            autopilot_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            elastic_stats = bench_elastic(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            elastic_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            paging_stats = bench_paging(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            paging_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            overlap_stats = bench_overlap(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            overlap_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            timeseries_stats = bench_timeseries(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            timeseries_stats = {"error": f"{type(e).__name__}: {e}"}
        try:
            capacity_stats = bench_capacity(quick=args.quick)
        except Exception as e:  # noqa: BLE001 - secondary metric must not sink the bench
            capacity_stats = {"error": f"{type(e).__name__}: {e}"}

    def rnd(v, digits):
        return None if v is None else round(v, digits)

    out = {
        "metric": "tokens_per_sec_per_chip",
        "value": round(train_stats["tok_per_sec_chip"], 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(train_stats["vs_a100_40mfu"], 3),
        "extra": {
            "cpu_fallback": train_stats["cpu_fallback"],
            "mfu": rnd(train_stats["mfu"], 4),
            "vs_a100_per_dollar": rnd(train_stats["vs_a100_per_dollar"], 3),
            "n_params": train_stats["n_params"],
            "n_chips": train_stats["n_chips"],
            "device": train_stats["device"],
            "step_ms": round(train_stats["step_ms"], 2),
            "telemetry_overhead": train_stats["telemetry_overhead"],
            "asha_trials_per_hour": rnd(asha_stats["asha_trials_per_hour"], 1),
            "asha_wall_s": rnd(asha_stats["asha_wall_s"], 2),
            "ring_microbench": ring_stats,
            "serving": serving_stats,
            "autotune": autotune_stats,
            "input_pipeline": input_pipeline_stats,
            "serve_drain": serve_drain_stats,
            "fleet": fleet_stats,
            "qos": qos_stats,
            "fleetkv": fleetkv_stats,
            "autoscale": autoscale_stats,
            "trace_overhead": trace_overhead_stats,
            "autopilot": autopilot_stats,
            "elastic": elastic_stats,
            "paging": paging_stats,
            "overlap": overlap_stats,
            "timeseries": timeseries_stats,
            "capacity": capacity_stats,
            "tuned": tuned or None,
        },
    }
    if not train_stats["cpu_fallback"]:
        out["extra"]["batch_size_per_chip"] = _bench_bs()
    if not train_stats["cpu_fallback"] and not args.quick and not args.train_only:
        # keep-best: a sweep run with a worse knob setting must not clobber
        # the best real-silicon record the CPU-fallback path reports from.
        # --quick runs a different (shallower) model whose tok/s are not
        # comparable, and --train-only runs lack the ASHA/ring secondary
        # metrics, so neither ever touches the snapshot (the playbook ends
        # with a full bench at the winning config to land the record).
        try:
            with open(SNAPSHOT_PATH) as f:
                prev_best = json.load(f).get("value", 0.0)
        except (OSError, ValueError):
            prev_best = 0.0
        if out["value"] >= prev_best:
            try:
                with open(SNAPSHOT_PATH, "w") as f:
                    json.dump({**out, "snapshot_time": time.time()}, f)
            except OSError:
                pass
    elif train_stats["cpu_fallback"]:
        # fallback provenance only — real-hardware --quick/--train-only runs
        # must not carry the stale snapshot as if they hadn't run on silicon
        try:
            with open(SNAPSHOT_PATH) as f:
                out["extra"]["last_real_tpu"] = json.load(f)
        except (OSError, ValueError):
            pass
    try:
        write_run_summary(out)
    except OSError:
        pass
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
