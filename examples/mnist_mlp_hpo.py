"""BASELINE config 1: MNIST-style MLP HPO with lagom() (reference README parity).

Runs anywhere (CPU/TPU). Uses synthetic MNIST-shaped data so the example is
hermetic; swap in real MNIST arrays to reproduce the baseline.

    python examples/mnist_mlp_hpo.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import jax.numpy as jnp
import numpy as np
import optax

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig
from maggy_tpu.models import MLP
from maggy_tpu.train.native_loader import NativeBatchLoader


def make_data(n=2048, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 28, 28)).astype(np.float32)
    w = rng.normal(size=(28 * 28, 10)).astype(np.float32)
    y = (x.reshape(n, -1) @ w).argmax(-1).astype(np.int32)
    return {"inputs": x, "labels": y}


DATA = make_data()


def train(hparams, reporter):
    model = MLP(features=(hparams["width"],) * hparams["depth"], num_classes=10)
    loader = NativeBatchLoader(DATA, batch_size=128, seed=0)
    variables = model.init(jax.random.key(0), DATA["inputs"][:1])
    tx = optax.adam(hparams["lr"])
    opt_state = tx.init(variables["params"])

    @jax.jit
    def step(params, opt_state, batch):
        def loss_fn(p):
            logits = model.apply({"params": p}, batch["inputs"])
            logp = jax.nn.log_softmax(logits)
            return -jnp.take_along_axis(logp, batch["labels"][:, None], 1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state2, loss

    def accuracy(params):
        logits = model.apply({"params": params}, DATA["inputs"])
        return float((jnp.argmax(logits, -1) == DATA["labels"]).mean())

    params = variables["params"]
    for i in range(150):
        params, opt_state, loss = step(params, opt_state, next(loader))
        if i % 25 == 24:
            # broadcast the same quantity the trial returns, so early-stopped
            # trials are comparable with finished ones
            reporter.broadcast(accuracy(params), step=i)
    loader.close()
    return {"metric": accuracy(params), "final_loss": float(loss)}


if __name__ == "__main__":
    sp = Searchspace(
        lr=("DOUBLE", [1e-4, 1e-1]),
        width=("DISCRETE", [64, 128, 256]),
        depth=("INTEGER", [1, 3]),
    )
    config = HyperparameterOptConfig(
        num_trials=8,
        optimizer="randomsearch",
        searchspace=sp,
        direction="max",
        es_policy="median",
        es_min=3,
        hb_interval=0.2,
        seed=0,
    )
    result = experiment.lagom(train, config)
    print("best:", result["best"])
    print("avg accuracy:", round(result["avg"], 4))
