"""Packed serving: one-pass prefill of a multi-document prompt buffer, then
KV-cached continuation of each row's last segment.

Packing is how long-context training keeps the MXU fed; this example shows
the SAME batches serve efficiently too (the reference has no decode path at
all): `prefill()` runs the fully-packed buffer through the `decode=True`
model in a single apply — segment ids are cached alongside K/V, and every
cache read is masked to the query's segment, so the packed contexts stay
isolated exactly as during training — and `generate_cached_packed()`
continues each row's final segment.

    JAX_PLATFORMS=cpu python examples/packed_serving.py
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import jax.numpy as jnp
import numpy as np

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate_cached, generate_cached_packed

if __name__ == "__main__":
    cfg = DecoderConfig.tiny(max_seq_len=64)
    model = Decoder(cfg)
    rng = np.random.default_rng(0)

    # two rows, each packing a 6-token context doc + a 10-token prompt
    B, MAX_NEW = 2, 8
    rows, poss, segs, last_prompts = [], [], [], []
    for _ in range(B):
        ctx_doc = rng.integers(1, cfg.vocab_size, 6).astype(np.int32)
        prompt = rng.integers(1, cfg.vocab_size, 10).astype(np.int32)
        rows.append(np.concatenate([ctx_doc, prompt]))
        poss.append(np.concatenate([np.arange(6), np.arange(10)]))
        segs.append(np.concatenate([np.zeros(6), np.ones(10)]))
        last_prompts.append(prompt)
    packed = jnp.asarray(np.stack(rows).astype(np.int32))
    positions = jnp.asarray(np.stack(poss).astype(np.int32))
    segment_ids = jnp.asarray(np.stack(segs).astype(np.int32))

    variables = model.init(jax.random.key(7), packed)
    decode_model = Decoder(dataclasses.replace(cfg, decode=True))

    logits, new_tokens = generate_cached_packed(
        decode_model, variables["params"], packed, positions, segment_ids,
        max_new=MAX_NEW,
    )
    print(f"prefill logits: {logits.shape}  new tokens: {new_tokens.shape}")

    # proof of segment isolation: decoding each row's prompt ALONE (no packed
    # context doc in the cache at all) yields the same greedy continuation
    for r, prompt in enumerate(last_prompts):
        buf = np.zeros((1, 10 + MAX_NEW), np.int32)
        buf[0, :10] = prompt
        ref = generate_cached(
            decode_model, variables["params"], jnp.asarray(buf),
            jnp.asarray([10], jnp.int32),
        )
        match = bool(
            (np.asarray(new_tokens)[r] == np.asarray(ref)[0, 10:]).all()
        )
        print(f"row {r}: packed continuation == per-sequence decode: {match}")
        assert match
    print("packed serving OK")
