"""Pipeline-parallel training of the LLaMA-style decoder (1F1B schedule).

``ShardingSpec(pp=N)`` is honored directly by the Trainer: layer stages live
on different devices along the ``stage`` mesh axis, activations flow
stage→stage via ppermute, and the one-forward-one-backward schedule keeps
every stage busy after warmup with O(stages) activation memory. The reference
explicitly rejects pipeline engines (core/patching/modules.py:106-109); here
it is one config knob, composable with data AND tensor parallelism — pass
``--tp`` to also shard each stage's attention heads / MLP hidden / vocab
over the ``tensor`` axis (the layout a stage too large for one device
needs; stage attention stays on the flash kernel via a nested
tensor-manual shard_map).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/llama_pipeline.py [--tp]
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import optax

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.train import TrainContext
from maggy_tpu.train.data import synthetic_lm_batches

if __name__ == "__main__":
    n = len(jax.devices())
    if n < 4 or n % 2:
        raise SystemExit(
            f"This example needs an even device count >= 4 (got {n}); run with "
            "JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    use_tp = "--tp" in sys.argv
    if use_tp and n % 4:
        raise SystemExit(f"--tp needs a device count divisible by 4 (got {n})")
    pp = 2
    tp = 2 if use_tp else 1
    dp = n // (pp * tp)
    ctx = TrainContext.create(ShardingSpec(pp=pp, tp=tp, dp=dp))

    # llama-shaped in miniature: 4 layers -> 2 per stage
    cfg = DecoderConfig.tiny(n_layers=4, max_seq_len=64)
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
    n_micro = 2 * pp  # amortizes the 1F1B bubble
    trainer.n_microbatches = n_micro
    batch_size = n_micro * dp  # each microbatch still shards rows over dp

    data = synthetic_lm_batches(cfg.vocab_size, batch_size, 64, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))

    print(f"pipeline: {pp} stages x {tp}-way tensor x {dp}-way data parallel, "
          f"{n_micro} microbatches/step")
    for step in range(20):
        state, metrics = trainer.step(state, trainer.shard_batch(next(data)))
        if (step + 1) % 5 == 0:
            print(f"step {step + 1}: loss={float(metrics['loss']):.4f} "
                  f"grad_norm={float(metrics['grad_norm']):.3f}")
    print("pipeline-parallel training OK")
