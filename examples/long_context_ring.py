"""Long-context training with ring attention (sequence parallelism).

The sequence axis is sharded over the ``seq`` mesh ring; KV blocks rotate via
ppermute so no device ever holds the full [S, S] score matrix. Scale
``seq_len``/mesh to the pod; on CPU run with
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8.

    python examples/long_context_ring.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import optax

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.parallel.ringattention import make_ring_attention
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.train import TrainContext
from maggy_tpu.train.data import synthetic_lm_batches

if __name__ == "__main__":
    n = len(jax.devices())
    if n < 2 or n % 2:
        raise SystemExit(
            f"This example needs an even device count >= 2 (got {n}); run with "
            "JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    sp = max(2, n // 2)
    ctx = TrainContext.create(ShardingSpec(sp=sp, dp=n // sp))
    cfg = DecoderConfig.tiny(
        max_seq_len=32 * sp,
        attention_fn=make_ring_attention(ctx.mesh),
    )
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
    data = synthetic_lm_batches(cfg.vocab_size, batch_size=2 * (n // sp), seq_len=32 * sp)
    state = trainer.make_state(jax.random.key(0), next(data))
    print(f"mesh: sp={sp} dp={n // sp}, seq_len={32 * sp} sharded over the ring")
    for step in range(6):
        state, metrics = trainer.step(state, trainer.shard_batch(next(data)))
        if step % 3 == 2:
            print(f"step {step + 1}: loss {float(metrics['loss']):.4f}")
    print("done — the [S, S] score matrix never existed on any single device")
