"""Continuous-batching serving demo — the ISSUE 2 acceptance run, end to end.

Boots the full serving stack (engine + scheduler + RPC server) on CPU with
B=4 KV-cache slots, drives 8 requests with staggered arrivals through the
socket client, and then PROVES the three acceptance properties:

1. every request's greedy output equals a one-shot ``generate_cached`` over
   the same prompt (continuous batching changes latency, never tokens);
2. the jitted decode step compiled exactly ONCE for the whole run, across
   admissions, evictions, and varying prompt lengths (compile-count
   telemetry);
3. TTFT / queue-depth / tokens-per-sec gauges landed in the exported
   telemetry JSONL, and the monitor's STATUS panel renders them.

    JAX_PLATFORMS=cpu python examples/serving_demo.py
"""

import dataclasses
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import jax.numpy as jnp
import numpy as np

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate_cached
from maggy_tpu.parallel.sharding import unbox
from maggy_tpu.serve import Engine, Scheduler, ServeClient, ServeServer
from maggy_tpu.telemetry import worker_telemetry

if __name__ == "__main__":
    cfg = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    model = Decoder(cfg)
    params = unbox(
        model.init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))["params"]
    )

    exp_dir = tempfile.mkdtemp(prefix="maggy_serve_demo_")
    tel = worker_telemetry("serve", exp_dir, role="serve")
    engine = Engine(cfg, params, num_slots=4, telemetry_recorder=tel)
    server = ServeServer(Scheduler(engine))
    host, port = server.start(host="127.0.0.1")
    print(f"serving on {host}:{port} with B=4 slots")

    prompts = [
        [1, 2, 3, 4], [5, 6, 7], [9, 10, 11, 12, 13], [2, 4, 6, 8, 10, 12],
        [7, 3], [20, 21, 22, 23], [30, 31], [40, 41, 42, 44, 45, 46, 47],
    ]
    MAX_NEW = 6
    results = {}

    def drive(i, prompt, delay):
        time.sleep(delay)  # staggered arrivals: requests churn through slots
        with ServeClient((host, port), server.secret) as client:
            t0 = time.time()
            results[i] = client.generate(prompt, max_new=MAX_NEW, timeout=120)
            print(f"  request {i} (len {len(prompt)}): "
                  f"{results[i]}  [{time.time() - t0:.2f}s]")

    threads = [
        threading.Thread(target=drive, args=(i, p, 0.05 * i))
        for i, p in enumerate(prompts)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # 1. greedy equivalence against one-shot generate_cached
    decode_model = Decoder(dataclasses.replace(cfg, decode=True))
    for i, prompt in enumerate(prompts):
        buf = np.zeros((1, len(prompt) + MAX_NEW), np.int32)
        buf[0, : len(prompt)] = prompt
        ref = np.asarray(
            generate_cached(
                decode_model, params, jnp.asarray(buf),
                jnp.asarray([len(prompt)]),
            )
        )[0, len(prompt):]
        assert results[i] == list(ref), (i, results[i], list(ref))
    print("1. greedy outputs == one-shot generate_cached for all 8 requests")

    # 2. compile-once decode step, via the compile-count telemetry
    with ServeClient((host, port), server.secret) as client:
        stats = client.stats()
        status = client._client._request({"type": "STATUS"})
    assert stats["compile_counts"]["decode"] == 1, stats["compile_counts"]
    print(f"2. decode step compiled exactly once "
          f"(compile_counts={stats['compile_counts']})")

    # 3. telemetry gauges in the JSONL export + monitor panel
    from maggy_tpu.monitor import render_status

    panel = render_status(status)
    server.stop()
    tel.close()
    path = os.path.join(exp_dir, "telemetry", "worker_serve.jsonl")
    with open(path) as f:
        gauges = {
            r["name"]
            for r in map(json.loads, f)
            if r.get("kind") == "gauge"
        }
    need = {"serve.ttft_ms", "serve.queue_depth", "serve.tokens_per_sec"}
    assert need <= gauges, (need - gauges, path)
    print(f"3. gauges {sorted(need)} exported to {path}")
    print("\nmonitor panel:\n" + panel)
    print(f"\nttft p50={stats['ttft_ms_p50']:.0f}ms "
          f"p95={stats['ttft_ms_p95']:.0f}ms  "
          f"tokens_out={stats['tokens_out']}")
    print("serving demo OK")
