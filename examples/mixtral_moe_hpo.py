"""BASELINE config 5 (scaled down): MoE HPO with expert-parallel trial placement.

ASHA searches router/optimizer hyperparameters of a Mixtral-style MoE decoder;
each trial trains expert-parallel over its leased devices. Swap tiny_moe for
MoEConfig.mixtral_8x7b() on a pod.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/mixtral_moe_hpo.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()

import dataclasses

import jax
import optax

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig
from maggy_tpu.models import MoEConfig, MoEDecoder
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.train import TrainContext
from maggy_tpu.train.data import synthetic_lm_batches

BASE = MoEConfig.tiny_moe()


def train(hparams, budget, reporter, devices):
    cfg = dataclasses.replace(
        BASE,
        top_k=hparams["top_k"],
        capacity_factor=hparams["capacity_factor"],
        router_aux_weight=hparams["aux_weight"],
    )
    # expert-parallel mesh over this trial's device lease
    n = max(1, len(devices or []))
    ep = cfg.n_experts if n % cfg.n_experts == 0 else 1
    ctx = TrainContext.create(ShardingSpec(ep=ep, dp=n // ep), devices=devices or None)
    trainer = ctx.trainer(MoEDecoder(cfg), optax.adamw(hparams["lr"]))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    loss = None
    for step in range(int(budget) * 10):
        state, metrics = trainer.step(state, trainer.shard_batch(next(data)))
        if step % 5 == 4:
            loss = float(metrics["loss"])
            reporter.broadcast(-loss, step=step)
    return {"metric": -loss, "loss": loss}


if __name__ == "__main__":
    sp = Searchspace(
        lr=("DOUBLE", [1e-4, 1e-2]),
        top_k=("DISCRETE", [1, 2]),
        capacity_factor=("DOUBLE", [1.0, 2.0]),
        aux_weight=("DOUBLE", [0.0, 0.05]),
    )
    config = HyperparameterOptConfig(
        num_trials=6,
        optimizer="asha",
        searchspace=sp,
        direction="max",
        es_policy="none",
        devices_per_trial=4,
        hb_interval=0.2,
        seed=0,
    )
    result = experiment.lagom(train, config)
    print("best:", result["best"]["params"], "loss:", -result["best"]["metric"])
