"""BASELINE config 4 (scaled down): BERT component ablation study.

LOCO over encoder layers + the pooler: one baseline trial, one trial per
ablated component, ranked by downstream accuracy. ZERO factories (reference
parity, loco.py:82-136): the driver derives each ablated variant from the
config model automatically — BertConfig carries an ``ablated`` field, so the
model is rebuilt with the component dropped; models without one get generic
param-subtree masking.

    python examples/bert_ablation.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import jax.numpy as jnp
import numpy as np

from maggy_tpu import experiment
from maggy_tpu.ablation import AblationStudy
from maggy_tpu.config import AblationConfig
from maggy_tpu.models import Bert, BertConfig

CFG = BertConfig.tiny()
rng = np.random.default_rng(0)
TOKENS = rng.integers(1, CFG.vocab_size, (128, 16)).astype(np.int32)
LABELS = (TOKENS[:, 0] % 2).astype(np.int32)


def train(model, reporter):
    variables = model.init(jax.random.key(0), TOKENS)

    @jax.jit
    def step(params):
        def loss_fn(p):
            logits, _ = model.apply(p, TOKENS)
            logp = jax.nn.log_softmax(logits)
            return -jnp.take_along_axis(logp, LABELS[:, None], 1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return jax.tree.map(lambda a, b: a - 0.3 * b, params, grads), loss

    for i in range(30):
        variables, loss = step(variables)
    logits, _ = model.apply(variables, TOKENS)
    acc = float((jnp.argmax(logits, -1) == LABELS).mean())
    reporter.broadcast(acc, step=0)
    return acc


if __name__ == "__main__":
    study = AblationStudy()
    study.model.layers.include("layer_0", "layer_1", "pooler")
    result = experiment.lagom(
        train,
        AblationConfig(
            ablation_study=study,
            model=Bert(CFG),  # no set_factory: variants derived automatically
            direction="max",
            hb_interval=0.2,
        ),
    )
    print("trials:", result["num_trials"])
    print("best variant:", result["best"]["params"], result["best"]["metric"])
