"""BASELINE config 2 (scaled down): ResNet/CIFAR-style ASHA HPO.

ASHA allocates epochs as budget; swap the synthetic data for CIFAR-10 arrays
and ResNetConfig.resnet50() to reproduce the baseline on a v5e-8.

    python examples/resnet_asha.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import jax.numpy as jnp
import numpy as np
import optax

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig
from maggy_tpu.models import ResNet, ResNetConfig
from maggy_tpu.train.native_loader import NativeBatchLoader


def make_data(n=512, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 16, 16, 3)).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    return {"inputs": x, "labels": y}


DATA = make_data()


def train(hparams, budget, reporter):
    cfg = ResNetConfig(
        stage_sizes=(1, 1),
        width=hparams["width"],
        num_classes=2,
        dtype=jnp.float32,
    )
    model = ResNet(cfg)
    loader = NativeBatchLoader(DATA, batch_size=64, seed=1)
    variables = model.init(jax.random.key(0), DATA["inputs"][:1])
    tx = optax.adam(hparams["lr"])
    opt_state = tx.init(variables["params"])

    @jax.jit
    def step(params, opt_state, batch):
        def loss_fn(p):
            logits = model.apply({"params": p}, batch["inputs"])
            logp = jax.nn.log_softmax(logits)
            return -jnp.take_along_axis(logp, batch["labels"][:, None], 1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state2, loss

    params = variables["params"]
    steps_per_epoch = 8
    for epoch in range(int(budget)):
        for _ in range(steps_per_epoch):
            params, opt_state, loss = step(params, opt_state, next(loader))
        logits = model.apply({"params": params}, DATA["inputs"])
        acc = float((jnp.argmax(logits, -1) == DATA["labels"]).mean())
        reporter.broadcast(acc, step=epoch)
    loader.close()
    return {"metric": acc}


if __name__ == "__main__":
    sp = Searchspace(lr=("DOUBLE", [1e-4, 3e-2]), width=("DISCRETE", [8, 16, 32]))
    config = HyperparameterOptConfig(
        num_trials=8,
        optimizer="asha",
        searchspace=sp,
        direction="max",
        es_policy="none",
        hb_interval=0.2,
        seed=0,
    )
    result = experiment.lagom(train, config)
    print("best:", result["best"]["params"], "acc:", result["best"]["metric"])
    print("total trials (incl. promotions):", result["num_trials"])
