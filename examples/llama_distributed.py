"""BASELINE config 3 (scaled down): LLaMA-style decoder distributed training.

FSDP sharding over every visible device; swap DecoderConfig.tiny() for
DecoderConfig.llama3_8b() on a v5p pod. On CPU, run with
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 to
simulate 8 devices.

    python examples/llama_distributed.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import optax

from maggy_tpu import experiment
from maggy_tpu.config import DistributedConfig
from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.train.data import synthetic_lm_batches

CFG = DecoderConfig.tiny()


def train(model, dataset, hparams, reporter, ctx):
    trainer = ctx.trainer(model, optax.adamw(hparams["lr"]))
    state = trainer.make_state(jax.random.key(0), next(dataset))
    state, metrics = trainer.fit(
        state,
        dataset,
        num_steps=hparams["steps"],
        reporter=reporter,
        report_every=10,
        metric_sign=-1.0,  # metric is -loss (direction="max")
    )
    return {"metric": -metrics["loss"], "loss": metrics["loss"]}


if __name__ == "__main__":
    config = DistributedConfig(
        module=Decoder(CFG),
        dataset=synthetic_lm_batches(CFG.vocab_size, batch_size=8, seq_len=64),
        hparams={"lr": 3e-3, "steps": 60},
        sharding="fsdp",
        hb_interval=0.2,
    )
    result = experiment.lagom(train, config)
    print("final:", result)
