"""Train a decoder straight from a Parquet directory it never fully loads.

The streaming input pipeline (reference petastorm parity, §2.9): token
sequences live in Parquet files as fixed-size-list columns, **row groups**
are the shard unit split round-robin across processes (exactly petastorm's
RANK/WORLD_SIZE semantics, reference dataloader.py:100-144), batches are
assembled by the C++ row-gather on a background thread with a two-level
shuffle, and fed through ``shard_batch(local=True)``. The pre-split ``.npy``
layout (``ShardedDataset``/``write_sharded``) remains for corpora already
converted. Also prints the loader's standalone batch rate vs the training
step time — input is overlapped, so it only needs to be >= the step rate.

    python examples/llama_streaming.py
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import numpy as np
import optax

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.train import ParquetShardedDataset, TrainContext, write_parquet

CFG = DecoderConfig.tiny(max_seq_len=256)
BATCH, SEQ, STEPS = 8, 128, 30


def main():
    work = tempfile.mkdtemp(prefix="maggy_stream_")
    rng = np.random.default_rng(0)
    # a mixture of repeated-token rows: learnable next-token structure
    base = rng.integers(0, CFG.vocab_size, (2048, 1), dtype=np.int32)
    tokens = np.tile(base, (1, SEQ))
    write_parquet(
        os.path.join(work, "lm"), {"tokens": tokens},
        rows_per_group=64, num_files=4,  # 32 row-group shards
    )

    ds = ParquetShardedDataset(os.path.join(work, "lm"))
    ctx = TrainContext.create("dp" if len(jax.devices()) == 1 else "fsdp")
    trainer = ctx.trainer(Decoder(CFG), optax.adamw(1e-2))
    loader = ds.loader(batch_size=BATCH, ctx=ctx)

    state = trainer.make_state(jax.random.key(0), next(loader))
    state, m = trainer.step(state, trainer.shard_batch(next(loader), local=True))
    float(m["loss"])  # compile barrier

    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, m = trainer.step(state, trainer.shard_batch(next(loader), local=True))
    final = float(m["loss"])
    step_ms = (time.perf_counter() - t0) / STEPS * 1e3

    # standalone loader rate (no device work): how fast input CAN flow
    t0 = time.perf_counter()
    for _ in range(STEPS):
        next(loader)
    load_ms = (time.perf_counter() - t0) / STEPS * 1e3
    loader.close()

    print(
        f"final_loss={final:.3f} step={step_ms:.1f}ms "
        f"loader_batch={load_ms:.2f}ms overlap_ok={load_ms <= step_ms}"
    )


if __name__ == "__main__":
    main()
