"""Factory-free model ablation (VERDICT r3 item 3).

The reference ablates layers of *any* user Keras model by JSON surgery —
``model_from_json`` after deleting named layers (reference loco.py:82-136) —
with zero user plumbing. The flax-idiomatic counterpart here is a three-tier
:func:`auto_ablate` the ablation driver applies when the study has no model
factory:

1. the model's config has a ``without()`` method (DecoderConfig and friends):
   rebuild from ``cfg.without(components)`` — forward-pass gating, unchanged
   param tree;
2. the config carries an ``ablated`` field (BertConfig): rebuild with the
   component names merged in — the model drops those submodules itself;
3. any other flax module: :class:`ParamMaskedModel` zeros the parameter
   subtrees whose path matches the component names on every ``apply`` — a
   residual block with a zeroed output projection reduces to the identity,
   and the masked params receive zero gradients, so the component stays
   ablated through training.
"""

from __future__ import annotations

import dataclasses
from typing import Any, FrozenSet, Iterable, Tuple


class ParamMaskedModel:
    """Generic factory-free fallback: delegates to a base flax module but
    zeros matching param subtrees on ``init`` and every ``apply``.

    A component name matches a parameter whose key path contains it as a
    contiguous segment sequence — ``"mlp"`` masks every ``.../mlp/...``
    subtree, ``"encoder.layer_0"`` only that nested one. Raises at mask time
    if a name matches nothing (a typo must not silently train the full
    model)."""

    def __init__(self, base: Any, ablated: Iterable[str]):
        self.base = base
        self.ablated: FrozenSet[str] = frozenset(ablated)
        self._patterns: Tuple[Tuple[str, ...], ...] = tuple(
            tuple(c.split(".")) for c in sorted(self.ablated)
        )

    def _matched_pattern(self, path_names: Tuple[str, ...]):
        """The first ablated pattern occurring as a contiguous segment
        sequence in ``path_names``, or None."""
        for pat in self._patterns:
            k = len(pat)
            if any(
                tuple(path_names[i : i + k]) == pat
                for i in range(len(path_names) - k + 1)
            ):
                return pat
        return None

    def _mask(self, variables):
        import jax
        import jax.numpy as jnp

        hit = set()

        def one(path, leaf):
            names = tuple(getattr(p, "key", getattr(p, "name", str(p))) for p in path)
            pat = self._matched_pattern(names)
            if pat is not None:
                hit.add(pat)
                return jnp.zeros_like(leaf)
            return leaf

        masked = jax.tree_util.tree_map_with_path(one, variables)
        missing = [".".join(p) for p in self._patterns if p not in hit]
        if missing:
            raise ValueError(
                f"Ablated component(s) {missing} match no parameter subtree; "
                "check the names against the model's param tree."
            )
        return masked

    def init(self, *args, **kwargs):
        return self._mask(self.base.init(*args, **kwargs))

    def apply(self, variables, *args, **kwargs):
        return self.base.apply(self._mask(variables), *args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.base, name)

    def __repr__(self):
        return f"ParamMaskedModel({self.base!r}, ablated={sorted(self.ablated)})"


def _rebuild(model: Any, new_cfg: Any) -> Any:
    """Variant of ``model`` with ``cfg`` swapped; flax ``Module.clone``
    preserves every other constructor attribute (a bare
    ``type(model)(cfg)`` would silently reset them)."""
    if hasattr(model, "clone"):
        return model.clone(cfg=new_cfg)
    return type(model)(new_cfg)


def auto_ablate(model: Any, ablated: FrozenSet[str]) -> Any:
    """Build the ablated variant of ``model`` with zero user plumbing."""
    cfg = getattr(model, "cfg", None)
    if cfg is not None and hasattr(cfg, "without"):
        return _rebuild(model, cfg.without(ablated))
    if (
        cfg is not None
        and dataclasses.is_dataclass(cfg)
        and any(f.name == "ablated" for f in dataclasses.fields(cfg))
    ):
        merged = frozenset(getattr(cfg, "ablated", frozenset())) | frozenset(ablated)
        return _rebuild(model, dataclasses.replace(cfg, ablated=merged))
    return ParamMaskedModel(model, ablated)
