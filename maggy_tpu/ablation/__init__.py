from maggy_tpu.ablation.ablationstudy import AblationStudy, Features, ModelSpec

__all__ = ["AblationStudy", "Features", "ModelSpec"]
