"""Declarative ablation-study specification.

Capability parity with the reference ``maggy/ablation/ablationstudy.py:18-408``:
``study.features.include(...)`` marks dataset columns for leave-one-out removal,
``study.model.layers.include(...)`` / ``include_groups(...)`` marks model
components (single names, or groups ablated together), and custom model
generators cover anything declarative names cannot.

Model surgery is flax-idiomatic and **factory-free by default** (matching the
reference's zero-plumbing Keras-JSON surgery, loco.py:82-136): when the study
has no factory the driver derives each variant from the config model via
:func:`maggy_tpu.ablation.masking.auto_ablate` — ``cfg.without(components)``
for config-driven families (Decoder), an ``ablated`` config field rebuild
(Bert), or generic param-subtree zero-masking for any other flax module. A
**model factory** ``fn(ablated: frozenset[str]) -> flax module`` remains the
escape hatch for fully custom surgery.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, FrozenSet, Iterable, List, Optional


class Features:
    """Dataset columns to ablate one at a time (reference ablationstudy.py
    features API)."""

    def __init__(self):
        self.included: List[str] = []

    def include(self, *names: str) -> None:
        for name in _flatten(names):
            if not isinstance(name, str):
                raise ValueError(f"Feature names must be str, got {name!r}")
            if name not in self.included:
                self.included.append(name)

    def exclude(self, *names: str) -> None:
        for name in _flatten(names):
            if name in self.included:
                self.included.remove(name)

    def list_all(self) -> List[str]:
        return list(self.included)


class _Layers:
    """Model components to ablate: single names and groups (ablated together),
    mirroring ``model.layers.include`` / ``include_groups`` (reference
    ablationstudy.py:306-347)."""

    def __init__(self):
        self.included: List[str] = []
        self._groups: List[FrozenSet[str]] = []
        self._prefixes: List[str] = []

    def include(self, *names: str) -> None:
        for name in _flatten(names):
            if not isinstance(name, str):
                raise ValueError(f"Component names must be str, got {name!r}")
            if name not in self.included:
                self.included.append(name)

    def exclude(self, *names: str) -> None:
        for name in _flatten(names):
            if name in self.included:
                self.included.remove(name)

    def include_groups(self, *groups: Iterable[str], prefix: Optional[str] = None) -> None:
        if prefix is not None:
            if groups:
                raise ValueError("Pass either explicit groups or a prefix, not both")
            self._prefixes.append(prefix)
            return
        for group in groups:
            fs = frozenset(group)
            if not fs:
                raise ValueError("Cannot include an empty component group")
            if fs not in self._groups:
                self._groups.append(fs)

    @property
    def included_groups(self) -> List[FrozenSet[str]]:
        """Explicit groups plus prefix groups resolved against the included
        components (reference prefix groups resolve against Keras layer names,
        ablationstudy.py:306-347)."""
        out = list(self._groups)
        for prefix in self._prefixes:
            group = frozenset(c for c in self.included if c.startswith(prefix))
            if not group:
                raise ValueError(
                    f"Prefix group {prefix!r} matches no included components "
                    f"{self.included}; call layers.include(...) first."
                )
            if group not in out:
                out.append(group)
        return out

    def list_all(self) -> List[Any]:
        return list(self.included) + list(self.included_groups)


class ModelSpec:
    def __init__(self):
        self.layers = _Layers()
        self._factory: Optional[Callable[[FrozenSet[str]], Any]] = None
        self.custom_generators: Dict[str, Callable[[], Any]] = {}

    def set_factory(self, fn: Callable[[FrozenSet[str]], Any]) -> None:
        """``fn(ablated_components) -> model`` — called with frozenset() for the
        baseline trial and with each ablation target otherwise."""
        self._factory = fn

    @property
    def factory(self) -> Optional[Callable[[FrozenSet[str]], Any]]:
        return self._factory

    def add_custom_generator(self, name: str, fn: Callable[[], Any]) -> None:
        """A fully custom model variant, one trial per generator (reference
        ablationstudy.py:240-250)."""
        self.custom_generators[name] = fn


class AblationStudy:
    """Spec consumed by the LOCO ablator.

    Example::

        study = AblationStudy()
        study.features.include("age", "income")
        study.model.layers.include("mlp", "attn")
        study.model.set_factory(lambda ablated: Decoder(cfg.without(ablated)))
    """

    def __init__(
        self,
        dataset_generator: Optional[Callable] = None,
        label_name: Optional[str] = None,
    ):
        """:param dataset_generator: optional ``fn(dataset, ablated_feature) ->
            dataset``; the default handles dict-of-arrays datasets by dropping
            the feature key (the TPU-native stand-in for the reference's
            feature-store TFRecord schema editing, loco.py:41-80).
        :param label_name: column never ablated.
        """
        self.features = Features()
        self.model = ModelSpec()
        self.dataset_generator = dataset_generator
        self.label_name = label_name

    def to_dict(self) -> Dict[str, Any]:
        return {
            "features": self.features.list_all(),
            "components": self.model.layers.included,
            "component_groups": [sorted(g) for g in self.model.layers.included_groups],
            "custom_generators": sorted(self.model.custom_generators),
            "label_name": self.label_name,
        }


def _flatten(names):
    for n in names:
        if isinstance(n, (list, tuple, set, frozenset)):
            yield from _flatten(n)
        else:
            yield n


def default_dataset_generator(dataset: Any, ablated_feature: Optional[str]) -> Any:
    """Drop one feature from the dataset, schema-style (the reference edits
    the feature-store TFRecord schema automatically, loco.py:41-80):

    * dict-of-arrays — the key is dropped;
    * ``ShardedDataset`` / ``ParquetShardedDataset`` — rebuilt with a
      column list excluding the feature (no file rewrites: the loader just
      stops reading that field/column);
    * anything else — pass ``AblationStudy(dataset_generator=...)``.
    """
    if ablated_feature is None or dataset is None:
        return dataset
    if isinstance(dataset, dict):
        if ablated_feature not in dataset:
            raise KeyError(
                f"Ablated feature {ablated_feature!r} not in dataset keys "
                f"{sorted(dataset)}"
            )
        return {k: v for k, v in dataset.items() if k != ablated_feature}
    from maggy_tpu.train.sharded_dataset import (
        ParquetShardedDataset,
        ShardedDataset,
    )

    if isinstance(dataset, (ParquetShardedDataset, ShardedDataset)):
        fields = dataset.fields
        if ablated_feature not in fields:
            raise KeyError(
                f"Ablated feature {ablated_feature!r} not in dataset fields "
                f"{sorted(fields)}"
            )
        keep = [f for f in fields if f != ablated_feature]
        if not keep:
            raise ValueError("Cannot ablate the only field of a dataset")
        if isinstance(dataset, ParquetShardedDataset):
            return ParquetShardedDataset(dataset.path, columns=keep)
        return ShardedDataset(dataset.data_dir, columns=keep)
    raise TypeError(
        "Default dataset generator handles dict and (Parquet)ShardedDataset "
        "datasets; pass AblationStudy(dataset_generator=...) for custom types."
    )
