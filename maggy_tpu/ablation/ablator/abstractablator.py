"""Ablator interface (reference ablation/ablator/abstractablator.py:20-86)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional

from maggy_tpu.trial import Trial


class AbstractAblator(ABC):
    def __init__(self, ablation_study, final_store=None):
        self.ablation_study = ablation_study
        self.final_store = final_store if final_store is not None else []

    @abstractmethod
    def get_number_of_trials(self) -> int:
        ...

    @abstractmethod
    def get_trial(self, ablation_trial: Optional[Trial] = None) -> Optional[Trial]:
        """Return the next ablation Trial or None when exhausted."""

    def initialize(self) -> None:
        ...

    def finalize_experiment(self, trials) -> None:
        ...
