from maggy_tpu.ablation.ablator.abstractablator import AbstractAblator
from maggy_tpu.ablation.ablator.loco import LOCO

__all__ = ["AbstractAblator", "LOCO"]
