"""LOCO — Leave One Component Out.

Capability parity with the reference ``maggy/ablation/ablator/loco.py:26-261``:
trial 0 is the full-model baseline, then one trial per included feature, per
model component, per component group, and per custom model generator. Trial
params carry ``ablated_feature`` / ``ablated_component`` markers; the ablation
executor resolves them into concrete (dataset, model) pairs via the study's
generators, so the user's train_fn stays oblivious.
"""

from __future__ import annotations

from typing import List, Optional

from maggy_tpu.ablation.ablator.abstractablator import AbstractAblator
from maggy_tpu.trial import Trial


class LOCO(AbstractAblator):
    def __init__(self, ablation_study, final_store=None):
        super().__init__(ablation_study, final_store)
        self._buffer: List[Trial] = []

    def initialize(self) -> None:
        study = self.ablation_study
        trials = [self._make_trial(None, None)]  # baseline first
        for feature in study.features.list_all():
            trials.append(self._make_trial(feature, None))
        for comp in study.model.layers.included:
            trials.append(self._make_trial(None, comp))
        for group in study.model.layers.included_groups:
            trials.append(self._make_trial(None, "|".join(sorted(group))))
        for name in sorted(study.model.custom_generators):
            trials.append(self._make_trial(None, f"custom:{name}"))
        self._buffer = trials

    def get_number_of_trials(self) -> int:
        study = self.ablation_study
        return (
            1
            + len(study.features.list_all())
            + len(study.model.layers.included)
            + len(study.model.layers.included_groups)
            + len(study.model.custom_generators)
        )

    def get_trial(self, ablation_trial: Optional[Trial] = None) -> Optional[Trial]:
        return self._buffer.pop(0) if self._buffer else None

    @staticmethod
    def _make_trial(feature: Optional[str], component: Optional[str]) -> Trial:
        return Trial(
            {"ablated_feature": feature or "None", "ablated_component": component or "None"},
            trial_type="ablation",
        )
