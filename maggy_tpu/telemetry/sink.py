"""JSONL telemetry sink on the env storage seam.

Records land under ``<exp_dir>/telemetry/worker_<pid>.jsonl`` — identically on
a local disk and on ``gs://`` (via :class:`maggy_tpu.core.env.gcs.GcsEnv`).
Local roots append per flush; remote object stores cannot append, so the sink
buffers the full record history and republishes the whole object each flush
(bounded, same trade the Reporter's remote log makes).
"""

from __future__ import annotations

import json
import posixpath
from typing import Any, Dict, List

# remote (object-store) sinks cap the republished history; oldest records are
# dropped with an explicit truncation marker, mirroring Reporter's remote log
_REMOTE_MAX_RECORDS = 50_000


def telemetry_dir(exp_dir: str) -> str:
    return posixpath.join(str(exp_dir), "telemetry")


class JsonlSink:
    """Append-oriented JSONL writer for one worker's telemetry file."""

    def __init__(self, path: str, env=None):
        self.path = str(path)
        self._env = env
        self._remote = "://" in self.path
        self._history: List[str] = []
        self._truncated = 0
        self._closed = False

    @property
    def env(self):
        if self._env is None:
            from maggy_tpu.core.env import EnvSing

            self._env = EnvSing.get_instance()
        return self._env

    def write(self, records: List[Dict[str, Any]]) -> None:
        if self._closed or not records:
            return
        lines = [
            json.dumps(rec, separators=(",", ":"), default=str) for rec in records
        ]
        try:
            if self._remote:
                self._history.extend(lines)
                if len(self._history) > _REMOTE_MAX_RECORDS:
                    dropped = len(self._history) - _REMOTE_MAX_RECORDS
                    self._history = self._history[dropped:]
                    self._truncated += dropped
                head = (
                    [json.dumps({"kind": "truncated", "dropped": self._truncated})]
                    if self._truncated
                    else []
                )
                self.env.dump("\n".join(head + self._history) + "\n", self.path)
            else:
                with self.env.open_file(self.path, "a") as f:
                    f.write("\n".join(lines) + "\n")
        except Exception:  # noqa: BLE001 - telemetry is best-effort, never fatal
            pass

    def close(self) -> None:
        self._closed = True
        self._history = []


def worker_telemetry(partition_id, exp_dir: str, role: str = "worker", env=None):
    """Build a worker's recorder with its JSONL sink attached — or the shared
    no-op recorder when ``MAGGY_TPU_TELEMETRY=0``, so executors need no flag
    checks of their own."""
    from maggy_tpu.telemetry import recorder

    if not recorder.enabled():
        return recorder.NULL
    tel = recorder.Telemetry(worker=partition_id, role=role)
    name = f"worker_{partition_id}.jsonl" if role != "driver" else "driver.jsonl"
    tel.attach_sink(JsonlSink(posixpath.join(telemetry_dir(exp_dir), name), env=env))
    return tel
