"""JSONL telemetry sink on the env storage seam.

Records land under ``<exp_dir>/telemetry/worker_<pid>.jsonl`` — identically on
a local disk and on ``gs://`` (via :class:`maggy_tpu.core.env.gcs.GcsEnv`).
Local roots append per flush; remote object stores cannot append, so the sink
buffers the full record history and republishes the whole object each flush
(bounded, same trade the Reporter's remote log makes).

**Rotation (local roots):** a multi-day serve fleet would grow one unbounded
file, so when a worker file passes ``max_bytes``
(``MAGGY_TPU_TELEMETRY_MAX_BYTES``, default 64 MiB) it is rotated shift-style
— ``worker_0.jsonl`` → ``worker_0.jsonl.1`` → ``.2`` … up to
``max_segments``, oldest dropped. The exporters
(:func:`maggy_tpu.telemetry.export.load_records`) and
``tools/analyze_trace.py`` read rotated segments oldest-first, so rotation is
invisible to every consumer.
"""

from __future__ import annotations

import json
import os
import posixpath
import threading
from typing import Any, Dict, List, Optional

# remote (object-store) sinks cap the republished history; oldest records are
# dropped with an explicit truncation marker, mirroring Reporter's remote log
_REMOTE_MAX_RECORDS = 50_000

ENV_MAX_BYTES = "MAGGY_TPU_TELEMETRY_MAX_BYTES"
DEFAULT_MAX_BYTES = 64 << 20  # per segment, before rotation
DEFAULT_MAX_SEGMENTS = 4  # rotated segments kept beside the live file


def telemetry_dir(exp_dir: str) -> str:
    return posixpath.join(str(exp_dir), "telemetry")


def default_max_bytes() -> int:
    try:
        return int(os.environ[ENV_MAX_BYTES])
    except (KeyError, ValueError):
        return DEFAULT_MAX_BYTES


class JsonlSink:
    """Append-oriented JSONL writer for one worker's telemetry file."""

    def __init__(
        self,
        path: str,
        env=None,
        max_bytes: Optional[int] = None,
        max_segments: int = DEFAULT_MAX_SEGMENTS,
    ):
        self.path = str(path)
        self._env = env
        self._remote = "://" in self.path
        self.max_bytes = default_max_bytes() if max_bytes is None else int(max_bytes)
        self.max_segments = max(1, int(max_segments))
        self._history: List[str] = []
        self._truncated = 0
        self._closed = False
        self._size: Optional[int] = None  # lazy: current segment's byte size
        # a sink may be shared by recorders flushing from different threads;
        # the rotate-then-append sequence must be atomic or a rotation racing
        # a write drops/interleaves records
        self._lock = threading.Lock()

    @property
    def env(self):
        if self._env is None:
            from maggy_tpu.core.env import EnvSing

            self._env = EnvSing.get_instance()
        return self._env

    def _rotate(self) -> None:  # guarded-by: _lock
        """Shift-rotate the live file: ``.jsonl`` -> ``.jsonl.1`` -> … up to
        ``max_segments`` (oldest removed). Local filesystem only — the
        remote path bounds history by republishing instead."""
        oldest = f"{self.path}.{self.max_segments}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.max_segments - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        if os.path.exists(self.path):
            os.replace(self.path, f"{self.path}.1")
        self._size = 0

    def write(self, records: List[Dict[str, Any]]) -> None:  # thread-entry — recorders flush from heartbeat/flusher threads
        if self._closed or not records:
            return
        lines = [
            json.dumps(rec, separators=(",", ":"), default=str) for rec in records
        ]
        try:
            if self._remote:
                with self._lock:
                    self._history.extend(lines)
                    if len(self._history) > _REMOTE_MAX_RECORDS:
                        dropped = len(self._history) - _REMOTE_MAX_RECORDS
                        self._history = self._history[dropped:]
                        self._truncated += dropped
                    head = (
                        [json.dumps({"kind": "truncated", "dropped": self._truncated})]
                        if self._truncated
                        else []
                    )
                    body = "\n".join(head + self._history) + "\n"
                self.env.dump(body, self.path)
            else:
                data = "\n".join(lines) + "\n"
                with self._lock:
                    if self._size is None:  # first write: adopt an existing file
                        try:
                            self._size = os.path.getsize(self.path)
                        except OSError:
                            self._size = 0
                    if self._size and self._size + len(data) > self.max_bytes:
                        self._rotate()
                    with self.env.open_file(self.path, "a") as f:
                        f.write(data)
                    self._size += len(data)
        except Exception:  # noqa: BLE001 - telemetry is best-effort, never fatal
            pass

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._history = []


def worker_telemetry(partition_id, exp_dir: str, role: str = "worker", env=None):
    """Build a worker's recorder with its JSONL sink attached — or the shared
    no-op recorder when ``MAGGY_TPU_TELEMETRY=0``, so executors need no flag
    checks of their own. Also points the process stall watchdog's dump dir
    at ``<exp_dir>/telemetry/`` so flight-recorder dumps land beside the
    JSONL they explain."""
    from maggy_tpu.telemetry import flightrec, recorder

    if not recorder.enabled():
        return recorder.NULL
    tel = recorder.Telemetry(worker=partition_id, role=role)
    tdir = telemetry_dir(exp_dir)
    tel.attach_sink(JsonlSink(posixpath.join(tdir, f"worker_{partition_id}.jsonl" if role != "driver" else "driver.jsonl"), env=env))
    flightrec.get().configure(dump_dir=tdir, env=env)
    return tel
