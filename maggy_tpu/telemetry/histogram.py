"""Fixed-log-bucket latency histogram.

The serving scheduler used to keep a 512-entry deque of raw TTFT samples and
sort it on every SSTATS poll — O(n log n) per poll, a hard sample cap that
silently forgets the past, and nothing two replicas could merge. This
primitive replaces it everywhere latencies are aggregated (TTFT, TPOT,
queue-wait, e2e, decode drain):

* **Fixed log-spaced buckets.** Bucket ``i`` covers
  ``[lo * growth**i, lo * growth**(i+1))`` milliseconds. With the defaults
  (``lo=0.05``, ``growth=1.15``, 128 buckets) the range runs ~0.05 ms to
  ~40 minutes at a constant ~7% relative resolution — percentile error is
  bounded by the bucket width, never by sample count.
* **O(1) observe** (one ``math.log`` + a list increment), unbounded sample
  count, constant memory.
* **Mergeable.** Two histograms with the same geometry add bucket-wise —
  the fleet router folds per-replica histograms into one fleet histogram
  with exact total counts (``merge``), which no percentile-of-percentiles
  scheme can do honestly.
* **JSON-portable.** ``to_dict``/``from_dict`` round-trip a sparse
  ``{index: count}`` encoding, small enough to ride in SSTATS replies,
  heartbeat snapshots, and telemetry JSONL.

Percentiles are reported at the geometric midpoint of the selected bucket;
``attainment(slo_ms)`` (the fraction of observations at or under an SLO
threshold) interpolates inside the straddling bucket. Both are therefore
bucket-resolution approximations — by construction within one bucket width
(~7%) of the exact order statistic.

Thread-safety: ``observe`` is a single list increment plus two scalar adds,
each GIL-atomic — same single-writer-per-worker contract as the recorder's
counters. ``merge`` and the readers copy under the caller's lock.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

DEFAULT_LO_MS = 0.05
DEFAULT_GROWTH = 1.15
DEFAULT_BUCKETS = 128


class LatencyHistogram:
    """Log-bucketed histogram of millisecond latencies."""

    __slots__ = ("lo", "growth", "nbuckets", "counts", "n", "total_ms", "_inv_log_growth")

    def __init__(
        self,
        lo: float = DEFAULT_LO_MS,
        growth: float = DEFAULT_GROWTH,
        nbuckets: int = DEFAULT_BUCKETS,
    ):
        if lo <= 0 or growth <= 1.0 or nbuckets < 2:
            raise ValueError(f"bad histogram geometry ({lo}, {growth}, {nbuckets})")
        self.lo = float(lo)
        self.growth = float(growth)
        self.nbuckets = int(nbuckets)
        self.counts = [0] * self.nbuckets
        self.n = 0
        self.total_ms = 0.0
        self._inv_log_growth = 1.0 / math.log(self.growth)

    # ------------------------------------------------------------------ write

    def _index(self, ms: float) -> int:
        if ms <= self.lo:
            return 0
        i = int(math.log(ms / self.lo) * self._inv_log_growth)
        return min(i, self.nbuckets - 1)

    def observe(self, ms: float) -> None:
        ms = float(ms)
        if ms < 0 or ms != ms:  # negative or NaN: clock skew, drop
            return
        self.counts[self._index(ms)] += 1
        self.n += 1
        self.total_ms += ms

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Add ``other``'s buckets into self (same geometry required)."""
        if (other.lo, other.growth, other.nbuckets) != (
            self.lo,
            self.growth,
            self.nbuckets,
        ):
            raise ValueError("cannot merge histograms with different geometry")
        for i, c in enumerate(other.counts):
            if c:
                self.counts[i] += c
        self.n += other.n
        self.total_ms += other.total_ms
        return self

    # ------------------------------------------------------------------- read

    def _edges(self, i: int):
        lower = self.lo * self.growth**i if i > 0 else 0.0
        upper = self.lo * self.growth ** (i + 1)
        return lower, upper

    def percentile(self, q: float) -> Optional[float]:
        """Approximate q-quantile (q in [0, 1]): the geometric midpoint of
        the bucket holding the ceil(q*n)-th observation. None when empty."""
        if self.n == 0:
            return None
        target = max(1, math.ceil(q * self.n))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                lower, upper = self._edges(i)
                return math.sqrt(max(lower, self.lo / self.growth) * upper)
        return None  # unreachable with n > 0

    def percentiles(self) -> Dict[str, Optional[float]]:
        return {
            "p50": self.percentile(0.50),
            "p90": self.percentile(0.90),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }

    @property
    def mean_ms(self) -> Optional[float]:
        return self.total_ms / self.n if self.n else None

    def attainment(self, slo_ms: float) -> Optional[float]:
        """Fraction of observations <= ``slo_ms`` (SLO attainment), with
        linear interpolation inside the bucket the threshold lands in.
        None when empty."""
        if self.n == 0:
            return None
        slo_ms = float(slo_ms)
        idx = self._index(slo_ms)
        under = sum(self.counts[:idx])
        lower, upper = self._edges(idx)
        frac = min(1.0, max(0.0, (slo_ms - lower) / (upper - lower)))
        if slo_ms >= self.lo * self.growth**self.nbuckets:
            frac = 1.0  # past the last bucket's upper edge: everything counts
        return (under + frac * self.counts[idx]) / self.n

    # ------------------------------------------------------------- serialize

    def to_dict(self) -> Dict[str, Any]:
        """Sparse JSON-safe encoding (bucket index -> count)."""
        return {
            "lo": self.lo,
            "growth": self.growth,
            "nbuckets": self.nbuckets,
            "n": self.n,
            "total_ms": round(self.total_ms, 3),
            "counts": {str(i): c for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LatencyHistogram":
        h = cls(
            lo=float(d.get("lo", DEFAULT_LO_MS)),
            growth=float(d.get("growth", DEFAULT_GROWTH)),
            nbuckets=int(d.get("nbuckets", DEFAULT_BUCKETS)),
        )
        for k, c in (d.get("counts") or {}).items():
            i = int(k)
            if 0 <= i < h.nbuckets:
                h.counts[i] = int(c)
        h.n = int(d.get("n", sum(h.counts)))
        h.total_ms = float(d.get("total_ms", 0.0))
        return h

    def copy(self) -> "LatencyHistogram":
        h = LatencyHistogram(self.lo, self.growth, self.nbuckets)
        h.counts = list(self.counts)
        h.n = self.n
        h.total_ms = self.total_ms
        return h

    def __repr__(self) -> str:  # debugging aid
        p = self.percentiles()
        return (
            f"LatencyHistogram(n={self.n}, p50={p['p50']}, p95={p['p95']}, "
            f"p99={p['p99']})"
        )


def merge_dicts(dicts) -> Optional[LatencyHistogram]:
    """Merge an iterable of ``to_dict`` encodings (skipping None/malformed)
    into one histogram; None when nothing merged. The fleet router's
    SSTATS fold uses this on per-replica snapshots."""
    out: Optional[LatencyHistogram] = None
    for d in dicts:
        if not d:
            continue
        try:
            h = LatencyHistogram.from_dict(d)
        except (TypeError, ValueError):
            continue
        if out is None:
            out = h
        else:
            try:
                out.merge(h)
            except ValueError:
                continue
    return out
