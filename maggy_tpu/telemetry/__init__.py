"""Unified telemetry: spans, step metrics, and durable trace export.

The reference framework's only observability is log shipping plus one scalar
metric per heartbeat (SURVEY §2.4 LOG/METRIC verbs). This package adds the
structured layer every tier threads through:

* :mod:`maggy_tpu.telemetry.recorder` — a process-local :class:`Telemetry`
  recorder with ``span(name)`` context managers and typed counters/gauges,
  buffered lock-free per worker. ``MAGGY_TPU_TELEMETRY=0`` swaps in a no-op
  recorder so the hot path carries zero instrumentation cost.
* :mod:`maggy_tpu.telemetry.sink` — a JSONL sink on the env storage seam, so
  records land under ``<exp_dir>/telemetry/worker_<pid>.jsonl`` identically on
  a local disk or ``gs://``.
* :mod:`maggy_tpu.telemetry.export` — merges every worker's JSONL into one
  Chrome-trace (Perfetto-loadable) JSON on the shared wall-clock base —
  including one lane per traced request — and mirrors gauge series into
  TensorBoard scalars via the tensorboard.py seam.
* :mod:`maggy_tpu.telemetry.tracing` — request-scoped trace ids, minted at
  the edge and propagated on every RPC frame; records tagged automatically.
* :mod:`maggy_tpu.telemetry.histogram` — fixed-log-bucket latency
  histograms (TTFT/TPOT/queue-wait/e2e), mergeable across replicas.
* :mod:`maggy_tpu.telemetry.flightrec` — stall watchdog + flight recorder:
  bounded event rings plus thread-stack dumps when a progress loop wedges.
* :mod:`maggy_tpu.telemetry.metrics` — the checked-in metric-name registry
  ``tools/check_telemetry_names.py`` enforces.
* :mod:`maggy_tpu.telemetry.timeseries` — bounded ring-buffer series sampled
  from the recorder on a fixed tick, with windowed ``rate``/``delta``/
  percentile queries and a versioned snapshot form (the ``METRICS`` RPC
  payload and the autoscaler's input substrate).
* :mod:`maggy_tpu.telemetry.alerts` — the checked-in alert-rule registry
  (threshold + for-duration, multi-window SLO burn rate) evaluated per
  worker and at fleet scope, plus the recompile sentinel.

Wiring: executors build a worker recorder (:func:`worker_telemetry`), install
it as the thread-ambient recorder (``Trainer.fit`` and ``Checkpointer`` pick
it up via :func:`get`), and hand it to the RPC client so per-verb latencies
and heartbeat RTTs record too; every heartbeat attaches a snapshot that the
driver folds into STATUS for the live monitor panel.
"""

from __future__ import annotations

from maggy_tpu.telemetry import alerts, flightrec, timeseries, tracing  # noqa: F401
from maggy_tpu.telemetry.alerts import AlertEvaluator, RecompileSentinel  # noqa: F401
from maggy_tpu.telemetry.histogram import LatencyHistogram  # noqa: F401
from maggy_tpu.telemetry.timeseries import Series, SeriesStore  # noqa: F401
from maggy_tpu.telemetry.recorder import (  # noqa: F401
    NULL,
    NullTelemetry,
    Telemetry,
    current,
    enabled,
    get,
    set_current,
)
from maggy_tpu.telemetry.sink import JsonlSink, telemetry_dir, worker_telemetry  # noqa: F401

__all__ = [
    "Telemetry",
    "NullTelemetry",
    "NULL",
    "enabled",
    "get",
    "set_current",
    "current",
    "JsonlSink",
    "telemetry_dir",
    "worker_telemetry",
    "LatencyHistogram",
    "Series",
    "SeriesStore",
    "AlertEvaluator",
    "RecompileSentinel",
    "tracing",
    "flightrec",
    "timeseries",
    "alerts",
]
