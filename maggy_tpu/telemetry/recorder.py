"""Process-local telemetry recorder.

One :class:`Telemetry` instance per worker (executor thread, pod process, or
the driver itself). The hot path — ``span`` enter/exit, ``gauge``,
``event``, ``histogram`` — touches only ``deque.append``s and dict stores,
each a single GIL-atomic operation, so per-worker recording is lock-free;
the only lock in the class guards the RPC latency accumulators, which sit
on network-bound paths where a ~100ns uncontended acquire is noise.

Every record is tagged with the thread-ambient trace id
(:mod:`maggy_tpu.telemetry.tracing`) when one is in scope, and teed into a
bounded flight ring the stall watchdog
(:mod:`maggy_tpu.telemetry.flightrec`) dumps when a progress loop wedges.
``histogram`` aggregates latency samples into fixed-log-bucket
distributions (:mod:`maggy_tpu.telemetry.histogram`) that ride in
snapshots — mergeable across workers, percentile-ready.

Two clocks, deliberately: every record carries a wall-clock ``ts``
(``time.time()``, the common base that lets the exporter merge spans from
many workers/hosts into one Chrome trace) while durations come from
``time.perf_counter()`` (monotonic, immune to NTP steps).

``MAGGY_TPU_TELEMETRY=0`` disables recording globally: :func:`get` then
returns the shared :data:`NULL` no-op recorder, whose ``span`` hands back one
reusable null context manager — the instrumented code paths stay in place at
zero cost.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from maggy_tpu.core import lockdebug
from maggy_tpu.telemetry import tracing
from maggy_tpu.telemetry.histogram import LatencyHistogram

ENV_FLAG = "MAGGY_TPU_TELEMETRY"

# span/gauge events kept in memory between sink flushes; oldest dropped first
# (a worker with an attached sink flushes every heartbeat, so the cap only
# matters for unflushed standalone use)
DEFAULT_CAPACITY = 100_000

# flight-recorder ring: the last records this worker produced, always in
# memory, dumped by the stall watchdog (telemetry/flightrec.py)
FLIGHT_CAPACITY = 512


def enabled() -> bool:
    """Telemetry is on unless explicitly disabled (``MAGGY_TPU_TELEMETRY=0``)."""
    return os.environ.get(ENV_FLAG, "1").lower() not in ("0", "false", "off")


class Telemetry:
    """Recorder for one worker: spans, gauges, counters, RPC latencies."""

    active = True

    def __init__(self, worker: Any = 0, role: str = "worker", capacity: int = DEFAULT_CAPACITY):
        self.worker = str(worker)
        self.role = role
        self._events: deque = deque(maxlen=capacity)
        # bounded tee of the same records for the stall flight recorder —
        # never drained, so a dump always has the recent past
        self.flight: deque = deque(maxlen=FLIGHT_CAPACITY)
        self._gauges: Dict[str, float] = {}  # race: ok — GIL-atomic dict stores, latest-value-wins semantics; snapshot copies are best-effort
        self._counters: Dict[str, int] = {}  # race: ok — single-writer per key by design (module docstring); rpc_errors.* keys are written only under _rpc_lock
        # name -> fixed-log-bucket latency distribution (single-writer per
        # worker, like counters; snapshot copies under no lock by the same
        # GIL-atomicity argument)
        self._hists: Dict[str, LatencyHistogram] = {}
        # verb -> [n, total_ms, max_ms]; the single locked structure (see
        # module docstring) because two threads (worker + heartbeat) write it
        self._rpc: Dict[str, List[float]] = {}
        self._rpc_lock = lockdebug.lock("telemetry._rpc_lock")
        self._sink = None
        # flush is called from both the worker thread (trial boundaries) and
        # the heartbeat thread (per beat); serialize so JSONL lines never tear
        self._flush_lock = lockdebug.lock("telemetry._flush_lock")
        _instances.add(self)

    # ------------------------------------------------------------------ spans

    def _append(self, rec: Dict[str, Any]) -> None:
        """Journal one record (sink buffer + flight ring), tagging it with
        the thread-ambient trace id when one is in scope — the whole
        cross-worker correlation story is this one optional field."""
        trace = tracing.current()
        if trace is not None:
            rec["trace"] = trace
        self._events.append(rec)
        self.flight.append(rec)

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        """Time a block; records wall-clock start + duration on exit."""
        ts = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            rec = {
                "kind": "span",
                "name": name,
                "ts": ts,
                "dur_ms": (time.perf_counter() - t0) * 1e3,
                "worker": self.worker,
                "tid": threading.get_ident() & 0xFFFF,
            }
            if attrs:
                rec["attrs"] = attrs
            self._append(rec)

    # ------------------------------------------------------- gauges / counters

    def gauge(self, name: str, value: float) -> None:  # thread-entry — heartbeat + scheduler threads record gauges
        """Set a gauge to its latest value (also journaled as an event)."""
        value = float(value)
        self._gauges[name] = value
        self._append(
            {
                "kind": "gauge",
                "name": name,
                "ts": time.time(),
                "value": value,
                "worker": self.worker,
            }
        )

    def count(self, name: str, n: int = 1) -> None:  # thread-entry — scheduler/router threads count from their loops
        """Increment a counter (single-writer per worker by design)."""
        self._counters[name] = self._counters.get(name, 0) + n

    def event(self, name: str, trace: Optional[str] = None, **attrs) -> None:
        """Journal one lifecycle milestone (request/run state transition),
        correlated by ``trace`` (explicit, else the thread-ambient id)."""
        rec: Dict[str, Any] = {
            "kind": "event",
            "name": name,
            "ts": time.time(),
            "worker": self.worker,
        }
        if attrs:
            rec["attrs"] = attrs
        if trace is not None:
            rec["trace"] = trace
            self._events.append(rec)
            self.flight.append(rec)
        else:
            self._append(rec)

    def histogram(self, name: str, value_ms: float) -> None:
        """Observe one latency sample into the named fixed-log-bucket
        histogram (created on first use; serialized into snapshots)."""
        h = self._hists.get(name)
        if h is None:
            h = self._hists.setdefault(name, LatencyHistogram())
        h.observe(value_ms)

    def rpc(self, verb: str, ms: Optional[float] = None, ok: bool = True) -> None:  # thread-entry — worker + heartbeat threads both record RPCs
        """Record one RPC round-trip for ``verb`` (thread-safe)."""
        with self._rpc_lock:
            rec = self._rpc.setdefault(verb, [0, 0.0, 0.0])
            rec[0] += 1
            if ms is not None:
                rec[1] += ms
                if ms > rec[2]:
                    rec[2] = ms
            if not ok:
                self._counters[f"rpc_errors.{verb}"] = (
                    self._counters.get(f"rpc_errors.{verb}", 0) + 1
                )

    # ------------------------------------------------------------------ export

    def snapshot(self) -> Dict[str, Any]:  # thread-entry — the heartbeat thread attaches snapshots to beats
        """Compact aggregate state for heartbeat attachment: latest gauges,
        counters, and per-verb RPC stats — no event history."""
        out: Dict[str, Any] = {"worker": self.worker, "role": self.role, "ts": time.time()}
        if self._gauges:
            out["gauges"] = dict(self._gauges)
        if self._counters:
            out["counters"] = dict(self._counters)
        if self._hists:
            out["hist"] = {name: h.to_dict() for name, h in self._hists.items()}
        with self._rpc_lock:
            if self._rpc:
                out["rpc"] = {
                    verb: {
                        "n": int(n),
                        "mean_ms": round(total / n, 3) if n else None,
                        "max_ms": round(mx, 3),
                    }
                    for verb, (n, total, mx) in self._rpc.items()
                }
        return out

    def drain_events(self) -> List[Dict[str, Any]]:
        """Pop and return all buffered events (safe against concurrent appends)."""
        out = []
        try:
            while True:
                out.append(self._events.popleft())
        except IndexError:
            pass
        return out

    # ------------------------------------------------------------------- sink

    def attach_sink(self, sink) -> None:
        self._sink = sink

    def flush(self) -> None:  # thread-entry — the heartbeat thread flushes every beat
        """Drain buffered events into the attached sink (no-op without one)."""
        if self._sink is None:
            return
        with self._flush_lock:
            if self._sink is None:
                return
            events = self.drain_events()
            if events:
                self._sink.write(events)

    def close(self) -> None:
        """Final flush + snapshot record, then close the sink."""
        if self._sink is None:
            return
        snap = self.snapshot()
        snap["kind"] = "snapshot"
        self._events.append(snap)
        self.flush()
        with self._flush_lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None


class NullTelemetry:
    """No-op recorder installed when telemetry is disabled."""

    active = False
    worker = "null"
    role = "null"

    _NULL_CTX = contextlib.nullcontext()

    def span(self, name: str, **attrs):
        return self._NULL_CTX

    def gauge(self, name: str, value: float) -> None:
        pass

    def count(self, name: str, n: int = 1) -> None:
        pass

    def event(self, name: str, trace: Optional[str] = None, **attrs) -> None:
        pass

    def histogram(self, name: str, value_ms: float) -> None:
        pass

    def rpc(self, verb: str, ms: Optional[float] = None, ok: bool = True) -> None:
        pass

    def snapshot(self) -> Dict[str, Any]:
        return {}

    def drain_events(self) -> List[Dict[str, Any]]:
        return []

    def attach_sink(self, sink) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


NULL = NullTelemetry()

# every live recorder, for the stall watchdog's dump (weak: a recorder dies
# with its owner, the registry must not keep it alive)
_instances: "weakref.WeakSet[Telemetry]" = weakref.WeakSet()


def flight_snapshots() -> List[Dict[str, Any]]:
    """Every live recorder's flight ring (most recent records last), for
    the watchdog dump. Rings are copied, never drained."""
    out = []
    for tel in list(_instances):
        ring = list(tel.flight)
        if ring:
            out.append({"worker": tel.worker, "role": tel.role, "events": ring})
    return out


# thread-ambient recorder: executors are THREADS in one process (like the
# Reporter print tee), so the current recorder is thread-local, with one lazy
# process-wide default for standalone Trainer.fit use outside any experiment
_tls = threading.local()
_default_lock = threading.Lock()
_default: Optional[Telemetry] = None


def get():
    """The ambient recorder for this thread; :data:`NULL` when disabled."""
    if not enabled():
        return NULL
    tel = getattr(_tls, "telemetry", None)
    if tel is not None:
        return tel
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Telemetry(worker="main", role="standalone")
    return _default


def set_current(tel) -> None:
    """Install ``tel`` as this thread's ambient recorder (None to clear)."""
    _tls.telemetry = tel


@contextlib.contextmanager
def current(tel) -> Iterator[None]:
    """Scope ``tel`` as the ambient recorder for this thread."""
    prev = getattr(_tls, "telemetry", None)
    _tls.telemetry = tel
    try:
        yield
    finally:
        _tls.telemetry = prev
