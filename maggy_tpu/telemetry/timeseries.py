"""Bounded in-memory time series over the telemetry recorder.

Everything else in ``telemetry/`` is a point-in-time snapshot: the recorder
keeps *latest* gauge values, *cumulative* counters, and *cumulative*
histograms. This module adds the time dimension — a :class:`SeriesStore`
samples a recorder on a fixed tick into bounded ring buffers, so windowed
questions ("TTFT p95 over the last 30 s", "queue-depth trend", "requests/s")
have an answer without a log scan. The fleet router keeps one store per
replica plus a fleet-aggregate store fed at the *same* tick, which is what
makes ``tools/metrics_query.py`` able to reproduce fleet percentiles from
per-replica exports: bucket-wise histogram merge commutes with windowed
subtraction when the ticks align.

Design rules, same as the recorder:

- Lock-free hot path. Appends are single-writer (the sampling thread);
  readers copy via ``list(deque)`` which is atomic under the GIL.
- Histogram series store *cumulative* ``LatencyHistogram.to_dict()``
  encodings per tick. A windowed distribution is the bucket-wise difference
  between the newest snapshot and the last snapshot at-or-before the window
  start — O(buckets), no samples retained.
- Counters are cumulative too; ``delta()``/``rate()`` difference the ring.
  A counter reset (process restart) clamps to zero rather than going
  negative.

The serialized form (:meth:`SeriesStore.snapshot`) is versioned so exported
snapshots stay readable across PRs.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .histogram import LatencyHistogram, merge_dicts

SCHEMA_VERSION = 1

DEFAULT_CAPACITY = 512  # points per series ring
DEFAULT_INTERVAL_S = 1.0  # sampling tick

KINDS = ("gauge", "counter", "hist")


def hist_delta(
    newer: Optional[Dict[str, Any]], older: Optional[Dict[str, Any]]
) -> Optional[LatencyHistogram]:
    """Bucket-wise ``newer - older`` of two cumulative ``to_dict`` encodings.

    Returns the distribution of observations that happened *between* the two
    snapshots. ``older=None`` means "since the beginning" (newer as-is).
    Negative buckets (histogram reset) clamp to zero. Geometry mismatch —
    impossible for snapshots of one series, conceivable across restarts —
    falls back to ``newer``.
    """
    if not newer:
        return None
    try:
        h = LatencyHistogram.from_dict(newer)
    except (TypeError, ValueError):
        return None
    if not older:
        return h
    try:
        o = LatencyHistogram.from_dict(older)
    except (TypeError, ValueError):
        return h
    if (o.lo, o.growth, o.nbuckets) != (h.lo, h.growth, h.nbuckets):
        return h
    for i, c in enumerate(o.counts):
        if c:
            h.counts[i] = max(0, h.counts[i] - c)
    h.n = max(0, h.n - o.n)
    h.total_ms = max(0.0, h.total_ms - o.total_ms)
    return h


class Series:
    """One named metric over time: a bounded ring of ``(ts, value)`` points.

    ``kind`` is ``"gauge"`` (point-in-time value), ``"counter"`` (cumulative
    total; query via ``delta``/``rate``), or ``"hist"`` (cumulative
    ``LatencyHistogram.to_dict()`` encoding; query via
    ``percentile``/``attainment`` over a window).
    """

    __slots__ = ("name", "kind", "_points")

    def __init__(self, name: str, kind: str, capacity: int = DEFAULT_CAPACITY):
        if kind not in KINDS:
            raise ValueError(f"unknown series kind {kind!r}")
        self.name = name
        self.kind = kind
        self._points: deque = deque(maxlen=max(2, int(capacity)))

    # ------------------------------------------------------------------ write

    def append(self, ts: float, value: Any) -> None:
        self._points.append((ts, value))

    # ------------------------------------------------------------------- read

    def __len__(self) -> int:
        return len(self._points)

    def points(self) -> List[Tuple[float, Any]]:
        return list(self._points)

    def latest(self) -> Optional[Tuple[float, Any]]:
        pts = self._points
        return pts[-1] if pts else None

    def tail(self, n: int) -> List[Tuple[float, Any]]:
        pts = list(self._points)
        return pts[-int(n):] if n else []

    def window(self, window_s: float, now: Optional[float] = None) -> List[Tuple[float, Any]]:
        """Points with ``ts >= now - window_s`` (oldest first)."""
        pts = list(self._points)
        if not pts:
            return []
        cutoff = (now if now is not None else pts[-1][0]) - window_s
        return [p for p in pts if p[0] >= cutoff]

    def _bounds(
        self, window_s: float, now: Optional[float] = None
    ) -> Tuple[Optional[Tuple[float, Any]], Optional[Tuple[float, Any]]]:
        """(base, last) spanning the window: ``base`` is the newest point
        at-or-before the window start (so the difference covers the full
        window), or None when the ring doesn't reach back that far — then
        the caller differences against the oldest retained point."""
        pts = list(self._points)
        if not pts:
            return None, None
        last = pts[-1]
        cutoff = (now if now is not None else last[0]) - window_s
        base = None
        for p in pts:
            if p[0] <= cutoff:
                base = p
            else:
                break
        return base, last

    # ------------------------------------------------- windowed queries

    def delta(self, window_s: float, now: Optional[float] = None) -> Optional[float]:
        """Increase of a cumulative series over the window (clamped >= 0)."""
        base, last = self._bounds(window_s, now)
        if last is None:
            return None
        if base is None:
            pts = list(self._points)
            if len(pts) < 2:
                return None
            base = pts[0]
        return max(0.0, float(last[1]) - float(base[1]))

    def rate(self, window_s: float, now: Optional[float] = None) -> Optional[float]:
        """Per-second increase over the window (counter kind)."""
        base, last = self._bounds(window_s, now)
        if last is None:
            return None
        if base is None:
            pts = list(self._points)
            if len(pts) < 2:
                return None
            base = pts[0]
        elapsed = last[0] - base[0]
        if elapsed <= 0:
            return None
        return max(0.0, float(last[1]) - float(base[1])) / elapsed

    def window_hist(
        self, window_s: float, now: Optional[float] = None
    ) -> Optional[LatencyHistogram]:
        """Distribution of observations inside the window (hist kind)."""
        base, last = self._bounds(window_s, now)
        if last is None:
            return None
        return hist_delta(last[1], base[1] if base is not None else None)

    def percentile(
        self, q: float, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        h = self.window_hist(window_s, now)
        return h.percentile(q) if h is not None else None

    def attainment(
        self, slo_ms: float, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        h = self.window_hist(window_s, now)
        return h.attainment(slo_ms) if h is not None else None

    def values(self, n: int = 16) -> List[float]:
        """Last ``n`` numeric values for trend display. Counters come back as
        successive differences (per-tick increments), hist as per-point n."""
        pts = self.tail(n + 1 if self.kind == "counter" else n)
        if self.kind == "gauge":
            return [float(v) for _, v in pts]
        if self.kind == "counter":
            vals = [float(v) for _, v in pts]
            return [max(0.0, b - a) for a, b in zip(vals, vals[1:])]
        out = []
        for _, v in pts:
            out.append(float((v or {}).get("n", 0)))
        return out

    # -------------------------------------------------------------- serialize

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "points": [[ts, v] for ts, v in self._points]}

    @classmethod
    def from_dict(cls, name: str, d: Dict[str, Any], capacity: int = DEFAULT_CAPACITY) -> "Series":
        s = cls(name, str(d.get("kind", "gauge")), capacity)
        for ts, v in d.get("points") or []:
            s.append(float(ts), v)
        return s


class SeriesStore:
    """A keyed set of :class:`Series` plus the sampling tick that feeds them.

    One store per scope: each worker's scheduler owns one (fed from its
    recorder), and the fleet router owns one per replica plus a
    fleet-aggregate store fed at the same tick.
    """

    def __init__(
        self,
        interval_s: float = DEFAULT_INTERVAL_S,
        capacity: int = DEFAULT_CAPACITY,
    ):
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        # single-writer per store: only the owner's metrics tick (scheduler
        # decode thread / router pump) appends; RPC-side readers copy via
        # list() and tolerate a tick of staleness
        self._series: Dict[str, Series] = {}  # race: ok — single-writer (owner tick); GIL-atomic dict stores; readers snapshot via list()
        self._last_sample = 0.0  # race: ok — single-writer tick gate; a stale read costs one extra compare

    # ------------------------------------------------------------------ write

    def series(self, name: str, kind: str) -> Series:
        s = self._series.get(name)
        if s is None or s.kind != kind:
            s = Series(name, kind, self.capacity)
            self._series[name] = s
        return s

    def ingest(  # thread-entry — the router pump / scheduler metrics threads feed ticks
        self,
        ts: float,
        gauges: Optional[Dict[str, float]] = None,
        counters: Optional[Dict[str, float]] = None,
        hists: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> None:
        """Append one aligned tick of externally-sourced values (the router
        feeds replica SSTATS through this)."""
        for name, v in (gauges or {}).items():
            if v is not None:
                self.series(name, "gauge").append(ts, float(v))
        for name, v in (counters or {}).items():
            if v is not None:
                self.series(name, "counter").append(ts, float(v))
        for name, d in (hists or {}).items():
            if d:
                self.series(name, "hist").append(ts, dict(d))

    def sample(self, recorder, now: Optional[float] = None) -> float:  # thread-entry — called from the scheduler's decode-loop tick
        """Copy the recorder's current gauges/counters/histograms into the
        rings as one tick. Cheap: dict copies + one ``to_dict`` per live
        histogram; the recorder's single-writer/GIL-atomic contract makes
        the reads safe without locks."""
        ts = now if now is not None else time.time()
        gauges = dict(getattr(recorder, "_gauges", None) or {})
        counters = dict(getattr(recorder, "_counters", None) or {})
        hists = dict(getattr(recorder, "_hists", None) or {})
        self.ingest(
            ts,
            gauges=gauges,
            counters=counters,
            hists={k: h.to_dict() for k, h in hists.items()},
        )
        self._last_sample = ts
        return ts

    def maybe_sample(self, recorder, now: Optional[float] = None) -> bool:
        """Tick-gated :meth:`sample`; the per-call cost when it's not time
        yet is one clock read and a compare."""
        ts = now if now is not None else time.time()
        if ts - self._last_sample < self.interval_s:
            return False
        self.sample(recorder, ts)
        return True

    # ------------------------------------------------------------------- read

    def get(self, name: str) -> Optional[Series]:
        return self._series.get(name)

    def names(self) -> List[str]:
        return sorted(self._series)

    def trends(self, names: Iterable[str], n: int = 16) -> Dict[str, List[float]]:
        """Compact recent-values map for sparkline rendering."""
        out: Dict[str, List[float]] = {}
        for name in names:
            s = self._series.get(name)
            if s is not None and len(s):
                vals = s.values(n)
                if vals:
                    out[name] = [round(v, 3) for v in vals]
        return out

    # -------------------------------------------------------------- serialize

    def snapshot(self) -> Dict[str, Any]:
        """Versioned JSON-safe encoding of every series (the METRICS verb
        payload and the on-disk export form)."""
        return {
            "v": SCHEMA_VERSION,
            "interval_s": self.interval_s,
            "series": {name: s.to_dict() for name, s in list(self._series.items())},
        }

    @classmethod
    def from_snapshot(cls, d: Dict[str, Any]) -> "SeriesStore":
        v = int(d.get("v", 0))
        if v > SCHEMA_VERSION:
            raise ValueError(f"timeseries snapshot v{v} newer than supported v{SCHEMA_VERSION}")
        store = cls(interval_s=float(d.get("interval_s", DEFAULT_INTERVAL_S)))
        for name, sd in (d.get("series") or {}).items():
            store._series[name] = Series.from_dict(name, sd, store.capacity)
        return store


def merge_windowed_hist(
    stores: Iterable[SeriesStore],
    name: str,
    window_s: float,
    now: Optional[float] = None,
) -> Optional[LatencyHistogram]:
    """Fleet merge of one histogram series: sum of each store's windowed
    distribution. Because bucket addition commutes with the windowed
    subtraction, this equals the router's fleet-aggregate series (which
    appends the bucket-wise merge of per-replica cumulative snapshots at
    the same tick) queried over the same window."""
    parts = []
    for store in stores:
        s = store.get(name)
        if s is None or s.kind != "hist":
            continue
        h = s.window_hist(window_s, now)
        if h is not None:
            parts.append(h.to_dict())
    return merge_dicts(parts)


def merge_windowed_percentile(
    stores: Iterable[SeriesStore],
    name: str,
    q: float,
    window_s: float,
    now: Optional[float] = None,
) -> Optional[float]:
    h = merge_windowed_hist(stores, name, window_s, now)
    return h.percentile(q) if h is not None else None
