"""Alert-triggered profile capture: evidence at the moment capacity breaks.

A burn-rate page tells you *that* HBM headroom collapsed; by the time a
human attaches a profiler the episode is over. This module closes that gap:
a bounded, cooldown-limited controller that arms a profile capture the
moment a watched critical alert **transitions to firing** (the scheduler's
metrics tick feeds it the transition list ``AlertEvaluator.evaluate``
already returns), and writes the capture next to the flight-recorder dumps
under ``<exp_dir>/telemetry/profcap_<ts>_<n>/``.

What a capture holds:

* on an accelerator backend, a real ``jax.profiler`` trace of
  :attr:`ProfileCapture.trace_s` seconds (the device timeline for the
  exact window the alert fired in);
* everywhere (and always, as the CPU-safe fallback), a flight-recorder-style
  ``capture.json``: the triggering alert, every firing alert, the recent
  samples of each alerted series (``alerted_series_tails``), and the stack
  of every thread — self-describing without any device tooling.

Bounds, because a profiler armed by an alert is a footgun: at most
:data:`MAX_CAPTURES` per process, at least :attr:`~ProfileCapture.cooldown_s`
seconds apart (a flapping alert produces ONE capture per episode, not one
per flap), and the whole controller is disabled by ``MAGGY_TPU_PROFCAP=0``.
A capture failure is swallowed — observability must never sink the loop.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional

from maggy_tpu.core import lockdebug

ENV_FLAG = "MAGGY_TPU_PROFCAP"
DEFAULT_COOLDOWN_S = 120.0
DEFAULT_TRACE_S = 0.5
MAX_CAPTURES = 4  # per-process cap, like flightrec.MAX_DUMPS

# critical capacity alerts that arm a capture by default; callers can widen
# or narrow per instance
DEFAULT_WATCH = ("alert.hbm_headroom", "alert.fragmentation")


def enabled() -> bool:
    return os.environ.get(ENV_FLAG, "1").lower() not in ("0", "false", "off")


class ProfileCapture:
    """Cooldown-limited alert→profile controller for one worker.

    Owned by the scheduler (or trainer) beside its :class:`AlertEvaluator`;
    :meth:`tick` runs on the owner's metrics thread with the transitions
    that thread's ``evaluate`` call just returned. State is lock-guarded so
    SSTATS readers can snapshot it from RPC threads.
    """

    def __init__(
        self,
        dump_dir: Optional[str] = None,
        env=None,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        max_captures: int = MAX_CAPTURES,
        trace_s: float = DEFAULT_TRACE_S,
        watch: Optional[Iterable[str]] = None,
    ):
        self._lock = lockdebug.lock("profcap._lock")
        self.dump_dir = dump_dir
        self._env = env
        self.cooldown_s = float(cooldown_s)
        self.max_captures = int(max_captures)
        self.trace_s = float(trace_s)
        self.watch = frozenset(watch if watch is not None else DEFAULT_WATCH)
        self._count = 0  # guarded-by: _lock
        self._last_ts: Optional[float] = None  # guarded-by: _lock
        self.captures: List[str] = []  # written paths  # guarded-by: _lock
        self.last_capture: Optional[Dict[str, Any]] = None

    def configure(self, dump_dir: Optional[str] = None, env=None) -> None:
        """Late wiring — the telemetry sink knows the dump dir, not us."""
        if dump_dir is not None:
            self.dump_dir = str(dump_dir)
        if env is not None:
            self._env = env

    # ------------------------------------------------------------------- tick

    def tick(self, transitions, now: Optional[float] = None) -> Optional[str]:  # thread-entry — ticked from the owning scheduler/trainer metrics loop
        """Arm a capture when a watched alert just transitioned to firing.

        ``transitions`` is whatever ``AlertEvaluator.evaluate`` returned this
        tick. Returns the capture directory path (None when nothing fired,
        disabled, in cooldown, or over the per-process cap)."""
        if not enabled() or not transitions:
            return None
        from maggy_tpu.telemetry.alerts import ALERT_FIRING

        trigger = None
        for t in transitions:
            if t.get("event") == ALERT_FIRING and t.get("alert") in self.watch:
                trigger = t
                break
        if trigger is None:
            return None
        ts = time.time() if now is None else float(now)
        with self._lock:
            if self._count >= self.max_captures:
                return None
            if self._last_ts is not None and ts - self._last_ts < self.cooldown_s:
                return None
            self._last_ts = ts
            self._count += 1
            n = self._count
        try:
            return self._capture(trigger, ts, n)
        except Exception:  # noqa: BLE001 - a failed capture must not kill serving
            return None

    # ---------------------------------------------------------------- capture

    def _capture(self, trigger: Dict[str, Any], ts: float, n: int) -> Optional[str]:
        from maggy_tpu.telemetry import alerts as alerts_mod
        from maggy_tpu.telemetry import flightrec
        from maggy_tpu.telemetry import recorder as rec_mod

        out_dir = (
            os.path.join(str(self.dump_dir), f"profcap_{int(ts)}_{n}")
            if self.dump_dir is not None
            else None
        )
        payload: Dict[str, Any] = {
            "kind": "profcap",
            "ts": ts,
            "reason": f"alert:{trigger.get('alert')}",
            "trigger": dict(trigger),
            "pid": os.getpid(),
            "profiler": self._device_trace(out_dir),
            "alerts": alerts_mod.active_alerts(),
            "alert_series": alerts_mod.alerted_series_tails(),
            "threads": flightrec.thread_stacks(),
        }
        self.last_capture = payload
        rec_mod.get().count("profcap.captures")
        if out_dir is None:
            return None
        path = os.path.join(out_dir, "capture.json")
        text = json.dumps(payload, separators=(",", ":"), default=str)
        try:
            os.makedirs(out_dir, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                f.write(text)
        except OSError:
            return None
        with self._lock:
            self.captures.append(out_dir)
        return out_dir

    def _device_trace(self, out_dir: Optional[str]) -> str:
        """Bounded ``jax.profiler`` trace on accelerator backends; on CPU
        (or any failure) the JSON fallback payload IS the capture."""
        if out_dir is None:
            return "fallback"
        try:
            import jax

            if jax.default_backend() == "cpu":
                return "fallback"
            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(min(self.trace_s, 2.0))
            finally:
                jax.profiler.stop_trace()
            return "jax.profiler"
        except Exception:  # noqa: BLE001 - profiler arming is best-effort
            return "fallback"

    # ------------------------------------------------------------------ state

    def snapshot(self) -> Dict[str, Any]:
        """SSTATS-ready controller state."""
        with self._lock:
            return {
                "enabled": enabled(),
                "captures": self._count,
                "cooldown_s": self.cooldown_s,
                "max_captures": self.max_captures,
                "last_ts": self._last_ts,
                "paths": list(self.captures),
            }
