"""Trace attribution: where time went, per request and per training step.

This is the ONE implementation behind both consumers of a run's merged
telemetry JSONL — the human report (``tools/analyze_trace.py``) and the
autopilot Diagnoser (:mod:`maggy_tpu.autopilot.diagnose`) — so the numbers
an operator reads and the numbers the continuous-tuning loop acts on can
never drift apart.

Input: an experiment dir (or its ``telemetry/`` subdir) holding the
per-worker ``*.jsonl`` files (rotated ``*.jsonl.N`` segments are read too,
oldest first). The request-scoped tracing layer (docs/observability.md)
stamps every lifecycle event with a trace id, so one request's milestones —
``req.accepted`` on the router, ``req.queued``/``req.admitted``/
``req.first_token``/``req.finished`` on whichever replica served it,
``req.requeued`` hops in between — line up on the shared wall clock no
matter which worker wrote them.

Attribution is gap-labeling: consecutive milestone pairs within one trace
name the segment between them (accepted→dispatched = ``route``,
queued→admitted = ``queue``, admitted→first_token = ``prefill``,
first_token→finished = ``decode``, ...; unknown pairs land in ``other``).
Segments therefore sum to the measured e2e by construction — the report's
job is to show *which* bucket ate the time.

Per-step attribution reads the training gauges: ``step_time_ms`` (host wall
per step), ``input_wait_ms`` (blocked on the input pipeline), and
``metrics_drain_ms`` (lagged broadcast reads), with the remainder reported
as compute/dispatch.

:func:`analyze` returns the machine-readable result (the exact object
``tools/analyze_trace.py --json`` prints); its layout is versioned under
``schema`` = :data:`SCHEMA` and treated as a stable contract:

* ``requests``: one row per trace — ``trace``, ``rid``, ``state``,
  ``start_ts``, ``e2e_ms``, ``hops``, ``components`` ({bucket: ms}).
* ``request_summary``: ``requests``, ``requeue_hops``, ``e2e_ms_mean``,
  ``components_ms_mean``, ``components_share``.
* ``step_summary``: ``steps``, ``step_ms_mean``, ``input_wait_ms_mean``,
  ``metrics_drain_ms_mean``, ``compute_ms_est``.

Keep this module stdlib-only: the CLI tool loads it without jax.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

# bump ONLY with an additive change note in docs/observability.md; external
# tooling keys on this.
# v2 (additive): rows carry the capacity attrs stamped on the lifecycle
# events — ``pages_held_peak`` (req.finished) and ``headroom_at_admit``
# (req.admitted / req.prefix_admitted). v1 JSONL without those attrs still
# reads fine: the fields are simply None.
SCHEMA = "maggy-tpu.trace-attribution.v2"

# (previous milestone, this milestone) -> attribution bucket; gaps between
# consecutive lifecycle events not named here land in "other"
GAP_LABELS: Dict[Tuple[str, str], str] = {
    ("req.accepted", "req.dispatched"): "route",
    ("req.requeued", "req.dispatched"): "route",
    ("req.accepted", "req.shed"): "route",
    ("req.dispatched", "req.queued"): "transit",
    ("req.accepted", "req.queued"): "transit",
    ("req.queued", "req.admitted"): "queue",
    ("req.queued", "req.prefix_admitted"): "queue",
    ("req.admitted", "req.first_token"): "prefill",
    ("req.prefix_admitted", "req.first_token"): "prefill",
    ("req.first_token", "req.finished"): "decode",
    ("req.finished", "req.completed"): "completion",
    ("req.queued", "req.requeued"): "lost",
    ("req.admitted", "req.requeued"): "lost",
    ("req.prefix_admitted", "req.requeued"): "lost",
    ("req.first_token", "req.requeued"): "lost",
    ("req.dispatched", "req.requeued"): "lost",
    ("req.finished", "req.requeued"): "lost",
    ("req.queued", "req.finished"): "queue",  # expired/cancelled in queue
}

COMPONENT_ORDER = (
    "route",
    "transit",
    "queue",
    "prefill",
    "decode",
    "lost",
    "completion",
    "other",
)

TERMINALS = ("req.completed", "req.finished", "req.shed")

# the per-step gauges the step attribution aggregates
STEP_GAUGES = ("step_time_ms", "input_wait_ms", "metrics_drain_ms")


def iter_jsonl_files(tdir: str) -> List[str]:
    """All JSONL files under ``tdir``, rotated segments ordered oldest
    first within each stem (``x.jsonl.3`` before ``x.jsonl.1`` before
    ``x.jsonl``)."""
    entries = []
    for path in glob.glob(os.path.join(tdir, "*.jsonl*")):
        base = os.path.basename(path)
        stem, _, suffix = base.partition(".jsonl")
        if suffix and not suffix[1:].isdigit():
            continue  # not a rotation segment (e.g. .jsonl.tmp)
        seg = int(suffix[1:]) if suffix else 0
        entries.append((stem, -seg, path))
    return [path for _, _, path in sorted(entries)]


def load_records(tdir: str) -> List[Dict[str, Any]]:
    import json

    records: List[Dict[str, Any]] = []
    for path in iter_jsonl_files(tdir):
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail from a crashed worker
        except OSError:
            continue
    return records


# --------------------------------------------------------------- per request


def attribute_requests(records: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One attribution row per trace that carries request lifecycle events:
    ``{trace, rid, state, e2e_ms, components: {bucket: ms}}``. Components
    sum to e2e_ms by construction (every inter-milestone gap is labeled)."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("kind") != "event" or not rec.get("trace"):
            continue
        if not str(rec.get("name", "")).startswith("req."):
            continue
        by_trace.setdefault(rec["trace"], []).append(rec)
    out = []
    for trace, events in sorted(by_trace.items()):
        events.sort(key=lambda e: float(e.get("ts", 0.0)))
        # cut the timeline at the last terminal milestone: late duplicate
        # polls after completion must not stretch the request
        end_idx = max(
            (i for i, e in enumerate(events) if e.get("name") in TERMINALS),
            default=len(events) - 1,
        )
        events = events[: end_idx + 1]
        components: Dict[str, float] = {}
        for prev, cur in zip(events, events[1:]):
            gap_ms = (float(cur["ts"]) - float(prev["ts"])) * 1e3
            label = GAP_LABELS.get((prev["name"], cur["name"]), "other")
            components[label] = components.get(label, 0.0) + max(0.0, gap_ms)
        attrs = {}
        for e in events:
            attrs.update(e.get("attrs") or {})
        out.append(
            {
                "trace": trace,
                "rid": attrs.get("rid"),
                "state": attrs.get("state", "?"),
                "start_ts": float(events[0]["ts"]),
                "e2e_ms": (float(events[-1]["ts"]) - float(events[0]["ts"])) * 1e3,
                "hops": sum(1 for e in events if e["name"] == "req.requeued"),
                "components": components,
                # schema v2 capacity fields (None on v1 JSONL)
                "pages_held_peak": attrs.get("pages_held_peak"),
                "headroom_at_admit": attrs.get("headroom_at_admit"),
            }
        )
    return out


def summarize_requests(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    if not rows:
        return {"requests": 0}
    total = {k: 0.0 for k in COMPONENT_ORDER}
    for row in rows:
        for k, v in row["components"].items():
            total[k] = total.get(k, 0.0) + v
    e2e_sum = sum(r["e2e_ms"] for r in rows)
    return {
        "requests": len(rows),
        "requeue_hops": sum(r["hops"] for r in rows),
        "e2e_ms_mean": e2e_sum / len(rows),
        "components_ms_mean": {
            k: v / len(rows) for k, v in total.items() if v > 0
        },
        "components_share": {
            k: v / e2e_sum for k, v in total.items() if v > 0 and e2e_sum > 0
        },
    }


# ------------------------------------------------------------------ per step


def attribute_steps(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Training-loop attribution from the per-step gauges: where a mean
    step's wall clock went (input wait, metrics drain, compute residual)."""
    series: Dict[str, List[float]] = {}
    for rec in records:
        if rec.get("kind") != "gauge":
            continue
        name = rec.get("name")
        if name in STEP_GAUGES:
            try:
                series.setdefault(name, []).append(float(rec.get("value", 0.0)))
            except (TypeError, ValueError):
                continue

    def mean(name: str) -> Optional[float]:
        vals = series.get(name)
        return sum(vals) / len(vals) if vals else None

    step = mean("step_time_ms")
    wait = mean("input_wait_ms") or 0.0
    drain = mean("metrics_drain_ms") or 0.0
    out: Dict[str, Any] = {
        "steps": len(series.get("step_time_ms", [])),
        "step_ms_mean": step,
        "input_wait_ms_mean": mean("input_wait_ms"),
        "metrics_drain_ms_mean": mean("metrics_drain_ms"),
    }
    if step is not None:
        out["compute_ms_est"] = max(0.0, step - wait - drain)
    return out


# -------------------------------------------------------------------- entry


def analyze(path: str) -> Dict[str, Any]:
    """Full attribution for an experiment dir (or its ``telemetry/``
    subdir). The returned dict IS the ``--json`` output — see the module
    docstring for the schema contract."""
    tdir = path
    sub = os.path.join(path, "telemetry")
    if os.path.isdir(sub):
        tdir = sub
    records = load_records(tdir)
    rows = attribute_requests(records)
    return {
        "schema": SCHEMA,
        "telemetry_dir": tdir,
        "requests": rows,
        "request_summary": summarize_requests(rows),
        "step_summary": attribute_steps(records),
    }
