"""Checked-in metric-name registry.

Every ``gauge``/``counter``/``histogram``/``event`` name the codebase emits
must appear here — ``tools/check_telemetry_names.py`` (wired into tier-1)
walks ``maggy_tpu/`` and fails on any telemetry call whose literal name is
missing. The failure mode this kills: a typo'd name (``serve.ttft_m``)
silently splits a series into two, and every dashboard/percentile downstream
quietly reads half the data.

Keep this module import-light (stdlib only): the lint loads it by file path
without importing the package, so it must not pull jax or anything heavy.

Adding a metric = add the name here (grouped by kind, with a one-line
meaning) + emit it. Names are grouped per kind because a name may legally
be both a gauge (latest value, monitor panel) and a histogram (full
distribution, SSTATS percentiles) — ``serve.ttft_ms`` is.
"""

from __future__ import annotations

# gauges: latest-value signals (monitor panels, heartbeat snapshots)
GAUGES = frozenset(
    {
        # training loop (train/trainer.py)
        "step_time_ms",  # host wall clock per step
        "step_time_ms_mean",  # mean over the run, compile step excluded
        "compile_time_ms",  # first step, synced to cover the XLA compile
        "steps_per_sec",
        "tokens_per_sec",
        "mfu_est",  # 6*params FLOPs estimate vs detected chip peak
        "metrics_lag",  # steps between a broadcast and its metric
        "metrics_drain_ms",  # host time in the lagged broadcast read
        "resumed_step",  # resume="auto" restore point
        # input pipeline (train/prefetch.py)
        "input_wait_ms",
        "prefetch_depth",
        # checkpointing (train/checkpoint.py)
        "checkpoint_save_ms",
        # control plane (core/rpc.py, core/pod.py)
        "heartbeat_rtt_ms",
        "data_plane_init_ms",
        "driver_connect_ms",
        # serving engine + scheduler (serve/)
        "serve.ttft_ms",
        "serve.tokens_per_sec",
        "serve.queue_depth",
        "serve.active_slots",
        "serve.drain_ms",
        "serve.decode_retraces",
        "serve.prefill_retraces",
        # paged KV cache (serve/paging/, docs/serving.md "Paged KV cache")
        "serve.pages_free",  # allocatable pages left in the pool
        "serve.pages_shared",  # pages aliased by >1 request (prefix reuse)
        # KV page heat + fragmentation (serve/paging/allocator.py heat
        # stamps; docs/observability.md "Capacity")
        "serve.pages_hot",  # pages accessed within the hot generation window
        "serve.pages_warm",  # pages idle past hot but inside warm
        "serve.pages_cold",  # pages idle past the warm window (eviction candidates)
        "serve.fragmentation",  # free-pool frag ratio (0=one run, ->1 shattered)
        # prefix residency (serve/prefix.py residency_stats)
        "serve.prefix_resident_bytes",  # KV bytes pinned by resident prompts
        "serve.prefix_resident_count",  # resident prompts in the prefix index
        # host-DRAM KV tier (serve/tier/, docs/serving.md "Host-DRAM page tier")
        "tier.host_pages_free",  # unallocated host pool pages
        "tier.host_pages_total",  # host pool capacity in pages
        "tier.host_bytes",  # bytes held by resident host packs
        "tier.resident_packs",  # spilled KV packs resident in host DRAM
        # device memory ledger (telemetry/memtrack.py; per-account gauges
        # ride the mem.account. dynamic prefix)
        "mem.hbm_used",  # reported device bytes in use (sim on CPU)
        "mem.hbm_free",  # pool limit minus used
        "mem.headroom_pct",  # free/limit — the autoscaler's capacity signal
        "mem.unattributed",  # reported-used minus the account sum
        # serving fleet (serve/fleet/)
        "fleet.healthy_replicas",
        "fleet.breaker_open",  # circuit breakers currently open (gray replicas)
        "fleet.brownout_level",  # degradation ladder position (0=normal..3=shed)
        # capacity loop (serve/fleet/autoscale.py; docs/fleet.md "Autoscaling")
        "fleet.replicas",  # fleet size (non-dead replicas, any role)
        "fleet.draining",  # replicas mid-retirement (no dispatch, still polled)
        "fleet.at_capacity",  # 1 while scale-out pressure is pinned at max_replicas
        "serve.handoff_ms",  # prefill->decode KV handoff latency
        # autotuner (tune/)
        "tune.candidates",
        "tune.pruned_oom",
        "tune.best_step_time",
        # gradient overlap + ZeRO (parallel/overlap.py, train/trainer.py;
        # docs/distributed.md "Gradient overlap & ZeRO")
        "train.bucket_count",  # gradient-reduction buckets in the compiled step
        "train.comm_exposed_ms",  # comm time still on the critical path
        "train.comm_overlapped_ms",  # comm time hidden under backward
        # autopilot online controller (autopilot/controller.py)
        "autopilot.tick_ms",  # per-sample controller cost (≤2% budget)
        # elastic membership (resilience/membership.py, core/driver/distributed.py)
        "resilience.membership_epoch",  # current membership epoch
        "resilience.active_slices",  # slices currently in the data mesh
        "resilience.reshape_ms",  # epoch bump -> reshape barrier complete
        # alerting (telemetry/alerts.py)
        "alerts.firing",  # alerts currently firing at this scope
    }
)

# counters: monotonic totals
COUNTERS = frozenset(
    {
        "trials_done",
        "trials_errored",
        "checkpoint_fallback",
        "serve.prefix_hits",
        "serve.prefix_tokens_saved",
        "serve.preemptions",  # paged-pool preemptions (request requeued, not failed)
        "fleet.shed",
        "fleet.quarantined",
        "fleet.requeued",
        "fleet.routed",
        # overload robustness (docs/fleet.md "QoS classes", docs/resilience.md
        # "Gray failure & circuit breakers")
        "fleet.brownout_clamped",  # best-effort dispatches with max_new clamped
        "fleet.retry_deferred",  # requeues delayed by an exhausted retry budget
        "fleet.breaker_opened",  # breaker transitions into OPEN (incl. re-opens)
        "fleet.breaker_closed",  # half-open probes that verified recovery
        "fleet.scale_events",  # autoscaler decisions applied (up + down)
        # per-QoS-class scheduler accounting (serve/scheduler.py); the class
        # tail is the closed qos set, spelled out so the lint sees every name
        "serve.qos.admitted.premium",
        "serve.qos.admitted.standard",
        "serve.qos.admitted.best_effort",
        "serve.qos.preempted.premium",
        "serve.qos.preempted.standard",
        "serve.qos.preempted.best_effort",
        "serve.qos.quota_deferred.premium",
        "serve.qos.quota_deferred.standard",
        "serve.qos.quota_deferred.best_effort",
        "resilience.auto_resumes",
        "resilience.preempt_saves",
        "resilience.worker_deaths",
        "resilience.workers_quarantined",
        "resilience.trials_requeued",
        "resilience.trials_exhausted",
        "resilience.dist_restarts",
        # elastic membership (docs/resilience.md "Elastic membership")
        "resilience.slice_drops",  # slices that left the data mesh
        "resilience.slice_rejoins",  # dropped slices re-admitted
        "resilience.reshape_checkpoints",  # graceful-reshape convergence saves
        "resilience.ckpt_reshards",  # restores re-placed across mesh layouts
        "resilience.ckpt_zero_reshards",  # optimizer states converted across zero layouts
        "tune.cache_hits",
        "tune.cache_misses",
        "flightrec.dumps",  # stall watchdog dumps written (telemetry/flightrec.py)
        # series-only SLO attainment counters (telemetry/timeseries.py):
        # ingested into the time-series store from scheduler/router SLO
        # accounting, never emitted via tel.count — registered so alert
        # rules and metrics_query resolve them with units
        "serve.slo_ok",  # requests that met the TTFT SLO
        "serve.slo_miss",  # requests that missed the TTFT SLO
        # series-only headroom low-water tick counters (telemetry/memtrack.py):
        # the counter pair alert.hbm_headroom's multi-window burn reads
        "mem.headroom_ok",  # ledger ticks with headroom above the low-water mark
        "mem.headroom_miss",  # ledger ticks under it (capacity budget burning)
        "profcap.captures",  # alert-triggered profile captures written (telemetry/profcap.py)
        # host-DRAM KV tier (serve/tier/) + prefix-affinity routing
        # (serve/fleet/router.py; docs/fleet.md "Fleet-global KV")
        "tier.spills",  # streams spilled to the host tier (any kind)
        "tier.fills",  # host packs swapped back onto the device
        "tier.spilled_pages",  # KV pages copied device -> host
        "tier.filled_pages",  # KV pages copied host -> device
        "tier.prefix_spills",  # released prefixes captured as host packs
        "tier.prefix_fills",  # admissions served from a host prefix pack
        "tier.host_evictions",  # LRU packs dropped to make host room
        "tier.pressure_spills",  # spills forced by a low-headroom tick
        "tier.affinity_hits",  # routed to a replica holding the prefix
        "tier.affinity_misses",  # no holder available; routed affinity-blind
        # autopilot online controller (autopilot/controller.py)
        "autopilot.diagnoses",  # windows classified
        "autopilot.retunes",  # guarded moves committed
        "autopilot.rollbacks",  # guarded moves reverted on regression
    }
)

# histograms: fixed-log-bucket latency distributions (telemetry/histogram.py)
HISTOGRAMS = frozenset(
    {
        "serve.ttft_ms",  # submit -> first token
        "serve.tpot_ms",  # per-token decode time after the first
        "serve.queue_wait_ms",  # submit -> admission
        "serve.e2e_ms",  # submit -> terminal state
        "serve.drain_ms",  # async decode host drain
        "serve.handoff_ms",  # disaggregated prefill->decode handoff
        "tier.swap_in_ms",  # host pack fetch + device scatter on admit
        "tier.spill_ms",  # device gather + host pack write on spill
        "fleet.drain_ms",  # scale-in drain: dispatch stop -> replica retired
    }
)

# lifecycle events: trace-correlated milestones (telemetry/tracing.py)
EVENTS = frozenset(
    {
        # serving request lifecycle (scheduler-side)
        "req.queued",
        "req.admitted",
        "req.prefix_admitted",
        "req.first_token",
        "req.finished",
        "req.preempted",  # pages freed, requeued ahead of fresh arrivals
        "req.preempted_for_priority",  # victim lost its pages to a higher class
        # router-side hops (serve/fleet/router.py)
        "req.accepted",
        "req.dispatched",
        "req.requeued",
        "req.shed",
        "req.completed",
        # disaggregated prefill/decode (serve/fleet/prefill.py, docs/fleet.md)
        "req.prefilled",  # prompt ran on a prefill replica
        "req.handoff",  # KV pack accepted by a decode replica
        # training runs (train/trainer.py)
        "train.run_start",
        "train.run_end",
        # autopilot decisions (autopilot/controller.py, serve/scheduler.py):
        # the auditable telemetry→config loop — diagnosis verdicts, applied
        # moves, guarded commits, automatic rollbacks
        "autopilot.diagnosis",
        "autopilot.applied",
        "autopilot.committed",
        "autopilot.rollback",
        "autopilot.reconfigure_failed",
        # fleet autoscaler decision journal (serve/fleet/autoscale.py;
        # docs/fleet.md "Autoscaling") — the auditable capacity loop:
        # decisions, safe-event milestones, guarded commits, auto-reverts
        "fleet.scale.up",  # scale-out decided; spawn + warm started
        "fleet.scale.down",  # scale-in decided; victim drain started
        "fleet.scale.admitted",  # warmed replica entered probation dispatch
        "fleet.scale.retired",  # drained (or kill-fallback) replica removed
        "fleet.scale.committed",  # post-scale guard window held
        "fleet.scale.rollback",  # guard regressed; event auto-reverted
        "fleet.scale.guard_extended",  # regression explained by ongoing storm
        "fleet.scale.blocked",  # decision suppressed (at max / warm failed)
        # alert rule transitions (telemetry/alerts.py; the rule name rides
        # in the ``alert=`` attr and must exist in alerts.RULES — linted)
        "alert.firing",
        "alert.resolved",
    }
)

# f-string names whose literal head is one of these prefixes are legal
# (the tail is a bounded enum resolved at runtime: request terminal states,
# RPC verbs)
DYNAMIC_PREFIXES = (
    "serve.requests_",  # scheduler terminal-state counters
    "rpc_errors.",  # per-verb client failures (recorder.rpc)
    "rpc_frame_errors.",  # server frame hygiene (core/rpc.py)
    "train.comm_exposed_ms.",  # per-mesh-axis comm exposure (".data" ICI / ".slice" DCN)
    "serve.qos.",  # per-class tails resolved from the closed qos set
    "mem.account.",  # per-account ledger gauges (telemetry/memtrack.py)
)

BY_KIND = {
    "gauge": GAUGES,
    "count": COUNTERS,
    "histogram": HISTOGRAMS,
    "event": EVENTS,
}

ALL = GAUGES | COUNTERS | HISTOGRAMS | EVENTS

# ---------------------------------------------------------------- units
# Every registered name carries a unit so downstream consumers (monitor
# sparklines, tools/metrics_query.py, the docs signal table) can label and
# scale values without guessing. The lint fails on any registered name
# missing from UNITS or carrying an unknown unit.
VALID_UNITS = frozenset({"ms", "count", "bytes", "ratio", "per_s"})

# counters and events are dimensionally counts; histograms are all latency
# distributions in ms. Gauges are mixed, so each is mapped explicitly —
# adding a gauge means adding its unit here too.
GAUGE_UNITS = {
    "step_time_ms": "ms",
    "step_time_ms_mean": "ms",
    "compile_time_ms": "ms",
    "steps_per_sec": "per_s",
    "tokens_per_sec": "per_s",
    "mfu_est": "ratio",
    "metrics_lag": "count",
    "metrics_drain_ms": "ms",
    "resumed_step": "count",
    "input_wait_ms": "ms",
    "prefetch_depth": "count",
    "checkpoint_save_ms": "ms",
    "heartbeat_rtt_ms": "ms",
    "data_plane_init_ms": "ms",
    "driver_connect_ms": "ms",
    "serve.ttft_ms": "ms",
    "serve.tokens_per_sec": "per_s",
    "serve.queue_depth": "count",
    "serve.active_slots": "count",
    "serve.drain_ms": "ms",
    "serve.decode_retraces": "count",
    "serve.prefill_retraces": "count",
    "serve.pages_free": "count",
    "serve.pages_shared": "count",
    "serve.pages_hot": "count",
    "serve.pages_warm": "count",
    "serve.pages_cold": "count",
    "serve.fragmentation": "ratio",
    "serve.prefix_resident_bytes": "bytes",
    "serve.prefix_resident_count": "count",
    "tier.host_pages_free": "count",
    "tier.host_pages_total": "count",
    "tier.host_bytes": "bytes",
    "tier.resident_packs": "count",
    "mem.hbm_used": "bytes",
    "mem.hbm_free": "bytes",
    "mem.headroom_pct": "ratio",
    "mem.unattributed": "bytes",
    "fleet.healthy_replicas": "count",
    "fleet.breaker_open": "count",
    "fleet.brownout_level": "count",
    "fleet.replicas": "count",
    "fleet.draining": "count",
    "fleet.at_capacity": "count",
    "serve.handoff_ms": "ms",
    "tune.candidates": "count",
    "tune.pruned_oom": "count",
    "tune.best_step_time": "ms",
    "train.bucket_count": "count",
    "train.comm_exposed_ms": "ms",
    "train.comm_overlapped_ms": "ms",
    "autopilot.tick_ms": "ms",
    "resilience.membership_epoch": "count",
    "resilience.active_slices": "count",
    "resilience.reshape_ms": "ms",
    "alerts.firing": "count",
}

UNITS = {name: "count" for name in COUNTERS | EVENTS}
UNITS.update({name: "ms" for name in HISTOGRAMS})
UNITS.update(GAUGE_UNITS)
