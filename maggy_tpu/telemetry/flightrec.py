"""Stall watchdog + flight recorder: evidence when a worker stops moving.

A hung replica, a wedged RPC event loop, or a stalled train-step loop dies
silently today: the process is alive, heartbeats may even flow, but no
progress happens and nothing records *what it was doing when it stopped*.
This module fixes both halves:

* **Flight recorder.** Every :class:`~maggy_tpu.telemetry.recorder.Telemetry`
  tees its records (spans, gauges, lifecycle events) into a small bounded
  ring — the last ~512 things the worker did, always in memory, costing one
  ``deque.append`` per record. Nothing is written anywhere until a stall.
* **Stall watchdog.** Code that owns a progress loop *arms a mark*
  (``begin(name)``), then ``beat(name)`` every iteration and ``end(name)``
  on exit. One daemon thread (lazily started on the first ``begin``) scans
  the marks; a mark that is armed but has not beaten for ``stall_s``
  seconds triggers a **dump**: every live recorder's event ring, the mark
  table, and the stack of every thread in the process, written to
  ``<dump_dir>/flightrec_<ts>_<n>.json`` (``<exp_dir>/telemetry/`` when the
  worker telemetry sink configured it) and kept at :attr:`Watchdog.last_dump`.

Armed marks (instrumented in this PR): ``rpc.<verb>`` around every server
dispatch (covers the chaos ``rpc_stall`` seam — the injected stall holds the
event loop exactly like a wedged driver host), ``serve.loop`` around the
serving scheduler's engine loop, and ``train.step`` around ``Trainer.fit``'s
step loop. A mark dumps once per stall episode (re-armed by its next beat),
and dumps are capped per process.

Env knobs: ``MAGGY_TPU_FLIGHTREC=0`` disables the watchdog entirely (a
shared no-op stands in, so call sites stay unconditional);
``MAGGY_TPU_STALL_S`` sets the stall threshold (default 60 s — far above any
healthy beat cadence, low enough to catch a genuinely wedged loop).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

ENV_FLAG = "MAGGY_TPU_FLIGHTREC"
ENV_STALL = "MAGGY_TPU_STALL_S"
DEFAULT_STALL_S = 60.0
MAX_DUMPS = 16  # per-process cap so a flapping stall can't fill a disk


def enabled() -> bool:
    return os.environ.get(ENV_FLAG, "1").lower() not in ("0", "false", "off")


def default_stall_s() -> float:
    try:
        return float(os.environ[ENV_STALL])
    except (KeyError, ValueError):
        return DEFAULT_STALL_S


def thread_stacks() -> Dict[str, List[str]]:
    """Formatted stack of every live thread, keyed ``name(ident)``."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, 'thread')}({ident})"
        out[key] = traceback.format_stack(frame)
    return out


class Watchdog:
    """Progress-mark table + scanner thread + dump writer."""

    def __init__(
        self,
        stall_s: Optional[float] = None,
        interval_s: Optional[float] = None,
        dump_dir: Optional[str] = None,
        env=None,
    ):
        self.stall_s = default_stall_s() if stall_s is None else float(stall_s)
        self.interval_s = (
            max(0.05, min(1.0, self.stall_s / 4))
            if interval_s is None
            else float(interval_s)
        )
        self.dump_dir = dump_dir
        self._env = env
        # name -> {"beat": ts, "busy": int, "detail": ..., "dumped": bool}
        self._marks: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.last_dump: Optional[Dict[str, Any]] = None
        self.dumps: List[str] = []  # written file paths, in order

    # ----------------------------------------------------------- mark surface

    def configure(
        self, dump_dir: Optional[str] = None, env=None, stall_s: Optional[float] = None
    ) -> None:
        """Late wiring (the telemetry sink knows the dump dir, not us)."""
        if dump_dir is not None:
            self.dump_dir = str(dump_dir)
        if env is not None:
            self._env = env
        if stall_s is not None:
            self.stall_s = float(stall_s)
            self.interval_s = max(0.05, min(1.0, self.stall_s / 4))

    def begin(self, name: str, detail: Any = None) -> None:
        """Arm ``name``: progress is now expected until :meth:`end`."""
        with self._lock:
            m = self._marks.setdefault(
                name, {"beat": 0.0, "busy": 0, "detail": None, "dumped": False}
            )
            m["busy"] += 1
            m["beat"] = time.time()
            m["detail"] = detail
            m["dumped"] = False
        self._ensure_thread()

    def beat(self, name: str, detail: Any = None) -> None:
        """Record one unit of progress on an armed mark."""
        with self._lock:
            m = self._marks.get(name)
            if m is None:
                return
            m["beat"] = time.time()
            if detail is not None:
                m["detail"] = detail
            m["dumped"] = False

    def end(self, name: str) -> None:
        """Disarm one :meth:`begin` (nested begins stay armed until paired)."""
        with self._lock:
            m = self._marks.get(name)
            if m is None:
                return
            m["busy"] = max(0, m["busy"] - 1)
            m["beat"] = time.time()

    def marks(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._marks.items()}

    # ---------------------------------------------------------------- scanner

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="maggy-watchdog", daemon=True
            )
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            now = time.time()
            stalled: List[str] = []
            with self._lock:
                for name, m in self._marks.items():
                    if (
                        m["busy"] > 0
                        and not m["dumped"]
                        and now - m["beat"] > self.stall_s
                    ):
                        m["dumped"] = True  # once per stall episode
                        stalled.append(name)
            for name in stalled:
                self.dump(f"stall: no progress on {name!r} for >{self.stall_s}s")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s + 1)
            self._thread = None

    # ------------------------------------------------------------------- dump

    def dump(self, reason: str) -> Optional[str]:
        """Write a flight-recorder dump; returns the path (None when the
        per-process cap is hit or no dump dir is configured — the payload is
        still kept at :attr:`last_dump` for in-process consumers)."""
        from maggy_tpu.telemetry import recorder as rec_mod

        payload: Dict[str, Any] = {
            "kind": "flightrec",
            "ts": time.time(),
            "reason": reason,
            "pid": os.getpid(),
            "marks": self.marks(),
            "events": rec_mod.flight_snapshots(),
            "threads": thread_stacks(),
        }
        # embed the firing alert set and the recent samples of the metrics
        # those alerts name, so a stall dump is self-describing
        try:
            from maggy_tpu.telemetry import alerts as alerts_mod

            payload["alerts"] = alerts_mod.active_alerts()
            payload["alert_series"] = alerts_mod.alerted_series_tails()
        except Exception:
            payload["alerts"] = []
            payload["alert_series"] = {}
        self.last_dump = payload
        rec_mod.get().count("flightrec.dumps")
        if self.dump_dir is None or len(self.dumps) >= MAX_DUMPS:
            return None
        name = f"flightrec_{int(payload['ts'])}_{len(self.dumps)}.json"
        path = os.path.join(str(self.dump_dir), name)
        text = json.dumps(payload, separators=(",", ":"), default=str)
        try:
            if self._env is not None:
                self._env.dump(text, path)
            else:
                os.makedirs(str(self.dump_dir), exist_ok=True)
                with open(path, "w", encoding="utf-8") as f:
                    f.write(text)
        except OSError:
            return None
        self.dumps.append(path)
        return path


class NullWatchdog:
    """No-op stand-in when ``MAGGY_TPU_FLIGHTREC=0``."""

    last_dump = None
    dumps: List[str] = []

    def configure(self, *a, **kw) -> None:
        pass

    def begin(self, name: str, detail: Any = None) -> None:
        pass

    def beat(self, name: str, detail: Any = None) -> None:
        pass

    def end(self, name: str) -> None:
        pass

    def marks(self) -> Dict[str, Any]:
        return {}

    def dump(self, reason: str) -> None:
        return None

    def stop(self) -> None:
        pass


NULL = NullWatchdog()

_lock = threading.Lock()
_active: Optional[Watchdog] = None


def get():
    """The process-wide watchdog (lazily built; :data:`NULL` when disabled)."""
    global _active
    if not enabled():
        return NULL
    if _active is None:
        with _lock:
            if _active is None:
                _active = Watchdog()
    return _active


def install(wd: Optional[Watchdog]) -> None:
    """Install a specific watchdog (tests); None restores lazy default."""
    global _active
    with _lock:
        prev, _active = _active, wd
    if prev is not None and prev is not wd:
        prev.stop()


def reset() -> None:
    install(None)
