"""Request-scoped distributed tracing: trace ids and their propagation.

A *trace* follows one unit of work across every worker it touches — a
serving request hopping client → router → replica → engine (→ requeue to a
survivor), or one ``Trainer.fit`` run. The machinery is deliberately tiny:

* :func:`new_trace_id` mints an opaque id (once, at the edge where the work
  enters the system: ``ServeClient.submit``, the router's SUBMIT handler
  for traceless clients, ``Trainer.fit`` per run).
* A thread-local *current trace* (:func:`current` / :func:`scope`) makes the
  id ambient within a worker, so instrumentation deep in the stack — the
  recorder's spans, gauges, and lifecycle events — tags records without any
  plumbing through intermediate signatures.
* The RPC layer propagates it across processes: ``rpc.Client._request``
  attaches the ambient id as a ``trace`` field on every outgoing frame, and
  ``rpc.Server._dispatch`` re-installs an incoming frame's id around the
  handler. One request's records therefore share one trace id across every
  worker JSONL, and the Chrome-trace exporter folds them into a single
  per-request lane (docs/observability.md).

Everything here is allocation-free on the hot path (one thread-local read);
there is no sampling — traces are cheap enough to always be on, and
``MAGGY_TPU_TELEMETRY=0`` already disables the recording they feed.
"""

from __future__ import annotations

import contextlib
import secrets
import threading
from typing import Iterator, Optional

_tls = threading.local()


def new_trace_id() -> str:
    """Mint a fresh trace id (opaque hex, unique per process lifetime)."""
    return secrets.token_hex(8)


def current() -> Optional[str]:
    """This thread's ambient trace id, or None outside any trace scope."""
    return getattr(_tls, "trace", None)


def set_current(trace: Optional[str]) -> None:
    """Install ``trace`` as this thread's ambient id (None to clear).
    Prefer :func:`scope` — it restores the previous id on exit."""
    _tls.trace = trace


@contextlib.contextmanager
def scope(trace: Optional[str]) -> Iterator[Optional[str]]:
    """Make ``trace`` ambient for the block (restores the prior id after).
    ``scope(None)`` deliberately masks any outer trace — an RPC handler
    serving a traceless frame must not leak the previous frame's id."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace
    try:
        yield trace
    finally:
        _tls.trace = prev


def ensure() -> str:
    """The ambient trace id, minting a fresh one if none is in scope.
    Does NOT install the minted id — pair with :func:`scope` for that."""
    return current() or new_trace_id()
