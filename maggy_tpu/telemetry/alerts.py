"""Declarative alert rules evaluated over the time-series store.

The rule registry is checked in (like ``telemetry/metrics.py``): every alert
the system can fire is named here, with its condition, severity, and scope.
``tools/check_telemetry_names.py`` loads this module by file path and
validates the registry (unique ``alert.``-prefixed names, known kinds and
severities, referenced metrics registered) — so keep it stdlib-plus-siblings
only.

Three rule kinds:

- ``threshold`` — fire when a gauge series crosses ``op threshold`` and stays
  there for ``for_s`` seconds (for-duration suppresses one-tick blips).
- ``burn_rate`` — multi-window SLO error-budget burn, the Google-SRE shape:
  error rate over a *long* and a *short* window, each divided by the budget
  ``(1 - objective)``; fire only when **both** exceed their factor. The long
  window keeps it significant, the short one makes it resolve fast. The
  error rate comes from a cumulative ok/miss counter pair (TTFT attainment:
  the scheduler's ``serve.slo_ok``/``serve.slo_miss``) or from a latency
  histogram series plus an SLO bound (TPOT attainment).
- ``sentinel`` — fired directly by :class:`RecompileSentinel`, not evaluated
  from a series; registered here so the name, severity, and docs table stay
  in one place.

Transitions emit ``alert.firing`` / ``alert.resolved`` events through the
recorder; a transition to firing on a ``critical`` rule triggers a
flight-recorder dump, and every dump embeds the currently-firing set plus
the recent samples of the metrics those alerts name (see
``telemetry/flightrec.py``).
"""

from __future__ import annotations

import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

ALERT_FIRING = "alert.firing"
ALERT_RESOLVED = "alert.resolved"

KINDS = ("threshold", "burn_rate", "sentinel")
SEVERITIES = ("warning", "critical")
SCOPES = ("worker", "fleet", "any")


@dataclass(frozen=True)
class Rule:
    """One checked-in alert rule. ``name`` must be ``alert.<slug>``."""

    name: str
    summary: str  # one line, shown on the monitor ALERTS line and in dumps
    kind: str = "threshold"
    severity: str = "warning"
    scope: str = "any"  # worker / fleet / any (evaluated at both)
    # threshold rules
    metric: Optional[str] = None  # gauge series name
    op: str = ">"
    threshold: float = 0.0
    for_s: float = 0.0  # condition must hold this long before firing
    # burn_rate rules — counter-pair source ...
    ok_metric: Optional[str] = None
    miss_metric: Optional[str] = None
    # ... or histogram source (metric = hist series name + slo_ms bound)
    slo_ms: Optional[float] = None
    objective: float = 0.99  # target attainment; budget = 1 - objective
    # ((window_s, burn_factor), ...) — all windows must exceed their factor
    windows: Tuple[Tuple[float, float], ...] = ((30.0, 2.0), (5.0, 2.0))

    def metrics(self) -> Tuple[str, ...]:
        """Series names this rule reads (flight-recorder dumps embed their
        recent samples)."""
        out = []
        for m in (self.metric, self.ok_metric, self.miss_metric):
            if m:
                out.append(m)
        return tuple(out)


# The checked-in registry. Adding an alert = add a Rule here (the lint
# validates it and the docs table in docs/observability.md mirrors it).
RULES: Tuple[Rule, ...] = (
    Rule(
        name="alert.queue_depth_high",
        summary="admission queue persistently deep; decode not keeping up",
        kind="threshold",
        metric="serve.queue_depth",
        op=">",
        threshold=64.0,
        for_s=3.0,
        severity="warning",
        scope="worker",
    ),
    Rule(
        name="alert.pages_exhausted",
        summary="paged-KV pool out of free pages; preemption imminent",
        kind="threshold",
        metric="serve.pages_free",
        op="<",
        threshold=1.0,
        for_s=3.0,
        severity="warning",
        scope="worker",
    ),
    Rule(
        name="alert.fleet_no_healthy_replicas",
        summary="router sees zero healthy replicas",
        kind="threshold",
        metric="fleet.healthy_replicas",
        op="<",
        threshold=1.0,
        for_s=1.0,
        severity="critical",
        scope="fleet",
    ),
    Rule(
        name="alert.fleet_at_capacity",
        summary="scale-out pressure pinned at max_replicas; brownout is the only relief",
        kind="threshold",
        metric="fleet.at_capacity",
        op=">",
        threshold=0.0,
        for_s=5.0,
        severity="warning",
        scope="fleet",
    ),
    Rule(
        name="alert.ttft_slo_burn",
        summary="TTFT SLO error budget burning in short and long windows",
        kind="burn_rate",
        ok_metric="serve.slo_ok",
        miss_metric="serve.slo_miss",
        objective=0.99,
        windows=((30.0, 2.0), (5.0, 2.0)),
        severity="critical",
        scope="any",
    ),
    Rule(
        name="alert.tpot_slo_burn",
        summary="per-token decode latency burning its attainment budget",
        kind="burn_rate",
        metric="serve.tpot_ms",
        slo_ms=200.0,
        objective=0.99,
        windows=((30.0, 3.0), (5.0, 3.0)),
        severity="warning",
        scope="any",
    ),
    Rule(
        name="alert.hbm_headroom",
        summary="HBM headroom below the low-water mark; capacity budget burning",
        kind="burn_rate",
        # counter pair synthesized by the memory ledger's reconcile tick
        # (telemetry/memtrack.py): a tick with headroom under the low-water
        # mark counts as a miss — the multi-window burn shape then gives
        # sustained pressure a fast page and a one-tick dip nothing
        ok_metric="mem.headroom_ok",
        miss_metric="mem.headroom_miss",
        objective=0.90,
        windows=((30.0, 2.0), (5.0, 2.0)),
        severity="critical",
        scope="worker",
    ),
    Rule(
        name="alert.fragmentation",
        summary="paged-KV free pool fragmented; large admissions may thrash",
        kind="threshold",
        metric="serve.fragmentation",
        op=">",
        threshold=0.5,
        for_s=3.0,
        severity="warning",
        scope="worker",
    ),
    Rule(
        name="alert.brownout",
        summary="fleet degrading best-effort traffic (brownout ladder > normal)",
        kind="threshold",
        metric="fleet.brownout_level",
        op=">",
        threshold=0.0,
        for_s=0.0,
        severity="warning",
        scope="fleet",
    ),
    Rule(
        name="alert.recompile",
        summary="jitted program retraced outside a reconfigure window",
        kind="sentinel",
        severity="critical",
        scope="any",
    ),
)

BY_NAME: Dict[str, Rule] = {r.name: r for r in RULES}

# live evaluators/sentinels, so flight-recorder dumps can embed the firing
# set without plumbing references through every call site
_EVALUATORS: "weakref.WeakSet" = weakref.WeakSet()


def active_alerts() -> List[Dict[str, Any]]:
    """Currently-firing alerts across every live evaluator in the process."""
    out: List[Dict[str, Any]] = []
    for ev in list(_EVALUATORS):
        try:
            out.extend(ev.firing())
        except Exception:
            continue
    return out


def alerted_series_tails(n: int = 32) -> Dict[str, List]:
    """Last ``n`` samples of every series named by a firing alert, keyed
    ``<scope>/<metric>`` — what makes a stall dump self-describing."""
    out: Dict[str, List] = {}
    for ev in list(_EVALUATORS):
        try:
            store = ev.store
            for a in ev.firing():
                rule = BY_NAME.get(a.get("alert", ""))
                if rule is None or store is None:
                    continue
                for m in rule.metrics():
                    s = store.get(m)
                    if s is not None:
                        out[f"{ev.scope}/{m}"] = [[ts, v] for ts, v in s.tail(n)]
        except Exception:
            continue
    return out


class AlertEvaluator:
    """Evaluates the registry against one :class:`SeriesStore` at one scope.

    Owned by whatever owns the store (scheduler loop, router pump) and
    ticked from that thread; ``firing()`` is safe to call from RPC threads
    (it copies under the GIL)."""

    def __init__(
        self,
        store,
        recorder=None,
        scope: str = "worker",
        rules: Optional[Tuple[Rule, ...]] = None,
        stale_s: float = 30.0,
    ):
        self.store = store
        self.scope = scope
        self._tel = recorder
        self._stale_s = stale_s
        self._rules = tuple(
            r
            for r in (rules if rules is not None else RULES)
            if r.kind != "sentinel" and r.scope in ("any", scope)
        )
        self._pending: Dict[str, float] = {}  # rule -> condition-true since  # race: ok — single-writer (owner tick thread); never read off-thread
        self._firing: Dict[str, Dict[str, Any]] = {}  # race: ok — single-writer (owner tick); firing() copies dicts under the GIL
        _EVALUATORS.add(self)

    # ------------------------------------------------------------------- read

    def firing(self) -> List[Dict[str, Any]]:
        return [dict(v) for v in list(self._firing.values())]

    # ------------------------------------------------------------------- tick

    def evaluate(self, now: Optional[float] = None, watchdog=None) -> List[Dict[str, Any]]:  # thread-entry — ticked from the owning scheduler/router thread
        """One evaluation pass; returns the transitions (fired/resolved)."""
        ts = now if now is not None else time.time()
        transitions: List[Dict[str, Any]] = []
        for rule in self._rules:
            if rule.kind == "threshold":
                cond, value = self._eval_threshold(rule, ts)
            else:
                cond, value = self._eval_burn(rule, ts)
            transitions.extend(self._transition(rule, cond, value, ts, watchdog))
        return transitions

    # ----------------------------------------------------------- rule kinds

    def _eval_threshold(self, rule: Rule, ts: float) -> Tuple[bool, Optional[float]]:
        s = self.store.get(rule.metric) if rule.metric else None
        latest = s.latest() if s is not None else None
        if latest is None or ts - latest[0] > self._stale_s:
            return False, None
        value = float(latest[1])
        cond = value > rule.threshold if rule.op == ">" else value < rule.threshold
        if not cond:
            self._pending.pop(rule.name, None)
            return False, value
        since = self._pending.setdefault(rule.name, ts)
        return ts - since >= rule.for_s, value

    def _eval_burn(self, rule: Rule, ts: float) -> Tuple[bool, Optional[float]]:
        """Error-budget burn in every window must exceed its factor."""
        budget = max(1e-9, 1.0 - rule.objective)
        worst: Optional[float] = None
        for window_s, factor in rule.windows:
            err = self._error_rate(rule, window_s, ts)
            if err is None:
                return False, worst
            burn = err / budget
            worst = burn if worst is None else max(worst, burn)
            if burn <= factor:
                return False, worst
        return True, worst

    def _error_rate(self, rule: Rule, window_s: float, ts: float) -> Optional[float]:
        if rule.ok_metric and rule.miss_metric:
            ok_s = self.store.get(rule.ok_metric)
            miss_s = self.store.get(rule.miss_metric)
            if ok_s is None or miss_s is None:
                return None
            ok = ok_s.delta(window_s, ts)
            miss = miss_s.delta(window_s, ts)
            if ok is None or miss is None or ok + miss <= 0:
                return None
            return miss / (ok + miss)
        if rule.metric and rule.slo_ms is not None:
            s = self.store.get(rule.metric)
            if s is None:
                return None
            att = s.attainment(rule.slo_ms, window_s, ts)
            return None if att is None else 1.0 - att
        return None

    # ------------------------------------------------------------ transitions

    def _transition(
        self, rule: Rule, cond: bool, value: Optional[float], ts: float, watchdog
    ) -> List[Dict[str, Any]]:
        firing = rule.name in self._firing
        if cond and not firing:
            rec = {
                "alert": rule.name,
                "severity": rule.severity,
                "scope": self.scope,
                "since": round(ts, 3),
                "value": None if value is None else round(value, 4),
                "summary": rule.summary,
            }
            self._firing[rule.name] = rec
            self._emit(ALERT_FIRING, rule, value)
            if rule.severity == "critical":
                self._dump(rule, watchdog)
            return [dict(rec, event=ALERT_FIRING)]
        if not cond and firing:
            rec = self._firing.pop(rule.name)
            self._pending.pop(rule.name, None)
            self._emit(ALERT_RESOLVED, rule, value)
            return [dict(rec, event=ALERT_RESOLVED)]
        if cond and firing and value is not None:
            self._firing[rule.name]["value"] = round(value, 4)
        return []

    def _emit(self, event: str, rule: Rule, value: Optional[float]) -> None:
        tel = self._tel
        if tel is None:
            return
        try:
            tel.event(
                event,
                alert=rule.name,
                severity=rule.severity,
                scope=self.scope,
                value=None if value is None else round(value, 4),
            )
        except Exception:  # noqa: BLE001 - alerting must never kill the loop
            pass

    def _dump(self, rule: Rule, watchdog) -> None:
        try:
            if watchdog is None:
                from . import flightrec

                watchdog = flightrec.get()
            watchdog.dump(f"alert:{rule.name}")
        except Exception:  # noqa: BLE001 - a failed dump must not kill serving
            pass


class RecompileSentinel:
    """Turns the "compiles ONCE" test invariants into a production guardrail.

    Feed it the compile counts per jitted program (engine
    ``compile_counts``, trainer ``compile_counts``) each tick; every count
    becomes a ``compile.<program>`` series, and an *unexpected* increase on
    a steady program fires ``alert.recompile``. Expected recompiles — the
    first warm compile, and anything inside an :meth:`expect` window
    (reconfigure, explicit step-function invalidation) — re-baseline
    silently. Bucketed programs (prefill ladders) are tracked as series but
    never alerted: their compile ladder is by design.
    """

    RULE = BY_NAME["alert.recompile"]
    HOLD_S = 30.0  # how long a tripped sentinel stays on the ALERTS line

    def __init__(self, store, recorder=None, scope: str = "worker", steady=("decode", "admit")):
        self.store = store
        self.scope = scope
        self._tel = recorder
        self._steady = tuple(steady)
        self._baseline: Dict[str, int] = {}  # race: ok — single-writer (owner tick thread); GIL-atomic dict stores
        self._expected: set = set()  # race: ok — expect() runs on the owner thread before its own tick observes the counts
        self._tripped: Dict[str, float] = {}  # program -> fired at  # race: ok — single-writer tick; firing() iterates a list() copy
        _EVALUATORS.add(self)

    def expect(self, *programs: str) -> None:
        """Mark the next compile-count increase as legitimate (call before
        ``reconfigure`` or a deliberate step rebuild). No args = all steady
        programs."""
        self._expected.update(programs or self._steady)

    def firing(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        now = time.time() if now is None else now
        out = []
        for prog, ts in list(self._tripped.items()):
            if now - ts > self.HOLD_S:
                del self._tripped[prog]
                self._emit(ALERT_RESOLVED, prog, self._baseline.get(prog, 0))
                continue
            out.append(
                {
                    "alert": self.RULE.name,
                    "severity": self.RULE.severity,
                    "scope": self.scope,
                    "since": round(ts, 3),
                    "value": float(self._baseline.get(prog, 0)),
                    "summary": f"{prog}: {self.RULE.summary}",
                    "program": prog,
                }
            )
        return out

    def observe(  # thread-entry — ticked from the owning scheduler/router thread
        self, counts: Dict[str, int], now: Optional[float] = None, watchdog=None
    ) -> List[str]:
        """Record one tick of compile counts; returns programs that tripped."""
        ts = now if now is not None else time.time()
        tripped: List[str] = []
        for prog, c in (counts or {}).items():
            c = int(c)
            if self.store is not None:
                self.store.series(f"compile.{prog}", "counter").append(ts, float(c))
            base = self._baseline.get(prog)
            if base is None or c <= base:
                if base is None:
                    self._baseline[prog] = c
                continue
            self._baseline[prog] = c
            if prog in self._expected:
                self._expected.discard(prog)
                continue
            if prog not in self._steady or base == 0:
                continue  # bucketed ladder or the warm first compile
            self._tripped[prog] = ts
            tripped.append(prog)
            self._emit(ALERT_FIRING, prog, c)
            self._dump(prog, watchdog)
        return tripped

    def _emit(self, event: str, prog: str, count: int) -> None:
        tel = self._tel
        if tel is None:
            return
        try:
            tel.event(
                event,
                alert=self.RULE.name,
                severity=self.RULE.severity,
                scope=self.scope,
                program=prog,
                count=int(count),
            )
        except Exception:  # noqa: BLE001 - alerting must never kill the loop
            pass

    def _dump(self, prog: str, watchdog) -> None:
        try:
            if watchdog is None:
                from . import flightrec

                watchdog = flightrec.get()
            watchdog.dump(f"alert:{self.RULE.name}:{prog}")
        except Exception:  # noqa: BLE001 - a failed dump must not kill serving
            pass
