"""Per-worker device memory ledger: named accounts reconciled against HBM.

The fleet can see latency (tracing, time-series, burn-rate alerts) but not
*capacity*: nothing says how much HBM is spoken for, by what, or how much
headroom a replica has before the next admission preempts. This module is
that measurement layer — the substrate ROADMAP's autoscaler (cost-normalized
scaling needs headroom) and KV-tiering (eviction needs occupancy) items
consume as-is.

Mechanics: allocation sites register **named accounts** — ``params``,
``optimizer`` (ZeRO shards), ``kv_pages``, ``prefetch``, ``workspace`` —
each a byte figure the owner computes from its own arrays (``Trainer`` for
params/optimizer, ``Engine`` for the KV page pool, ``DevicePrefetcher`` for
its staging queue). A 1 Hz :meth:`MemoryLedger.tick` from the owning metrics
loop reconciles the account sum against what the runtime actually reports
(``jax.local_devices()[*].memory_stats()``), and exports:

* ``mem.hbm_used`` / ``mem.hbm_free`` / ``mem.headroom_pct`` gauges,
* one ``mem.account.<name>`` gauge per account,
* ``mem.unattributed`` — reported-used minus the account sum (a growing
  value here means an allocation site forgot to register),
* cumulative ``mem.headroom_ok`` / ``mem.headroom_miss`` counters — the
  pair the ``alert.hbm_headroom`` multi-window burn rule reads: a tick with
  headroom under the low-water mark is a miss.

**CPU-sim fallback.** On hosts whose devices expose no ``memory_stats``
(the CPU backend tier-1 runs on), reconciliation stays fully exercised
against a deterministic simulation: reported-used is the account sum plus a
fixed :data:`SIM_UNATTRIBUTED_FRAC` runtime overhead, against a pool of
:attr:`MemoryLedger.sim_limit_bytes` (settable; defaults to 4x used so the
sim reports healthy headroom). Tests assert the account sum lands within
10% of reported-used on this path — the same contract the device path is
expected to hold.

Reconciliation must *never* crash the metrics loop: every device probe is
wrapped, and a mismatch is a gauge (``mem.unattributed``), not an error.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from maggy_tpu.core import lockdebug

# CPU-sim runtime overhead: the deterministic stand-in for what a real
# runtime allocates beyond the registered accounts (XLA workspace, runtime
# scratch). 5% keeps the account sum within the 10% reconciliation contract.
SIM_UNATTRIBUTED_FRAC = 0.05

# headroom below this fraction of the pool counts the tick as a miss for
# the alert.hbm_headroom burn rule
DEFAULT_LOW_HEADROOM_PCT = 0.10


def device_memory() -> Optional[Tuple[int, int]]:
    """``(bytes_in_use, bytes_limit)`` summed over local devices, or None
    when no device reports memory stats (CPU backend, or jax absent)."""
    try:
        import jax

        used = limit = 0
        found = False
        for dev in jax.local_devices():
            stats = getattr(dev, "memory_stats", lambda: None)()
            if not stats:
                continue
            b_used = stats.get("bytes_in_use")
            b_limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
            if b_used is None or not b_limit:
                continue
            used += int(b_used)
            limit += int(b_limit)
            found = True
        if found and limit > 0:
            return used, limit
    except Exception:  # noqa: BLE001 - a probe failure must not kill the tick
        pass
    return None


class MemoryLedger:
    """Named byte accounts + reconciliation against reported device memory.

    Registration (``register``/``adjust``/``unregister``) happens from
    whatever thread owns the allocation (trainer setup, engine admit,
    prefetcher construction); :meth:`tick` runs on the owner's metrics
    thread — so the account table is lock-guarded.
    """

    def __init__(self, low_headroom_pct: float = DEFAULT_LOW_HEADROOM_PCT):
        self._lock = lockdebug.lock("memtrack._lock")
        self._accounts: Dict[str, int] = {}  # guarded-by: _lock
        self.low_headroom_pct = float(low_headroom_pct)
        # cumulative low-water tick counters (the burn-rule pair); written
        # only by the tick thread, read via snapshots
        self._headroom_ok = 0  # guarded-by: _lock
        self._headroom_miss = 0  # guarded-by: _lock
        # CPU-sim pool size; None = 4x reported-used (healthy headroom).
        # Pressure tests shrink this to drive headroom under the low-water
        # mark deterministically.
        self.sim_limit_bytes: Optional[int] = None

    # -------------------------------------------------------------- accounts

    def register(self, name: str, nbytes: int) -> None:
        """Set account ``name`` to ``nbytes`` (idempotent — re-registering
        an account replaces its figure; allocation sites call this on every
        (re)build so a reconfigure never double-counts)."""
        with self._lock:
            self._accounts[str(name)] = max(0, int(nbytes))

    def adjust(self, name: str, delta: int) -> None:
        """Add ``delta`` bytes to an account (clamped at zero)."""
        with self._lock:
            cur = self._accounts.get(str(name), 0)
            self._accounts[str(name)] = max(0, cur + int(delta))

    def unregister(self, name: str) -> None:
        with self._lock:
            self._accounts.pop(str(name), None)

    def accounts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._accounts)

    def accounted_bytes(self) -> int:
        with self._lock:
            return sum(self._accounts.values())

    # ----------------------------------------------------------- reconcile

    def reconcile(self) -> Dict[str, Any]:
        """One reconciliation pass: account sum vs reported device memory
        (or the deterministic CPU-sim). Pure read — no counters move."""
        accounted = self.accounted_bytes()
        reported = device_memory()
        if reported is not None:
            used, limit = reported
            source = "device"
        else:
            used = int(accounted * (1.0 + SIM_UNATTRIBUTED_FRAC))
            limit = self.sim_limit_bytes
            if limit is None:
                limit = max(1, used) * 4
            source = "sim"
        limit = max(int(limit), 1)
        used = min(int(used), limit)
        free = limit - used
        return {
            "source": source,
            "hbm_used": used,
            "hbm_free": free,
            "hbm_limit": limit,
            "headroom_pct": round(free / limit, 4),
            "accounted": accounted,
            "unattributed": max(0, used - accounted),
            "accounts": self.accounts(),
        }

    def tick(self, store=None, telemetry=None, now: Optional[float] = None) -> Dict[str, Any]:  # thread-entry — ticked from the owning scheduler/trainer metrics loop
        """Reconcile and export: gauges into the time-series ``store`` and
        the ``telemetry`` recorder, plus the cumulative headroom ok/miss
        counter pair the ``alert.hbm_headroom`` burn rule reads. Never
        raises — capacity observability must not sink the loop it rides."""
        try:
            rec = self.reconcile()
        except Exception:  # noqa: BLE001 - reconcile must never kill the tick
            return {}
        with self._lock:
            if rec["headroom_pct"] < self.low_headroom_pct:
                self._headroom_miss += 1
            else:
                self._headroom_ok += 1
            ok, miss = self._headroom_ok, self._headroom_miss
        rec["headroom_ok"] = ok
        rec["headroom_miss"] = miss
        try:
            if telemetry is not None:
                telemetry.gauge("mem.hbm_used", float(rec["hbm_used"]))
                telemetry.gauge("mem.hbm_free", float(rec["hbm_free"]))
                telemetry.gauge("mem.headroom_pct", rec["headroom_pct"])
                telemetry.gauge("mem.unattributed", float(rec["unattributed"]))
            if store is not None and now is not None:
                gauges = {
                    "mem.hbm_used": float(rec["hbm_used"]),
                    "mem.hbm_free": float(rec["hbm_free"]),
                    "mem.headroom_pct": rec["headroom_pct"],
                    "mem.unattributed": float(rec["unattributed"]),
                }
                for name, nbytes in rec["accounts"].items():
                    gauges[f"mem.account.{name}"] = float(nbytes)
                store.ingest(
                    now,
                    gauges=gauges,
                    counters={"mem.headroom_ok": ok, "mem.headroom_miss": miss},
                )
        except Exception:  # noqa: BLE001 - export must never kill the tick
            pass
        return rec

    def snapshot(self) -> Dict[str, Any]:
        """SSTATS-ready view (no counter movement)."""
        rec = self.reconcile()
        with self._lock:
            rec["headroom_ok"] = self._headroom_ok
            rec["headroom_miss"] = self._headroom_miss
        return rec


def array_bytes(tree: Any) -> int:
    """Total bytes of every array-like leaf in a (possibly nested) pytree —
    the helper allocation sites use to size an account. Works without jax
    (plain dicts/lists of numpy arrays) so tests stay backend-free."""
    total = 0
    try:
        import jax

        for leaf in jax.tree_util.tree_leaves(tree):
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes is not None:
                total += int(nbytes)
        return total
    except Exception:  # noqa: BLE001 - fall through to the stdlib walk
        pass

    def walk(node) -> int:
        nbytes = getattr(node, "nbytes", None)
        if nbytes is not None:
            return int(nbytes)
        if isinstance(node, dict):
            return sum(walk(v) for v in node.values())
        if isinstance(node, (list, tuple)):
            return sum(walk(v) for v in node)
        return 0

    return walk(tree)
