"""Telemetry exporters: merged Chrome trace + TensorBoard scalar mirror.

``export_chrome_trace`` folds every worker's JSONL under
``<exp_dir>/telemetry/`` into one Perfetto-loadable ``trace.json``: spans
become complete (``ph="X"``) events and gauges become counter (``ph="C"``)
events, all on the shared wall-clock microsecond base the recorder stamps, so
spans from different workers/hosts interleave correctly on one timeline.

``mirror_to_tensorboard`` replays each worker's gauge series through the
existing :mod:`maggy_tpu.tensorboard` seam (``events.jsonl`` always, real TF
event files when the tensorboard package is importable).
"""

from __future__ import annotations

import json
import posixpath
from typing import Any, Dict, List, Optional

from maggy_tpu.telemetry.sink import telemetry_dir


def _worker_pid(worker: Any, assigned: Dict[str, int]) -> int:
    """Chrome-trace pid for a worker id: numeric ids map directly; named
    workers (driver, standalone) get stable slots from 1000 up."""
    s = str(worker)
    if s.lstrip("-").isdigit():
        return int(s)
    if s not in assigned:
        assigned[s] = 1000 + len(assigned)
    return assigned[s]


def load_records(env, exp_dir: str) -> Dict[str, List[Dict[str, Any]]]:
    """All telemetry JSONL records under ``exp_dir``, keyed by file stem.
    Unparseable lines are skipped — a crashed worker may leave a torn tail."""
    tdir = telemetry_dir(exp_dir)
    out: Dict[str, List[Dict[str, Any]]] = {}
    try:
        names = [n for n in env.listdir(tdir) if n.endswith(".jsonl")]
    except OSError:
        return out
    for name in names:
        records = []
        try:
            with env.open_file(posixpath.join(tdir, name), "r") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
        if records:
            out[name[: -len(".jsonl")]] = records
    return out


def export_chrome_trace(env, exp_dir: str, out_name: str = "trace.json") -> Optional[str]:
    """Merge all worker JSONLs into ``<exp_dir>/telemetry/trace.json``.
    Returns the written path, or None when there is nothing to export."""
    by_worker = load_records(env, exp_dir)
    if not by_worker:
        return None
    assigned: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    seen_pids: Dict[int, str] = {}
    for stem, records in sorted(by_worker.items()):
        for rec in records:
            worker = rec.get("worker", stem)
            pid = _worker_pid(worker, assigned)
            seen_pids.setdefault(pid, str(worker))
            ts = rec.get("ts")
            if ts is None:
                continue
            kind = rec.get("kind")
            if kind == "span":
                events.append(
                    {
                        "name": rec.get("name", "?"),
                        "cat": "span",
                        "ph": "X",
                        "ts": int(float(ts) * 1e6),
                        "dur": max(1, int(float(rec.get("dur_ms", 0.0)) * 1e3)),
                        "pid": pid,
                        "tid": int(rec.get("tid", 0)),
                        "args": rec.get("attrs") or {},
                    }
                )
            elif kind == "gauge":
                events.append(
                    {
                        "name": rec.get("name", "?"),
                        "cat": "gauge",
                        "ph": "C",
                        "ts": int(float(ts) * 1e6),
                        "pid": pid,
                        "tid": 0,
                        "args": {rec.get("name", "value"): rec.get("value")},
                    }
                )
    if not events:
        return None
    events.sort(key=lambda e: e["ts"])
    # process-name metadata first (ts 0 keeps them ahead after the sort above)
    meta = [
        {
            "name": "process_name",
            "ph": "M",
            "ts": 0,
            "pid": pid,
            "tid": 0,
            "args": {"name": f"worker {label}"},
        }
        for pid, label in sorted(seen_pids.items())
    ]
    path = posixpath.join(telemetry_dir(exp_dir), out_name)
    env.dump(
        json.dumps(
            {"traceEvents": meta + events, "displayTimeUnit": "ms"},
            separators=(",", ":"),
        ),
        path,
    )
    return path


def mirror_to_tensorboard(env, exp_dir: str) -> int:
    """Replay gauge series as TensorBoard scalars under
    ``<exp_dir>/telemetry/tb/<worker>/`` via the tensorboard.py seam.
    Returns the number of scalars written (0 when there is nothing)."""
    from maggy_tpu import tensorboard as tb

    by_worker = load_records(env, exp_dir)
    written = 0
    for stem, records in sorted(by_worker.items()):
        gauges = [r for r in records if r.get("kind") == "gauge"]
        if not gauges:
            continue
        logdir = posixpath.join(telemetry_dir(exp_dir), "tb", stem)
        tb._register(logdir)
        try:
            steps: Dict[str, int] = {}
            for rec in gauges:
                tag = str(rec.get("name", "value"))
                step = steps.get(tag, 0)
                steps[tag] = step + 1
                try:
                    tb.scalar(f"telemetry/{tag}", float(rec.get("value", 0.0)), step)
                    written += 1
                except (TypeError, ValueError, OSError):
                    continue
        finally:
            tb._unregister()
    return written
