"""Telemetry exporters: merged Chrome trace + TensorBoard scalar mirror.

``export_chrome_trace`` folds every worker's JSONL under
``<exp_dir>/telemetry/`` into one Perfetto-loadable ``trace.json``: spans
become complete (``ph="X"``) events and gauges become counter (``ph="C"``)
events, all on the shared wall-clock microsecond base the recorder stamps, so
spans from different workers/hosts interleave correctly on one timeline.

Request lanes: lifecycle events (``kind="event"``) carrying a trace id are
additionally folded into a synthetic ``requests`` process — one thread
(lane) per trace, with the raw milestones as instants and the gaps between
consecutive milestones rendered as labeled phase spans (``route`` /
``queue`` / ``prefill`` / ``decode`` / ``lost`` …). Because the trace id is
propagated across workers (docs/observability.md), a request that hopped
client → router → replica → survivor renders as ONE contiguous lane even
though its records came from several worker files.

``mirror_to_tensorboard`` replays each worker's gauge series through the
existing :mod:`maggy_tpu.tensorboard` seam (``events.jsonl`` always, real TF
event files when the tensorboard package is importable).
"""

from __future__ import annotations

import json
import posixpath
from typing import Any, Dict, List, Optional

from maggy_tpu.telemetry.sink import telemetry_dir


def _worker_pid(worker: Any, assigned: Dict[str, int]) -> int:
    """Chrome-trace pid for a worker id: numeric ids map directly; named
    workers (driver, standalone) get stable slots from 1000 up."""
    s = str(worker)
    if s.lstrip("-").isdigit():
        return int(s)
    if s not in assigned:
        assigned[s] = 1000 + len(assigned)
    return assigned[s]


def _jsonl_segments(names: List[str]) -> List[Any]:
    """(stem, path-name) pairs for every JSONL file including rotated
    segments (``x.jsonl.3``), ordered oldest-first within each stem so a
    rotated worker's records concatenate in write order."""
    entries = []
    for name in names:
        stem, sep, suffix = name.partition(".jsonl")
        if not sep:
            continue
        if suffix and not (suffix.startswith(".") and suffix[1:].isdigit()):
            continue  # e.g. trace.json / stray temp files
        seg = int(suffix[1:]) if suffix else 0
        entries.append((stem, -seg, name))
    return [(stem, name) for stem, _seg, name in sorted(entries)]


def load_records(env, exp_dir: str) -> Dict[str, List[Dict[str, Any]]]:
    """All telemetry JSONL records under ``exp_dir``, keyed by file stem —
    rotated segments (``worker_0.jsonl.1`` …) fold into their stem oldest
    first. Unparseable lines are skipped — a crashed worker may leave a
    torn tail."""
    tdir = telemetry_dir(exp_dir)
    out: Dict[str, List[Dict[str, Any]]] = {}
    try:
        names = list(env.listdir(tdir))
    except OSError:
        return out
    for stem, name in _jsonl_segments(names):
        records = out.setdefault(stem, [])
        try:
            with env.open_file(posixpath.join(tdir, name), "r") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    return {stem: records for stem, records in out.items() if records}


# synthetic process id for the per-request lanes (well clear of worker pids:
# numeric partition ids and the 1000+ named-worker slots)
REQUESTS_PID = 9000

# (previous milestone, this milestone) -> phase-span label on a request lane
_PHASE_LABELS: Dict[Any, str] = {
    ("req.accepted", "req.dispatched"): "route",
    ("req.requeued", "req.dispatched"): "route",
    ("req.accepted", "req.shed"): "route",
    ("req.dispatched", "req.queued"): "transit",
    ("req.accepted", "req.queued"): "transit",
    ("req.queued", "req.admitted"): "queue",
    ("req.queued", "req.prefix_admitted"): "queue",
    ("req.admitted", "req.first_token"): "prefill",
    ("req.prefix_admitted", "req.first_token"): "prefill",
    ("req.first_token", "req.finished"): "decode",
    ("req.finished", "req.completed"): "completion",
    ("req.queued", "req.finished"): "queue",
}


def _request_lanes(
    traces: Dict[str, List[Dict[str, Any]]], events: List[Dict[str, Any]]
) -> List[Dict[str, Any]]:
    """Fold per-trace lifecycle events into lane metadata + instants +
    phase spans under the synthetic ``requests`` process. Returns the
    thread-name metadata records (the events are appended in place)."""
    meta: List[Dict[str, Any]] = []
    order = sorted(traces, key=lambda t: min(float(e["ts"]) for e in traces[t]))
    for tid, trace in enumerate(order, start=1):
        recs = sorted(traces[trace], key=lambda e: float(e["ts"]))
        rid = None
        for rec in recs:
            rid = (rec.get("attrs") or {}).get("rid") or rid
        meta.append(
            {
                "name": "thread_name",
                "ph": "M",
                "ts": 0,
                "pid": REQUESTS_PID,
                "tid": tid,
                "args": {"name": f"req {rid or trace}"},
            }
        )
        for rec in recs:
            events.append(
                {
                    "name": rec.get("name", "?"),
                    "cat": "request",
                    "ph": "i",
                    "s": "t",
                    "ts": int(float(rec["ts"]) * 1e6),
                    "pid": REQUESTS_PID,
                    "tid": tid,
                    "args": {"trace": trace, **(rec.get("attrs") or {})},
                }
            )
        for prev, cur in zip(recs, recs[1:]):
            t0, t1 = float(prev["ts"]), float(cur["ts"])
            if t1 <= t0:
                continue
            label = _PHASE_LABELS.get(
                (prev.get("name"), cur.get("name")),
                "lost" if cur.get("name") == "req.requeued" else "other",
            )
            events.append(
                {
                    "name": label,
                    "cat": "request",
                    "ph": "X",
                    "ts": int(t0 * 1e6),
                    "dur": max(1, int((t1 - t0) * 1e6)),
                    "pid": REQUESTS_PID,
                    "tid": tid,
                    "args": {"trace": trace},
                }
            )
    return meta


def export_chrome_trace(env, exp_dir: str, out_name: str = "trace.json") -> Optional[str]:
    """Merge all worker JSONLs into ``<exp_dir>/telemetry/trace.json``.
    Returns the written path, or None when there is nothing to export."""
    by_worker = load_records(env, exp_dir)
    if not by_worker:
        return None
    assigned: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    seen_pids: Dict[int, str] = {}
    traces: Dict[str, List[Dict[str, Any]]] = {}
    for stem, records in sorted(by_worker.items()):
        for rec in records:
            worker = rec.get("worker", stem)
            pid = _worker_pid(worker, assigned)
            seen_pids.setdefault(pid, str(worker))
            ts = rec.get("ts")
            if ts is None:
                continue
            kind = rec.get("kind")
            trace = rec.get("trace")
            if kind == "span":
                args = dict(rec.get("attrs") or {})
                if trace:
                    args["trace"] = trace
                events.append(
                    {
                        "name": rec.get("name", "?"),
                        "cat": "span",
                        "ph": "X",
                        "ts": int(float(ts) * 1e6),
                        "dur": max(1, int(float(rec.get("dur_ms", 0.0)) * 1e3)),
                        "pid": pid,
                        "tid": int(rec.get("tid", 0)),
                        "args": args,
                    }
                )
            elif kind == "gauge":
                events.append(
                    {
                        "name": rec.get("name", "?"),
                        "cat": "gauge",
                        "ph": "C",
                        "ts": int(float(ts) * 1e6),
                        "pid": pid,
                        "tid": 0,
                        "args": {rec.get("name", "value"): rec.get("value")},
                    }
                )
            elif kind == "event" and trace:
                # request lanes are cross-worker: bucket by trace id now,
                # fold into the synthetic process after the sweep
                traces.setdefault(trace, []).append(rec)
    lane_meta = _request_lanes(traces, events) if traces else []
    if not events:
        return None
    events.sort(key=lambda e: e["ts"])
    # process-name metadata first (ts 0 keeps them ahead after the sort above)
    meta = [
        {
            "name": "process_name",
            "ph": "M",
            "ts": 0,
            "pid": pid,
            "tid": 0,
            "args": {"name": f"worker {label}"},
        }
        for pid, label in sorted(seen_pids.items())
    ]
    if lane_meta:
        meta.append(
            {
                "name": "process_name",
                "ph": "M",
                "ts": 0,
                "pid": REQUESTS_PID,
                "tid": 0,
                "args": {"name": "requests"},
            }
        )
        meta.extend(lane_meta)
    path = posixpath.join(telemetry_dir(exp_dir), out_name)
    env.dump(
        json.dumps(
            {"traceEvents": meta + events, "displayTimeUnit": "ms"},
            separators=(",", ":"),
        ),
        path,
    )
    return path


def mirror_to_tensorboard(env, exp_dir: str) -> int:
    """Replay gauge series as TensorBoard scalars under
    ``<exp_dir>/telemetry/tb/<worker>/`` via the tensorboard.py seam.
    Returns the number of scalars written (0 when there is nothing)."""
    from maggy_tpu import tensorboard as tb

    by_worker = load_records(env, exp_dir)
    written = 0
    for stem, records in sorted(by_worker.items()):
        gauges = [r for r in records if r.get("kind") == "gauge"]
        if not gauges:
            continue
        logdir = posixpath.join(telemetry_dir(exp_dir), "tb", stem)
        tb._register(logdir)
        try:
            steps: Dict[str, int] = {}
            for rec in gauges:
                tag = str(rec.get("name", "value"))
                step = steps.get(tag, 0)
                steps[tag] = step + 1
                try:
                    tb.scalar(f"telemetry/{tag}", float(rec.get("value", 0.0)), step)
                    written += 1
                except (TypeError, ValueError, OSError):
                    continue
        finally:
            tb._unregister()
    return written
