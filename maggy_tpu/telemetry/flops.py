"""FLOPs/MFU estimation for telemetry gauges.

Same model as bench.py's headline metric: training FLOPs/token ≈ 6·params
(fwd+bwd matmul estimate), peak chip FLOPs detected loosely from the device
kind (v5p 459 TFLOPs bf16, else v5e 197). Non-TPU devices return None — an
"MFU" against an unknown peak would be noise, so the gauge is simply omitted
there (CPU test meshes, GPU hosts).
"""

from __future__ import annotations

from typing import Any, Optional


def param_count(tree) -> int:
    """Total parameter count of a (possibly nn.Partitioned-boxed) param tree."""
    import jax

    try:
        import flax.linen as nn

        boxed = (nn.Partitioned,)
    except Exception:  # flax absent: plain arrays only
        boxed = ()

    total = 0
    for leaf in jax.tree.leaves(tree, is_leaf=lambda x: isinstance(x, boxed) if boxed else None):
        val = leaf.value if boxed and isinstance(leaf, boxed) else leaf
        total += getattr(val, "size", 0)
    return int(total)


def flops_per_token(n_params: int) -> float:
    """Training (fwd+bwd) matmul FLOPs per token, the standard 6N estimate."""
    return 6.0 * float(n_params)


def device_peak_flops(device: Any) -> Optional[float]:
    """Peak bf16 FLOPs/s for a device, or None when unknown (CPU/GPU)."""
    if getattr(device, "platform", None) != "tpu":
        return None
    kind = str(device).lower()
    return 459e12 if ("v5p" in kind or "p5" in kind) else 197e12


def estimate_mfu(tok_per_sec: float, n_params: int, devices) -> Optional[float]:
    """Achieved/peak FLOPs fraction for a whole device set, or None off-TPU."""
    if not devices or tok_per_sec <= 0 or n_params <= 0:
        return None
    peak = device_peak_flops(devices[0])
    if peak is None:
        return None
    achieved = tok_per_sec * flops_per_token(n_params)
    return achieved / (peak * len(devices))
