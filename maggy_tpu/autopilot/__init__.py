"""Autopilot: profile-guided continuous tuning (the ASAP direction).

The startup autotuner (:mod:`maggy_tpu.tune`) picks a system config once,
before anything runs. This package closes the loop *while the job runs*:

* :mod:`~maggy_tpu.autopilot.diagnose` — classify the dominant bottleneck
  per telemetry window (input/compute/drain/queue/memory), with an
  evidence struct naming the metrics behind every verdict; consumes the
  same attribution code path as ``tools/analyze_trace.py``.
* :mod:`~maggy_tpu.autopilot.knobs` — the checked-in knob registry
  (type/bounds/safe-live per knob; ``tools/check_knob_registry.py``
  enforces it in tier-1).
* :mod:`~maggy_tpu.autopilot.plan` — diagnosis → candidate moves over the
  registry, AOT-feasibility-pruned, persisted per workload fingerprint so
  a fleet shares learned configs.
* :mod:`~maggy_tpu.autopilot.controller` — the online controller: guarded
  before/after windows around every live re-tune, automatic rollback on
  guard regression, every decision journaled as ``autopilot.*`` telemetry.

Wiring: ``Trainer.fit(autopilot=...)``, ``Scheduler(autopilot=...)``,
``Router(autopilot=...)``. See docs/autotune.md "Continuous tuning".
"""

from __future__ import annotations

from maggy_tpu.autopilot.controller import (  # noqa: F401
    AutopilotConfig,
    Controller,
    RouterTarget,
    SchedulerTarget,
)
from maggy_tpu.autopilot.diagnose import (  # noqa: F401
    BOTTLENECKS,
    Diagnosis,
    Thresholds,
    diagnose_records,
    diagnose_requests,
    diagnose_serve,
    diagnose_steps,
    diagnose_train,
)
from maggy_tpu.autopilot.knobs import KNOBS, Knob  # noqa: F401
from maggy_tpu.autopilot.plan import (  # noqa: F401
    DecisionStore,
    Move,
    Planner,
    aot_memory_check,
    traffic_shape,
    workload_fingerprint,
)

__all__ = [
    "AutopilotConfig",
    "Controller",
    "SchedulerTarget",
    "RouterTarget",
    "BOTTLENECKS",
    "Diagnosis",
    "Thresholds",
    "diagnose_train",
    "diagnose_serve",
    "diagnose_steps",
    "diagnose_requests",
    "diagnose_records",
    "KNOBS",
    "Knob",
    "Move",
    "Planner",
    "DecisionStore",
    "aot_memory_check",
    "traffic_shape",
    "workload_fingerprint",
]
