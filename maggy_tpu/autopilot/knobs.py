"""Checked-in autopilot knob registry.

Every configuration knob the Planner (:mod:`maggy_tpu.autopilot.plan`) may
move must be declared here with a type, bounds and a ``safe_live`` flag —
``tools/check_knob_registry.py`` (wired into tier-1, mirroring the
telemetry-name lint) fails on any knob reference in ``maggy_tpu/`` that is
missing from this table, and on any registry entry whose declaration is
structurally incomplete. The failure mode this kills: the controller
"re-tunes" a knob nothing applies (a typo'd name silently becomes a no-op
move that still burns a guard window), or live-applies a knob that is only
safe at startup.

``safe_live`` semantics (docs/autotune.md "Rollback semantics"): a
safe-live knob can be changed on a RUNNING job — either instantly
(prefetch depth, metrics window, admission policy) or via the
drain-and-reconfigure seam between serving waves (slot geometry). Knobs
with ``safe_live=False`` are *startup* knobs: the Planner may still
recommend them (recorded into the workload-fingerprint decision cache for
the next launch, AOT-feasibility-checked through ``tune``'s memory
analysis) but the online controller never applies them mid-run.

Keep this module import-light (stdlib only): the lint loads it by file
path without importing the package.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

SCOPES = ("train", "serve", "fleet")
KINDS = ("int", "float", "bool", "choice")

# flash-attention tile candidates, promoted from the manual
# tools/tune_flash.py sweep grid — the sweep tool and the Planner's
# compute-bound recommendations now draw from this one table
FLASH_TILE_CHOICES = (128, 256, 512, 1024)

# remat policy names mirrored from models/transformer.py REMAT_POLICIES
# (kept literal here so the registry stays stdlib-importable)
REMAT_POLICY_CHOICES = (None, "nothing", "dots", "dots_attn")

# paged KV cache page sizes (tokens): powers of two that divide every
# supported max_seq_len; the engine snaps incompatible values down
PAGE_SIZE_CHOICES = (8, 16, 32, 64, 128)

# gradient-reduction bucket sizes in MiB (parallel/overlap.py): powers of
# two spanning tiny test models up to production param trees; None =
# unbucketed (one collective per dtype)
BUCKET_MB_CHOICES = (None, 1, 4, 16, 32, 64, 128, 256)

# ZeRO optimizer-state sharding stages supported by the trainer (0 = dense
# replicated states, 1 = states sharded over the data axis)
ZERO_STAGE_CHOICES = (0, 1)


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable: identity, type, bounds, and liveness contract."""

    name: str  # "<scope>.<knob>", e.g. "train.prefetch_depth"
    kind: str  # "int" | "float" | "bool" | "choice"
    scope: str  # "train" | "serve" | "fleet"
    safe_live: bool  # applicable to a running job (see module docstring)
    description: str
    lo: Optional[float] = None  # int/float bounds, inclusive
    hi: Optional[float] = None
    choices: Optional[Tuple[Any, ...]] = None  # for kind == "choice"

    def clamp(self, value: Any) -> Any:
        """``value`` coerced into this knob's domain (bounds/choices)."""
        if self.kind == "int":
            return int(min(self.hi, max(self.lo, int(value))))
        if self.kind == "float":
            return float(min(self.hi, max(self.lo, float(value))))
        if self.kind == "bool":
            return bool(value)
        return value if value in self.choices else self.choices[0]

    def valid(self, value: Any) -> bool:
        if self.kind == "int":
            return isinstance(value, int) and self.lo <= value <= self.hi
        if self.kind == "float":
            return (
                isinstance(value, (int, float)) and self.lo <= value <= self.hi
            )
        if self.kind == "bool":
            return isinstance(value, bool)
        return value in self.choices


KNOBS = {
    k.name: k
    for k in (
        # ---- training loop (applied inside Trainer.fit)
        Knob(
            "train.prefetch_depth", "int", "train", True,
            "DevicePrefetcher lookahead; raised when input-bound",
            lo=1, hi=16,
        ),
        Knob(
            "train.metrics_window", "int", "train", True,
            "lagged metrics drain window; raised when drain-bound",
            lo=0, hi=8,
        ),
        Knob(
            "train.batch_size", "int", "train", False,
            "global batch size (startup-only; AOT memory-checked)",
            lo=1, hi=65536,
        ),
        Knob(
            "train.remat_policy", "choice", "train", False,
            "activation remat policy (startup-only)",
            choices=REMAT_POLICY_CHOICES,
        ),
        Knob(
            "train.zero_stage", "choice", "train", False,
            "ZeRO optimizer-state sharding stage (startup-only: changes the "
            "optax state layout; memory-bound playbook raises it before "
            "shrinking batch)",
            choices=ZERO_STAGE_CHOICES,
        ),
        Knob(
            "train.bucket_mb", "choice", "train", False,
            "gradient-reduction bucket size in MiB (startup-only: None = "
            "unbucketed; smaller buckets overlap more comm with backward)",
            choices=BUCKET_MB_CHOICES,
        ),
        Knob(
            "train.flash_bwd_block_q", "choice", "train", False,
            "flash-attention backward q tile (tools/tune_flash.py grid)",
            choices=FLASH_TILE_CHOICES,
        ),
        Knob(
            "train.flash_bwd_block_k", "choice", "train", False,
            "flash-attention backward k tile (tools/tune_flash.py grid)",
            choices=FLASH_TILE_CHOICES,
        ),
        # ---- serving engine/scheduler (applied by the Scheduler)
        Knob(
            "serve.num_slots", "int", "serve", True,
            "decode slot count; drain-and-reconfigure between waves",
            lo=1, hi=256,
        ),
        Knob(
            "serve.max_queue", "int", "serve", True,
            "scheduler admission queue bound",
            lo=1, hi=65536,
        ),
        Knob(
            "serve.async_decode", "bool", "serve", True,
            "async decode double buffer (flushed before flipping)",
        ),
        Knob(
            "serve.prefix_min", "int", "serve", True,
            "minimum shared-prefix length for KV reuse",
            lo=1, hi=65536,
        ),
        Knob(
            "serve.page_size", "choice", "serve", False,
            "paged KV cache page size in tokens (startup-only: the page "
            "pool layout is baked into the compiled decode program)",
            choices=PAGE_SIZE_CHOICES,
        ),
        Knob(
            "serve.max_pages_per_req", "int", "serve", True,
            "cap on KV pages one request may hold; shrunk FIRST when "
            "memory-bound (before sacrificing num_slots concurrency)",
            lo=1, hi=65536,
        ),
        Knob(
            "serve.tier_host_pages", "int", "serve", True,
            "host-DRAM KV tier capacity in pages; grown when memory-bound "
            "so spill replaces preemption re-prefill (0 disables spills)",
            lo=0, hi=1_048_576,
        ),
        Knob(
            "serve.tier_low_water_pct", "float", "serve", True,
            "HBM headroom fraction below which the scheduler spills the "
            "coldest stream to the host tier each metrics tick",
            lo=0.0, hi=0.9,
        ),
        # ---- fleet router (applied by the Router)
        Knob(
            "fleet.admission", "choice", "fleet", True,
            "over-SLO behavior: park in router queue or shed BUSY",
            choices=("queue", "shed"),
        ),
        Knob(
            "fleet.slo_ttft_ms", "float", "fleet", True,
            "TTFT budget driving projected-TTFT admission",
            lo=1.0, hi=600_000.0,
        ),
        Knob(
            "fleet.affinity_weight", "float", "fleet", True,
            "prefix-affinity bonus in ms subtracted from projected TTFT "
            "for replicas holding the prompt's prefix resident (0 = "
            "affinity-blind routing; brownout level >= 2 zeroes it)",
            lo=0.0, hi=10_000.0,
        ),
        # ---- fleet autoscaler (docs/fleet.md "Autoscaling")
        Knob(
            "fleet.min_replicas", "int", "fleet", True,
            "autoscaler floor: scale-in never drains below this count",
            lo=1, hi=64,
        ),
        Knob(
            "fleet.max_replicas", "int", "fleet", True,
            "autoscaler ceiling: scale-out pressure past it raises the "
            "fleet.at_capacity gauge instead of spawning",
            lo=1, hi=64,
        ),
        Knob(
            "fleet.scale_cooldown_s", "float", "fleet", True,
            "minimum seconds between scale events (flap prevention: a "
            "burst's edge must not thrash the fleet)",
            lo=0.0, hi=3600.0,
        ),
        Knob(
            "fleet.target_util", "float", "fleet", True,
            "fleet slot-utilization ceiling the autoscaler holds: "
            "sustained util above it scales out, scale-in only when the "
            "survivors would stay below it",
            lo=0.05, hi=0.95,
        ),
    )
}


def validate_registry(knobs=None):
    """Structural check of the registry itself (run by the lint): every
    entry has a coherent kind/bounds/choices declaration, a scope-prefixed
    name, and an explicit safe-live flag. Returns a list of error strings."""
    errors = []
    for name, knob in (knobs if knobs is not None else KNOBS).items():
        where = f"knob {name!r}"
        if name != knob.name:
            errors.append(f"{where}: registered under a different key")
        if knob.scope not in SCOPES:
            errors.append(f"{where}: unknown scope {knob.scope!r}")
        elif not name.startswith(knob.scope + "."):
            errors.append(f"{where}: name must be prefixed '{knob.scope}.'")
        if knob.kind not in KINDS:
            errors.append(f"{where}: unknown kind {knob.kind!r}")
        if knob.kind in ("int", "float"):
            if knob.lo is None or knob.hi is None or knob.lo > knob.hi:
                errors.append(f"{where}: {knob.kind} knob needs lo <= hi bounds")
        if knob.kind == "choice" and not knob.choices:
            errors.append(f"{where}: choice knob needs a non-empty choices tuple")
        if not isinstance(knob.safe_live, bool):
            errors.append(f"{where}: safe_live must be an explicit bool")
        if not knob.description:
            errors.append(f"{where}: description required")
    return errors
