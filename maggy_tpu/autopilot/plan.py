"""Planner: diagnosis -> candidate config moves over the knob registry.

Second stage of the telemetry→config loop. A :class:`Move` names one knob
from the checked-in registry (:mod:`maggy_tpu.autopilot.knobs`) and a
target value; the Planner's playbook maps each bottleneck class to the
moves that historically relieve it, clamped into the knob's declared
bounds and filtered three ways:

* ``live_only`` keeps only ``safe_live`` knobs — what the online
  controller may touch mid-run. Startup-only recommendations (batch size,
  remat policy, flash tiles) still come back from :meth:`Planner.plan_all`
  and land in the decision cache for the next launch.
* a caller-supplied ``feasible(move)`` hook prunes moves the same way the
  startup tuner prunes candidates — :func:`aot_memory_check` adapts
  ``tune``'s AOT ``memory_analysis`` pruning for batch/remat moves, so an
  autopilot recommendation can never be one the static stage would reject.
* no-op moves (target equals current) are dropped.

Decisions persist in the tune cache keyed by a **workload fingerprint**
(:func:`workload_fingerprint` = model fingerprint × topology × bucketed
traffic shape), so a fleet of identical workers shares learned configs:
:class:`DecisionStore` is the read/write seam, and a fresh controller seeds
its knobs from whatever the fleet already committed for this workload.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import time
from typing import Any, Callable, Dict, List, Optional

from maggy_tpu.autopilot.knobs import FLASH_TILE_CHOICES, KNOBS, Knob

# decision-cache records are versioned alongside the attribution schema
DECISION_SCHEMA = "maggy-tpu.autopilot-decisions.v1"


@dataclasses.dataclass(frozen=True)
class Move:
    """One planned config change: a registered knob and its target value."""

    knob: str
    value: Any
    reason: str = ""

    def __post_init__(self):
        if self.knob not in KNOBS:
            raise ValueError(
                f"move targets unregistered knob {self.knob!r} "
                f"(declare it in maggy_tpu/autopilot/knobs.py)"
            )

    @property
    def spec(self) -> Knob:
        return KNOBS[self.knob]

    def to_dict(self) -> Dict[str, Any]:
        return {"knob": self.knob, "value": self.value, "reason": self.reason}


# ------------------------------------------------------------ fingerprints


def bucket_pow2(v: float) -> int:
    """Smallest power of two >= v (1 for v <= 1): traffic features are
    bucketed so near-identical workloads share a fingerprint instead of
    fragmenting the fleet cache per exact batch/prompt length."""
    v = max(1, int(v))
    b = 1
    while b < v:
        b *= 2
    return b


def traffic_shape(kind: str, **features: Any) -> Dict[str, Any]:
    """Canonical traffic-shape dict: ``kind`` ("train"/"serve") plus
    numeric features bucketed to powers of two."""
    out: Dict[str, Any] = {"kind": str(kind)}
    for key in sorted(features):
        v = features[key]
        out[key] = bucket_pow2(v) if isinstance(v, (int, float)) else str(v)
    return out


def workload_fingerprint(
    model: Any, topology: Dict[str, Any], traffic: Dict[str, Any]
) -> str:
    """Stable id of (what runs, where it runs, what hits it): model
    fingerprint/config identity × device topology × bucketed traffic
    shape. This is the key the fleet shares learned configs under."""
    payload = json.dumps(
        {"model": model, "topology": topology, "traffic": traffic},
        sort_keys=True,
        default=str,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


# -------------------------------------------------------------- feasibility


def aot_memory_check(
    model: Any,
    batch_fn: Callable[[int], Dict[str, Any]],
    *,
    optimizer: Any = None,
    budget_bytes: Optional[int] = None,
    devices: Optional[list] = None,
) -> Callable[[Move], bool]:
    """A ``feasible(move)`` hook backed by the startup tuner's AOT memory
    analysis: a ``train.batch_size``/``train.remat_policy`` move survives
    only if the candidate it implies compiles under the HBM budget —
    nothing executes. Non-memory moves pass through."""
    from maggy_tpu.tune import static as static_mod
    from maggy_tpu.tune.candidates import Candidate

    def feasible(move: Move) -> bool:
        if move.knob not in ("train.batch_size", "train.remat_policy"):
            return True
        if move.knob == "train.batch_size":
            bs, remat = int(move.value), None
        else:
            bs, remat = len(batch_fn(1)["tokens"]), move.value
            bs = max(1, bs)
        report = static_mod.analyze_candidate(
            model,
            Candidate(preset="dp", batch_size=bs, remat_policy=remat),
            batch_fn(bs),
            optimizer=optimizer,
            budget_bytes=budget_bytes,
            devices=devices,
        )
        return report.ok

    return feasible


# ----------------------------------------------------------------- planner


def _grow(knob: Knob, current: Any) -> Any:
    """Next value up for a numeric knob: double (min 2), clamped."""
    cur = int(current or 0)
    return knob.clamp(max(2, cur * 2))


def _shrink(knob: Knob, current: Any) -> Any:
    cur = int(current or 0)
    return knob.clamp(cur // 2)


class Planner:
    """Maps a :class:`Diagnosis` plus the target's current knob values to
    an ordered list of candidate :class:`Move`\\ s (best first)."""

    def __init__(self, feasible: Optional[Callable[[Move], bool]] = None):
        self.feasible = feasible

    # playbook: one method per (scope, bottleneck) worth acting on
    def _train_moves(self, diag, current) -> List[Move]:
        moves: List[Move] = []
        if diag.bottleneck == "input_bound":
            knob = KNOBS["train.prefetch_depth"]
            cur = current.get(knob.name)
            if cur is not None:
                moves.append(
                    Move(knob.name, _grow(knob, cur), diag.reason)
                )
        elif diag.bottleneck == "drain_bound":
            knob = KNOBS["train.metrics_window"]
            cur = current.get(knob.name)
            if cur is not None:
                moves.append(Move(knob.name, _grow(knob, cur), diag.reason))
        elif diag.bottleneck == "memory_bound":
            # ZeRO first (docs/distributed.md "Gradient overlap & ZeRO"):
            # sharding optimizer states over the data axis recovers
            # ~2x param bytes per device WITHOUT touching the batch —
            # shrink batch only when zero_stage is already raised (or the
            # caller doesn't report it)
            if current.get("train.zero_stage") == 0:
                moves.append(Move("train.zero_stage", 1, diag.reason))
            bs = current.get("train.batch_size")
            if bs and int(bs) > 1:
                moves.append(
                    Move(
                        "train.batch_size",
                        _shrink(KNOBS["train.batch_size"], bs),
                        diag.reason,
                    )
                )
            if current.get("train.remat_policy") is None:
                moves.append(
                    Move("train.remat_policy", "nothing", diag.reason)
                )
        elif diag.bottleneck == "compute_bound":
            # promoted tune_flash sweep: recommend the measured-best tiles
            # when none are pinned yet (offline; racing the full grid is
            # the startup tuner's job)
            if current.get("train.flash_bwd_block_q") is None:
                best = FLASH_TILE_CHOICES[2]  # 512: BENCH_NOTES round-2 winner
                moves.append(
                    Move("train.flash_bwd_block_q", best, diag.reason)
                )
                moves.append(
                    Move("train.flash_bwd_block_k", best, diag.reason)
                )
        return moves

    def _serve_moves(self, diag, current) -> List[Move]:
        moves: List[Move] = []
        if diag.bottleneck == "queue_bound":
            knob = KNOBS["serve.num_slots"]
            cur = current.get(knob.name)
            if cur is not None and _grow(knob, cur) != cur:
                moves.append(Move(knob.name, _grow(knob, cur), diag.reason))
            elif current.get("fleet.admission") == "queue":
                # slot geometry already at its bound: shed instead of
                # queueing past the SLO
                moves.append(Move("fleet.admission", "shed", diag.reason))
        elif diag.bottleneck == "drain_bound":
            if current.get("serve.async_decode") is False:
                moves.append(Move("serve.async_decode", True, diag.reason))
        elif diag.bottleneck == "memory_bound":
            # spill before preempt: growing the host-DRAM tier turns the
            # next preemption's re-prefill into a cheap swap-in without
            # giving up any HBM, so it leads the shrink ladder
            # (docs/serving.md "Host-DRAM page tier")
            tier = current.get("serve.tier_host_pages")
            if tier is not None and _grow(KNOBS["serve.tier_host_pages"], tier) != tier:
                moves.append(
                    Move(
                        "serve.tier_host_pages",
                        _grow(KNOBS["serve.tier_host_pages"], tier),
                        diag.reason,
                    )
                )
            # paged engines shrink the per-request page cap FIRST: it
            # bounds worst-case footprint without sacrificing concurrency;
            # cutting num_slots is the blunt fallback (docs/serving.md
            # "Paged KV cache")
            cap = current.get("serve.max_pages_per_req")
            if cap and int(cap) > 1:
                moves.append(
                    Move(
                        "serve.max_pages_per_req",
                        _shrink(KNOBS["serve.max_pages_per_req"], cap),
                        diag.reason,
                    )
                )
            cur = current.get("serve.num_slots")
            if cur and int(cur) > 1:
                moves.append(
                    Move(
                        "serve.num_slots",
                        _shrink(KNOBS["serve.num_slots"], cur),
                        diag.reason,
                    )
                )
        return moves

    def plan_all(self, diag, current: Dict[str, Any]) -> List[Move]:
        """Every candidate move for this diagnosis — live and startup-only
        alike — deduped, feasibility-filtered, no-ops dropped."""
        raw = (
            self._train_moves(diag, current)
            if diag.scope == "train"
            else self._serve_moves(diag, current)
        )
        out: List[Move] = []
        seen = set()
        for move in raw:
            if move.knob in seen:
                continue
            seen.add(move.knob)
            if current.get(move.knob) == move.value:
                continue  # no-op
            if not move.spec.valid(move.value):
                continue
            if self.feasible is not None and not self.feasible(move):
                continue
            out.append(move)
        return out

    def plan(
        self, diag, current: Dict[str, Any], live_only: bool = True
    ) -> List[Move]:
        moves = self.plan_all(diag, current)
        if live_only:
            moves = [m for m in moves if m.spec.safe_live]
        return moves


# ----------------------------------------------------------- decision cache


class DecisionStore:
    """Autopilot decisions in the persistent tune cache, keyed per
    workload fingerprint — the fleet-shared artifact: any worker running
    the same (model × topology × traffic shape) reads the knobs its peers
    already proved out, and commits its own wins back."""

    def __init__(self, env=None):
        from maggy_tpu.tune.cache import TuneCache

        self.cache = TuneCache(env)

    @staticmethod
    def key(workload: str) -> str:
        return f"autopilot-{workload}"

    def load(self, workload: str) -> Dict[str, Any]:
        """Committed knob values for this workload ({} when none). A
        record stamped with a different workload fingerprint (a clobber)
        reads as empty, never as someone else's config."""
        record = self.cache.get_record(self.key(workload))
        if not record or record.get("workload") != workload:
            return {}
        return dict(record.get("knobs") or {})

    def record(
        self,
        workload: str,
        move: Move,
        *,
        outcome: str,
        before: Optional[float] = None,
        after: Optional[float] = None,
    ) -> None:
        """Append one guarded decision; committed moves update the shared
        knob table, rollbacks only append to the history."""
        key = self.key(workload)
        record = self.cache.get_record(key)
        if not record or record.get("workload") != workload:
            record = {
                "schema": DECISION_SCHEMA,
                "workload": workload,
                "knobs": {},
                "history": [],
            }
        if outcome == "committed":
            record["knobs"][move.knob] = move.value
        history = record.setdefault("history", [])
        history.append(
            {
                "ts": time.time(),
                "move": move.to_dict(),
                "outcome": outcome,
                "guard_before": before,
                "guard_after": after,
            }
        )
        del history[:-50]  # bounded
        self.cache.put(key, record)
