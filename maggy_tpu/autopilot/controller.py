"""Online controller: the loop that closes telemetry→config while running.

A :class:`Controller` is a deterministic state machine fed metric samples
(``observe``) by whatever loop hosts it — ``Trainer.fit`` pushes one sample
per step, the serve ``Scheduler`` and fleet ``Router`` sample their own
stats on a wall-clock cadence (``maybe_sample``). Samples aggregate into
fixed-size windows; each completed window drives one transition:

* **baseline** — diagnose the window (:mod:`maggy_tpu.autopilot.diagnose`),
  plan a safe-live move (:mod:`maggy_tpu.autopilot.plan`), apply it through
  the target, remember the window's guard score, enter **trial**.
* **trial** — the next full window measures the move. Guard metric at or
  above ``before * (1 - regress_tol)`` commits the move (and records it in
  the workload-fingerprint decision cache so the fleet shares it); below,
  the controller **rolls back automatically** to the previous value.
  Samples taken while the target is still applying a move (e.g. the serve
  drain-and-reconfigure) are discarded, so a trial window never bills the
  transition cost to the new config.

Every transition is journaled as ``autopilot.*`` telemetry
(``diagnosis``/``applied``/``committed``/``rollback`` events plus
``autopilot.retunes``/``autopilot.rollbacks`` counters and the
``autopilot.tick_ms`` overhead gauge), so ``/monitor`` and
``tools/analyze_trace.py`` can show what the autopilot did and why.

A **target** is any object with::

    scope: "train" | "serve"          # picks the diagnoser
    guard_metric: str                 # sample key; higher is better
    current() -> {knob name: value}   # registered knobs it owns
    apply(knob, value) -> bool        # enact one move (False: refused)
    pending() -> bool                 # still mid-apply (optional)
    sample() -> {metric: value}       # pull-mode only (maybe_sample)

:class:`SchedulerTarget` and :class:`RouterTarget` adapt the serving tiers;
``Trainer.fit`` builds its own in-loop target.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

from maggy_tpu import telemetry
from maggy_tpu.autopilot import diagnose as diag_mod
from maggy_tpu.autopilot.plan import DecisionStore, Move, Planner


@dataclasses.dataclass
class AutopilotConfig:
    """Cadence and guard knobs for one controller."""

    window: int = 16  # samples per measurement window
    cooldown_windows: int = 1  # quiet windows after each decision
    regress_tol: float = 0.05  # rollback when after < before * (1 - tol)
    interval_s: float = 0.25  # pull-mode sampling cadence
    live_only: bool = True  # online controller: safe-live moves only
    store: bool = True  # persist decisions to the tune cache
    thresholds: diag_mod.Thresholds = dataclasses.field(
        default_factory=diag_mod.Thresholds
    )

    def validate(self) -> None:
        if self.window < 2:
            raise ValueError("autopilot window must be >= 2 samples")
        if not 0.0 <= self.regress_tol < 1.0:
            raise ValueError("regress_tol must be in [0, 1)")


class Controller:
    """One target's guarded continuous-tuning loop (see module docstring)."""

    def __init__(
        self,
        target: Any,
        config: Optional[AutopilotConfig] = None,
        planner: Optional[Planner] = None,
        telemetry_recorder=None,
        store: Optional[DecisionStore] = None,
        workload: Optional[str] = None,
    ):
        self.target = target
        self.config = config or AutopilotConfig()
        self.config.validate()
        self.planner = planner or Planner()
        self.telemetry = telemetry_recorder or telemetry.get()
        self.workload = workload
        self._store = store
        if store is None and self.config.store and workload is not None:
            try:
                self._store = DecisionStore()
            except Exception:  # noqa: BLE001 - no env root: run cache-less
                self._store = None
        self._samples: List[Dict[str, Any]] = []
        self._phase = "baseline"
        self._cooldown = 0
        self._move: Optional[Move] = None
        self._prev_value: Any = None
        self._baseline_score: float = 0.0
        self._last_score: Optional[float] = None  # newest full-window guard
        self._last_sample_ts = 0.0
        self.diagnoses = 0
        self.retunes = 0
        self.rollbacks = 0
        # last decision, for STATUS/monitor panels
        self.last: Dict[str, Any] = {"phase": self._phase}
        self._seed_from_store()

    # ----------------------------------------------------------- fleet seed

    def _seed_from_store(self) -> None:
        """Apply knobs a fleet peer already committed for this workload."""
        if self._store is None or self.workload is None:
            return
        current = self.target.current()
        for knob, value in self._store.load(self.workload).items():
            if knob not in current or current[knob] == value:
                continue
            try:
                move = Move(knob, value, reason="decision cache")
            except ValueError:
                continue  # stale record naming a since-removed knob
            if not move.spec.safe_live or not move.spec.valid(value):
                continue
            if self.target.apply(knob, value):
                self.telemetry.event(
                    "autopilot.applied", knob=knob, value=value,
                    prev=current[knob], reason="decision cache",
                    workload=self.workload,
                )

    # ------------------------------------------------------------- sampling

    def maybe_sample(self, now: Optional[float] = None) -> None:
        """Pull-mode tick (serve loops call this every iteration): at most
        one ``target.sample()`` per ``interval_s``."""
        now = time.time() if now is None else now
        if now - self._last_sample_ts < self.config.interval_s:
            return
        self._last_sample_ts = now
        self.observe(self.target.sample())

    def observe(self, sample: Dict[str, Any]) -> None:
        """Push one metric sample; closes a window every ``window`` calls.
        Samples during a target's pending apply are discarded (the trial
        window must measure the new config, not the transition)."""
        t0 = time.perf_counter()
        pending = getattr(self.target, "pending", None)
        if pending is not None and pending():
            self._samples.clear()
            return
        self._samples.append(sample)
        if len(self._samples) >= self.config.window:
            self._close_window()
        self.telemetry.gauge(
            "autopilot.tick_ms", (time.perf_counter() - t0) * 1e3
        )

    # -------------------------------------------------------------- windows

    @staticmethod
    def _aggregate(samples: List[Dict[str, Any]]) -> Dict[str, float]:
        """Mean of every numeric key across the window (None-safe)."""
        sums: Dict[str, float] = {}
        counts: Dict[str, int] = {}
        for s in samples:
            for k, v in s.items():
                if v is None:
                    continue
                try:
                    f = float(v)
                except (TypeError, ValueError):
                    continue
                sums[k] = sums.get(k, 0.0) + f
                counts[k] = counts.get(k, 0) + 1
        return {k: sums[k] / counts[k] for k in sums}

    def _close_window(self) -> None:
        window = self._aggregate(self._samples)
        self._samples.clear()
        score = float(window.get(self.target.guard_metric) or 0.0)
        self._last_score = score
        if self._phase == "trial":
            self._close_trial(score)
            return
        if self._cooldown > 0:
            self._cooldown -= 1
            return
        diagnosis = (
            diag_mod.diagnose_serve(window, self.config.thresholds)
            if self.target.scope == "serve"
            else diag_mod.diagnose_train(window, self.config.thresholds)
        )
        self.diagnoses += 1
        self.telemetry.count("autopilot.diagnoses")
        self.telemetry.event(
            "autopilot.diagnosis",
            bottleneck=diagnosis.bottleneck,
            scope=diagnosis.scope,
            evidence=diagnosis.evidence,
            shares=diagnosis.to_dict()["shares"],
            reason=diagnosis.reason,
        )
        self.last.update(
            {"phase": self._phase, "bottleneck": diagnosis.bottleneck}
        )
        moves = self.planner.plan(
            diagnosis, self.target.current(), live_only=self.config.live_only
        )
        if moves:
            self._start_trial(moves[0], score)

    def _start_trial(self, move: Move, baseline_score: float) -> None:
        prev = self.target.current().get(move.knob)
        if not self.target.apply(move.knob, move.value):
            return
        self._move = move
        self._prev_value = prev
        self._baseline_score = baseline_score
        self._phase = "trial"
        self.telemetry.event(
            "autopilot.applied",
            knob=move.knob, value=move.value, prev=prev,
            reason=move.reason, guard_before=baseline_score,
            workload=self.workload,
        )
        self.last.update(
            {
                "phase": "trial",
                "move": f"{move.knob}={move.value}",
                "prev": prev,
            }
        )

    def inject(self, move: Move) -> bool:
        """Chaos/test seam: force a trial of ``move`` right now, bypassing
        diagnosis — the guard + rollback machinery still judges it against
        the current (possibly partial) window's score."""
        if self._phase == "trial":
            return False
        # best available baseline: the partial window if it has guard
        # samples, else the newest completed window's score — an injected
        # move must still be judged against a REAL before-measurement
        partial = self._aggregate(self._samples).get(self.target.guard_metric)
        score = float(
            partial
            if partial is not None
            else (self._last_score if self._last_score is not None else 0.0)
        )
        self._samples.clear()
        self._start_trial(move, score)
        return self._phase == "trial"

    def _close_trial(self, score: float) -> None:
        move, prev = self._move, self._prev_value
        before = self._baseline_score
        kept = score >= before * (1.0 - self.config.regress_tol)
        if kept:
            self.retunes += 1
            self.telemetry.count("autopilot.retunes")
            self.telemetry.event(
                "autopilot.committed",
                knob=move.knob, value=move.value,
                guard_before=before, guard_after=score,
                workload=self.workload,
            )
            outcome = "committed"
            self.last.update({"phase": "baseline", "move": f"{move.knob}={move.value}"})
        else:
            self.target.apply(move.knob, prev)
            self.rollbacks += 1
            self.telemetry.count("autopilot.rollbacks")
            self.telemetry.event(
                "autopilot.rollback",
                knob=move.knob, value=move.value, restored=prev,
                guard_before=before, guard_after=score,
                workload=self.workload,
            )
            outcome = "rolled_back"
            self.last.update(
                {"phase": "baseline", "move": f"{move.knob}={prev} (rollback)"}
            )
        if self._store is not None and self.workload is not None:
            self._store.record(
                self.workload, move, outcome=outcome,
                before=before, after=score,
            )
        self._move = None
        self._prev_value = None
        self._phase = "baseline"
        self._cooldown = self.config.cooldown_windows

    # --------------------------------------------------------------- status

    def status(self) -> Dict[str, Any]:
        """Panel-ready summary (monitor serve/fleet dashboards)."""
        return {
            "phase": self._phase,
            "bottleneck": self.last.get("bottleneck"),
            "last_move": self.last.get("move"),
            "diagnoses": self.diagnoses,
            "retunes": self.retunes,
            "rollbacks": self.rollbacks,
            "workload": self.workload,
        }


# ------------------------------------------------------------------ targets


class SchedulerTarget:
    """Adapts a serve :class:`~maggy_tpu.serve.scheduler.Scheduler`:
    samples window token rates from its stats snapshot; applies queue and
    slot-geometry knobs (slot changes go through the scheduler's
    drain-and-reconfigure seam and report ``pending`` until enacted)."""

    scope = "serve"
    guard_metric = "tokens_per_sec"

    def __init__(self, scheduler):
        self.scheduler = scheduler
        self._last_tokens: Optional[int] = None
        self._last_ts = 0.0

    def sample(self) -> Dict[str, Any]:
        s = self.scheduler.stats()
        now = time.time()
        # window-delta token rate: more honest than the loop EMA for a
        # guard, because it covers exactly the sampled interval
        rate = None
        if self._last_tokens is not None and now > self._last_ts:
            rate = (s["tokens_out"] - self._last_tokens) / (now - self._last_ts)
        self._last_tokens, self._last_ts = s["tokens_out"], now
        engine = self.scheduler.engine
        return {
            "queue_depth": s["queue_depth"],
            "active_slots": s["active_slots"],
            "num_slots": s["num_slots"],
            "tpot_ms_p50": s.get("tpot_ms_p50"),
            "drain_ms": getattr(engine, "last_drain_ms", 0.0),
            "tokens_per_sec": rate,
        }

    def current(self) -> Dict[str, Any]:
        engine = self.scheduler.engine
        knobs = {
            "serve.num_slots": engine.slots.num_slots,
            "serve.max_queue": self.scheduler.max_queue,
            "serve.async_decode": engine.async_decode,
            "serve.prefix_min": engine.prefix_min,
        }
        if engine.paged:
            # page_size is startup-only (recorded for the next launch via
            # the decision cache); max_pages_per_req is the live memory
            # lever the planner shrinks before touching num_slots
            knobs["serve.page_size"] = engine.page_size
            knobs["serve.max_pages_per_req"] = engine.max_pages_per_req
        if engine.tier is not None:
            knobs["serve.tier_host_pages"] = engine.tier.stats()[
                "host_pages_total"
            ]
            knobs["serve.tier_low_water_pct"] = engine.tier_policy.low_water_pct
        return knobs

    def pending(self) -> bool:
        return self.scheduler.reconfigure_pending()

    def apply(self, knob: str, value: Any) -> bool:
        if knob == "serve.num_slots":
            return self.scheduler.request_reconfigure(int(value))
        if knob == "serve.max_queue":
            self.scheduler.max_queue = int(value)
            return True
        if knob == "serve.async_decode":
            engine = self.scheduler.engine
            engine.flush()  # no stale double-buffer across the flip
            engine.async_decode = bool(value)
            return True
        if knob == "serve.prefix_min":
            engine = self.scheduler.engine
            engine.prefix_min = max(1, int(value))
            engine.prefix_index.min_len = engine.prefix_min
            return True
        if knob == "serve.max_pages_per_req":
            engine = self.scheduler.engine
            if not engine.paged:
                return False
            engine.set_max_pages_per_req(int(value))
            return True
        if knob == "serve.tier_host_pages":
            engine = self.scheduler.engine
            if engine.tier is None:
                return False
            engine.set_tier_host_pages(int(value))
            return True
        if knob == "serve.tier_low_water_pct":
            engine = self.scheduler.engine
            if engine.tier is None:
                return False
            engine.set_tier_low_water(float(value))
            return True
        return False


class RouterTarget:
    """Adapts the fleet :class:`~maggy_tpu.serve.fleet.router.Router`:
    guard is fleet SLO attainment; moves touch the admission policy and
    the TTFT budget (both instant, lock-guarded config fields)."""

    scope = "serve"
    guard_metric = "slo_attainment"

    def __init__(self, router):
        self.router = router

    def sample(self) -> Dict[str, Any]:
        with self.router._lock:
            s = self.router._fleet_stats()
        return {
            "queue_depth": s["queue_depth"],
            "active_slots": s["active_slots"],
            "num_slots": s["num_slots"],
            "tpot_ms_p50": s.get("tpot_ms_p50"),
            "drain_ms": 0.0,
            "slo_attainment": s.get("slo_attainment"),
        }

    def current(self) -> Dict[str, Any]:
        cfg = self.router.config
        values = {
            "fleet.admission": cfg.admission,
            "fleet.slo_ttft_ms": cfg.slo_ttft_ms,
            "fleet.affinity_weight": cfg.affinity_weight_ms,
        }
        scaler = getattr(self.router, "autoscaler", None)
        if scaler is not None:
            values.update(
                {
                    "fleet.min_replicas": scaler.config.min_replicas,
                    "fleet.max_replicas": scaler.config.max_replicas,
                    "fleet.scale_cooldown_s": scaler.config.scale_cooldown_s,
                    "fleet.target_util": scaler.config.target_util,
                }
            )
        return values

    def pending(self) -> bool:
        return False

    def apply(self, knob: str, value: Any) -> bool:
        cfg = self.router.config
        with self.router._lock:
            if knob == "fleet.admission":
                if value not in ("queue", "shed"):
                    return False
                cfg.admission = str(value)
                return True
            if knob == "fleet.slo_ttft_ms":
                cfg.slo_ttft_ms = float(value)
                return True
            if knob == "fleet.affinity_weight":
                cfg.affinity_weight_ms = float(value)
                return True
        scaler = getattr(self.router, "autoscaler", None)
        if scaler is None:
            return False
        # autoscaler bounds move as a pair-consistent config: the scaler
        # reads them fresh each decision tick, so the change is instant
        scfg = scaler.config
        if knob == "fleet.min_replicas":
            v = int(value)
            if v < 1 or v > scfg.max_replicas:
                return False
            scfg.min_replicas = v
            return True
        if knob == "fleet.max_replicas":
            v = int(value)
            if v < scfg.min_replicas:
                return False
            scfg.max_replicas = v
            return True
        if knob == "fleet.scale_cooldown_s":
            scfg.scale_cooldown_s = max(0.0, float(value))
            return True
        if knob == "fleet.target_util":
            v = float(value)
            if not scfg.low_util < v <= 1.0:
                return False
            scfg.target_util = v
            return True
        return False
