"""Bottleneck diagnoser: telemetry window -> classified verdict + evidence.

The first stage of the telemetry→config loop (docs/autotune.md "Continuous
tuning"). Input is whatever the run already emits — aggregated gauge
windows from a live controller, a :func:`maggy_tpu.telemetry.attribution.
analyze` result (the SAME code path ``tools/analyze_trace.py`` renders),
or a raw merged-JSONL record list — and output is a :class:`Diagnosis`:
one dominant bottleneck per window plus an evidence struct naming exactly
the metrics (and the derived shares) behind the verdict, so every
``autopilot.diagnosis`` telemetry event is auditable after the fact.

Taxonomy (per scope, in precedence order — the first matching rule wins):

* ``train``: ``memory_bound`` (HBM headroom below the floor) →
  ``input_bound`` (input-pipeline wait dominates the step wall) →
  ``drain_bound`` (lagged-broadcast host reads dominate) →
  ``compute_bound`` (the device is the bottleneck — the healthy state).
* ``serve``: ``memory_bound`` → ``queue_bound`` (slots saturated with a
  backlog at least one wave deep — admission/capacity limited) →
  ``drain_bound`` (host token-drain time dominates per-token decode) →
  ``idle`` (nothing queued or running) → ``compute_bound``.

Thresholds are explicit :class:`Thresholds` fields, not magic numbers, so
tests and operators can reason about (and tighten) the classifier.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, Optional

BOTTLENECKS = (
    "input_bound",
    "compute_bound",
    "drain_bound",
    "queue_bound",
    "memory_bound",
    "idle",
)


@dataclasses.dataclass(frozen=True)
class Thresholds:
    """Classifier knobs: what 'dominates' means, per rule."""

    input_share: float = 0.25  # input wait / step wall
    drain_share: float = 0.20  # metrics drain / step wall (train)
    serve_drain_share: float = 0.25  # drain ms / per-token time (serve)
    queue_waves: float = 1.0  # backlog depth in units of num_slots
    slot_utilization: float = 0.85  # active/num_slots to call "saturated"
    min_headroom: float = 0.05  # HBM headroom fraction floor


@dataclasses.dataclass(frozen=True)
class Diagnosis:
    """One window's verdict. ``evidence`` holds the raw metric values the
    rule read; ``shares`` the derived fractions it compared; ``reason`` a
    one-line human account. All JSON-safe by construction."""

    bottleneck: str
    scope: str  # "train" | "serve"
    evidence: Dict[str, float]
    shares: Dict[str, float]
    reason: str

    def to_dict(self) -> Dict[str, Any]:
        return {
            "bottleneck": self.bottleneck,
            "scope": self.scope,
            "evidence": dict(self.evidence),
            "shares": {k: round(v, 4) for k, v in self.shares.items()},
            "reason": self.reason,
        }


def _f(window: Dict[str, Any], key: str, default: float = 0.0) -> float:
    v = window.get(key)
    try:
        return default if v is None else float(v)
    except (TypeError, ValueError):
        return default


# ------------------------------------------------------------------- train


def diagnose_train(
    window: Dict[str, Any], thresholds: Optional[Thresholds] = None
) -> Diagnosis:
    """Classify a training window. Expected keys (means over the window):
    ``step_time_ms``, ``input_wait_ms``, ``metrics_drain_ms``, optionally
    ``memory_headroom_frac`` — exactly the gauges ``Trainer.fit`` emits and
    ``attribution.attribute_steps`` aggregates."""
    th = thresholds or Thresholds()
    step = _f(window, "step_time_ms")
    wait = _f(window, "input_wait_ms")
    drain = _f(window, "metrics_drain_ms")
    headroom = window.get("memory_headroom_frac")
    evidence = {
        "step_time_ms": round(step, 3),
        "input_wait_ms": round(wait, 3),
        "metrics_drain_ms": round(drain, 3),
    }
    if headroom is not None:
        evidence["memory_headroom_frac"] = round(float(headroom), 4)
    shares = {}
    if step > 0:
        shares["input"] = wait / step
        shares["drain"] = drain / step
        shares["compute"] = max(0.0, 1.0 - shares["input"] - shares["drain"])

    if headroom is not None and float(headroom) < th.min_headroom:
        return Diagnosis(
            "memory_bound", "train", evidence, shares,
            f"HBM headroom {float(headroom):.1%} below the "
            f"{th.min_headroom:.0%} floor",
        )
    if step <= 0:
        return Diagnosis(
            "idle", "train", evidence, shares, "no measured steps in window"
        )
    if shares["input"] >= th.input_share and shares["input"] >= shares["drain"]:
        return Diagnosis(
            "input_bound", "train", evidence, shares,
            f"input_wait_ms is {shares['input']:.0%} of step_time_ms "
            f"(threshold {th.input_share:.0%})",
        )
    if shares["drain"] >= th.drain_share:
        return Diagnosis(
            "drain_bound", "train", evidence, shares,
            f"metrics_drain_ms is {shares['drain']:.0%} of step_time_ms "
            f"(threshold {th.drain_share:.0%})",
        )
    return Diagnosis(
        "compute_bound", "train", evidence, shares,
        f"device compute holds {shares['compute']:.0%} of the step wall",
    )


# ------------------------------------------------------------------- serve


def diagnose_serve(
    window: Dict[str, Any], thresholds: Optional[Thresholds] = None
) -> Diagnosis:
    """Classify a serving window from ``Scheduler.stats()``-shaped metrics
    (queue_depth, active_slots, num_slots, tpot_ms_p50, ...) plus the
    engine's ``drain_ms`` and an optional ``memory_headroom_frac``."""
    th = thresholds or Thresholds()
    queue = _f(window, "queue_depth")
    active = _f(window, "active_slots")
    slots = max(1.0, _f(window, "num_slots", 1.0))
    tpot = _f(window, "tpot_ms_p50")
    drain = _f(window, "drain_ms")
    headroom = window.get("memory_headroom_frac")
    evidence = {
        "queue_depth": round(queue, 2),
        "active_slots": round(active, 2),
        "num_slots": slots,
        "tpot_ms_p50": round(tpot, 3),
        "drain_ms": round(drain, 3),
    }
    shares = {
        "queue_waves": queue / slots,
        "slot_utilization": active / slots,
        "drain": (drain / tpot) if tpot > 0 else 0.0,
    }
    if headroom is not None:
        evidence["memory_headroom_frac"] = round(float(headroom), 4)
        if float(headroom) < th.min_headroom:
            return Diagnosis(
                "memory_bound", "serve", evidence, shares,
                f"HBM headroom {float(headroom):.1%} below the "
                f"{th.min_headroom:.0%} floor",
            )
    if (
        shares["queue_waves"] >= th.queue_waves
        and shares["slot_utilization"] >= th.slot_utilization
    ):
        return Diagnosis(
            "queue_bound", "serve", evidence, shares,
            f"backlog {queue:.0f} >= {th.queue_waves:.0%} of {slots:.0f} "
            f"slots with {shares['slot_utilization']:.0%} occupancy",
        )
    if shares["drain"] >= th.serve_drain_share:
        return Diagnosis(
            "drain_bound", "serve", evidence, shares,
            f"host drain is {shares['drain']:.0%} of per-token time "
            f"(threshold {th.serve_drain_share:.0%})",
        )
    if active == 0 and queue == 0:
        return Diagnosis(
            "idle", "serve", evidence, shares, "no queued or active requests"
        )
    return Diagnosis(
        "compute_bound", "serve", evidence, shares,
        "device decode holds the per-token time",
    )


# --------------------------------------------- attribution-backed diagnosis


def diagnose_steps(
    step_summary: Dict[str, Any], thresholds: Optional[Thresholds] = None
) -> Diagnosis:
    """Training diagnosis straight from an ``attribution.analyze`` result's
    ``step_summary`` — the offline twin of the live window path, reading
    the exact numbers ``tools/analyze_trace.py`` prints."""
    return diagnose_train(
        {
            "step_time_ms": step_summary.get("step_ms_mean"),
            "input_wait_ms": step_summary.get("input_wait_ms_mean"),
            "metrics_drain_ms": step_summary.get("metrics_drain_ms_mean"),
        },
        thresholds,
    )


def diagnose_requests(
    request_summary: Dict[str, Any], thresholds: Optional[Thresholds] = None
) -> Diagnosis:
    """Serving diagnosis from an ``attribution.analyze`` result's
    ``request_summary``: the component *shares* (queue/prefill/decode/...)
    name the dominant per-request cost directly."""
    th = thresholds or Thresholds()
    shares = dict(request_summary.get("components_share") or {})
    evidence = {
        k: round(v, 3)
        for k, v in (request_summary.get("components_ms_mean") or {}).items()
    }
    evidence["requests"] = request_summary.get("requests", 0)
    if not shares:
        return Diagnosis(
            "idle", "serve", evidence, shares, "no attributed requests"
        )
    queue_share = shares.get("queue", 0.0) + shares.get("route", 0.0)
    if queue_share >= max(th.queue_waves * 0.25, 0.25):
        return Diagnosis(
            "queue_bound", "serve", evidence, shares,
            f"queue+route hold {queue_share:.0%} of mean request e2e",
        )
    return Diagnosis(
        "compute_bound", "serve", evidence, shares,
        "prefill/decode dominate mean request e2e",
    )


def diagnose_records(
    records: Iterable[Dict[str, Any]],
    scope: str = "train",
    thresholds: Optional[Thresholds] = None,
) -> Diagnosis:
    """Diagnose directly from raw merged-JSONL records (the sink format),
    routing through the shared attribution module."""
    from maggy_tpu.telemetry import attribution

    if scope == "serve":
        rows = attribution.attribute_requests(records)
        return diagnose_requests(attribution.summarize_requests(rows), thresholds)
    return diagnose_steps(attribution.attribute_steps(records), thresholds)
