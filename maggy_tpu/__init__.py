"""maggy-tpu: distribution-transparent ML experiments on TPU.

A brand-new TPU-native framework with the capabilities of logicalclocks/maggy —
one "oblivious" ``train_fn`` runs unchanged as a local run, an async HPO trial,
an ablation trial, or one shard of a pjit/GSPMD distributed training job.

Public surface mirrors the reference (``from maggy import experiment, Searchspace``,
maggy/__init__.py):

    from maggy_tpu import experiment, Searchspace
    from maggy_tpu.config import HyperparameterOptConfig
    result = experiment.lagom(train_fn=train, config=cfg)
"""

from maggy_tpu.version import __version__
from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial
from maggy_tpu.reporter import Reporter

__all__ = ["__version__", "Searchspace", "Trial", "Reporter"]


def __getattr__(name):
    # Lazy imports keep `import maggy_tpu` light (no jax import for pure-HPO use).
    # importlib (not `from maggy_tpu import ...`) so a missing submodule raises
    # ImportError instead of recursing through this hook.
    import importlib

    if name == "experiment":
        return importlib.import_module("maggy_tpu.experiment")
    if name == "AblationStudy":
        return importlib.import_module("maggy_tpu.ablation").AblationStudy
    if name == "tensorboard":
        return importlib.import_module("maggy_tpu.tensorboard")
    if name == "callbacks":
        return importlib.import_module("maggy_tpu.callbacks")
    if name == "initialize_data_plane":
        return importlib.import_module("maggy_tpu.core.pod").initialize_data_plane
    raise AttributeError(f"module 'maggy_tpu' has no attribute {name!r}")
