"""Live experiment monitor — the jupyter/sparkmagic LOG-polling client
(reference rpc.py:490-502 + optimization_driver.py:412-431) as a CLI:

    python -m maggy_tpu.monitor <host:port> <secret> [--interval 1.0]
    python -m maggy_tpu.monitor --latest            # auto-attach via registry
    python -m maggy_tpu.monitor --app <app_id>      # attach a specific run

Polls the driver's LOG verb, printing shipped log lines and the progress bar.
Auto-attach resolves host/port/secret from the driver registry every running
driver writes under ``<MAGGY_TPU_LOG_ROOT>/.drivers/`` (the reference's
Hopsworks REST driver registry, hopsworks.py:136-190, on the storage seam);
explicit host:port + secret still works against any reachable driver.
"""

from __future__ import annotations

import argparse
import sys
import time


def resolve_target(env, app_id=None):
    """(host, port, secret) from the driver registry. ``app_id=None`` picks
    the newest record. Raises LookupError when nothing is registered."""
    if app_id:
        rec = env.lookup_driver(app_id)
        if rec is None:
            raise LookupError(
                f"No driver registered for app {app_id!r} under {env.root}"
            )
    else:
        recs = env.list_drivers()
        if not recs:
            raise LookupError(f"No drivers registered under {env.root}")
        rec = recs[0]
    host = rec["host"] if rec.get("scope", "pod") == "pod" else "127.0.0.1"
    return host, int(rec["port"]), rec.get("secret", "")


def monitor(host: str, port: int, secret: str, interval: float = 1.0) -> int:
    from maggy_tpu.core import rpc
    from maggy_tpu.exceptions import RpcError

    client = rpc.Client((host, port), partition_id=-1, secret=secret)
    last_progress = ""
    try:
        while True:
            try:
                reply = client._request({"type": "LOG"})
            except RpcError as e:
                if "rejected" in str(e):
                    print(f"[monitor] {e}", flush=True)  # e.g. bad secret
                    return 1
                print("[monitor] driver gone; exiting", flush=True)
                return 0
            for line in reply.get("logs") or []:
                print(line, flush=True)
            progress = reply.get("progress") or ""
            if progress and progress != last_progress:
                print(progress, flush=True)
                last_progress = progress
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0
    finally:
        client.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("addr", nargs="?", help="driver host:port")
    parser.add_argument("secret", nargs="?", help="experiment secret")
    parser.add_argument("--app", help="auto-attach this app id via the registry")
    parser.add_argument(
        "--latest", action="store_true",
        help="auto-attach the newest registered driver",
    )
    parser.add_argument("--interval", type=float, default=1.0)
    args = parser.parse_args(argv)
    if args.app or args.latest:
        from maggy_tpu.core.env import EnvSing

        try:
            host, port, secret = resolve_target(EnvSing.get_instance(), args.app)
        except LookupError as e:
            print(f"[monitor] {e}", file=sys.stderr)
            return 1
        print(f"[monitor] attaching to {host}:{port}", flush=True)
        return monitor(host, port, secret, args.interval)
    if not args.addr or args.secret is None:
        parser.error("need <addr> <secret>, or --app/--latest for auto-attach")
    from maggy_tpu.core.pod import _parse_addr

    try:
        host, port = _parse_addr(args.addr)
    except ValueError as e:
        parser.error(str(e))
    return monitor(host, port, args.secret, args.interval)


if __name__ == "__main__":
    sys.exit(main())
