"""Live experiment monitor — the jupyter/sparkmagic LOG-polling client
(reference rpc.py:490-502 + optimization_driver.py:412-431) as a CLI:

    python -m maggy_tpu.monitor <host:port> <secret> [--interval 1.0]

Polls the driver's LOG verb, printing shipped log lines and the progress bar.
Works against any running experiment (the driver logs its address at startup;
in-process, ``experiment.CURRENT_DRIVER.server`` has host/port/secret).
"""

from __future__ import annotations

import argparse
import sys
import time


def monitor(host: str, port: int, secret: str, interval: float = 1.0) -> int:
    from maggy_tpu.core import rpc
    from maggy_tpu.exceptions import RpcError

    client = rpc.Client((host, port), partition_id=-1, secret=secret)
    last_progress = ""
    try:
        while True:
            try:
                reply = client._request({"type": "LOG"})
            except RpcError as e:
                if "rejected" in str(e):
                    print(f"[monitor] {e}", flush=True)  # e.g. bad secret
                    return 1
                print("[monitor] driver gone; exiting", flush=True)
                return 0
            for line in reply.get("logs") or []:
                print(line, flush=True)
            progress = reply.get("progress") or ""
            if progress and progress != last_progress:
                print(progress, flush=True)
                last_progress = progress
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0
    finally:
        client.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("addr", help="driver host:port")
    parser.add_argument("secret", help="experiment secret")
    parser.add_argument("--interval", type=float, default=1.0)
    args = parser.parse_args(argv)
    from maggy_tpu.core.pod import _parse_addr

    try:
        host, port = _parse_addr(args.addr)
    except ValueError as e:
        parser.error(str(e))
    return monitor(host, port, args.secret, args.interval)


if __name__ == "__main__":
    sys.exit(main())
