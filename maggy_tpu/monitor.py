"""Live experiment monitor — the jupyter/sparkmagic LOG-polling client
(reference rpc.py:490-502 + optimization_driver.py:412-431) as a CLI:

    python -m maggy_tpu.monitor <host:port> <secret> [--interval 1.0]
    python -m maggy_tpu.monitor --latest            # auto-attach via registry
    python -m maggy_tpu.monitor --app <app_id>      # attach a specific run

Polls the driver's LOG verb, printing shipped log lines and the progress bar.
Auto-attach resolves host/port/secret from the driver registry every running
driver writes under ``<MAGGY_TPU_LOG_ROOT>/.drivers/`` (the reference's
Hopsworks REST driver registry, hopsworks.py:136-190, on the storage seam);
explicit host:port + secret still works against any reachable driver.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _record_addr(rec):
    """Connectable (host, port) for a registry record: pod records advertise
    a cross-host hostname, local records mean loopback."""
    host = rec["host"] if rec.get("scope", "pod") == "pod" else "127.0.0.1"
    return host, int(rec["port"])


def _driver_alive(host, port, timeout: float = 0.75) -> bool:
    """True when something accepts TCP connections at host:port."""
    import socket

    try:
        socket.create_connection((host, port), timeout=timeout).close()
        return True
    except OSError:
        return False


def resolve_target(env, app_id=None):
    """(host, port, secret) from the driver registry. ``app_id=None`` picks
    the newest record whose driver still accepts connections — a SIGKILLed
    driver cannot unregister, so stale records are skipped AND pruned (best
    effort) instead of attaching to a dead address. Raises LookupError when
    nothing live is registered."""
    if app_id:
        rec = env.lookup_driver(app_id)
        if rec is None:
            raise LookupError(
                f"No driver registered for app {app_id!r} under {env.root}"
            )
    else:
        recs = env.list_drivers()
        if not recs:
            raise LookupError(f"No drivers registered under {env.root}")
        rec = None
        pruned = 0
        for candidate in recs:  # newest first
            host, port = _record_addr(candidate)
            if _driver_alive(host, port):
                rec = candidate
                break
            pruned += 1
            stale_app = candidate.get("app_id")
            if stale_app:
                print(
                    f"[monitor] pruning stale registry record for {stale_app} "
                    f"({host}:{port} refuses connections)",
                    file=sys.stderr,
                )
                env.unregister_driver(stale_app)
        if rec is None:
            raise LookupError(
                f"No live drivers under {env.root} "
                f"({pruned} stale record(s) pruned)"
            )
    host, port = _record_addr(rec)
    # address-only records (MAGGY_TPU_REGISTRY_NO_SECRET=1 drivers) rely on
    # the secret arriving out-of-band via env
    secret = rec.get("secret") or os.environ.get("MAGGY_TPU_SECRET", "")
    return host, port, secret


def _pid_key(kv):  # JSON stringifies pids; sort numerically
    try:
        return (0, int(kv[0]))
    except ValueError:
        return (1, kv[0])


def _heartbeat_line(seen: dict) -> str:
    """'last heartbeat: w0:1.2s w1:0.4s ...' — shared by the HPO and
    distributed dashboard branches."""
    return "last heartbeat: " + "  ".join(
        f"w{pid}:{age}s" for pid, age in sorted(seen.items(), key=_pid_key)
    )


def _telemetry_lines(status: dict, width: int) -> list:
    """Throughput/step-time panel from the per-worker telemetry snapshots the
    driver folds into STATUS (heartbeat-attached recorder state)."""
    snaps = status.get("telemetry") or {}
    if not snaps:
        return []
    lines = []
    gauges = {pid: (snap.get("gauges") or {}) for pid, snap in snaps.items()}
    tok_total = sum(
        g["tokens_per_sec"] for g in gauges.values() if "tokens_per_sec" in g
    )
    step_times = [g["step_time_ms"] for g in gauges.values() if "step_time_ms" in g]
    agg = []
    if tok_total:
        agg.append(f"throughput {tok_total:,.0f} tok/s")
    if step_times:
        agg.append(f"mean step {sum(step_times) / len(step_times):.1f}ms")
    lines.append(("-- telemetry --" + ("  " + "  ".join(agg) if agg else ""))[:width])
    for pid, snap in sorted(snaps.items(), key=_pid_key):
        g = snap.get("gauges") or {}
        parts = []
        if "step_time_ms" in g:
            parts.append(f"{g['step_time_ms']:.1f}ms/step")
        if "steps_per_sec" in g:
            parts.append(f"{g['steps_per_sec']:.2f}st/s")
        if "tokens_per_sec" in g:
            parts.append(f"{g['tokens_per_sec']:,.0f}tok/s")
        # host-overlap health (docs/performance.md): time the step loop sat
        # waiting on the input pipeline, prefetch queue occupancy, and how
        # many steps behind the lagged metrics drain is running
        if "input_wait_ms" in g:
            parts.append(f"in-wait {g['input_wait_ms']:.1f}ms")
        if "prefetch_depth" in g:
            parts.append(f"prefetch {g['prefetch_depth']:.0f}")
        if "metrics_lag" in g:
            parts.append(f"lag {g['metrics_lag']:.0f}")
        if "mfu_est" in g:
            parts.append(f"mfu {100 * g['mfu_est']:.1f}%")
        # gradient-overlap health (docs/distributed.md "Gradient overlap &
        # ZeRO"): reduction buckets in the compiled step, and how much comm
        # is still exposed on the critical path vs hidden under backward
        if "train.bucket_count" in g:
            parts.append(f"buckets {g['train.bucket_count']:.0f}")
        if "train.comm_exposed_ms" in g:
            parts.append(
                f"comm {g['train.comm_exposed_ms']:.1f}ms exposed"
                f"/{g.get('train.comm_overlapped_ms', 0):.1f}ms hidden"
            )
        if "compile_time_ms" in g:
            parts.append(f"compile {g['compile_time_ms'] / 1e3:.1f}s")
        if "heartbeat_rtt_ms" in g:
            parts.append(f"hb {g['heartbeat_rtt_ms']:.1f}ms")
        if "serve.tokens_per_sec" in g:
            parts.append(f"{g['serve.tokens_per_sec']:,.0f}tok/s")
        if "serve.ttft_ms" in g:
            parts.append(f"ttft {g['serve.ttft_ms']:.0f}ms")
        if "serve.queue_depth" in g:
            parts.append(f"queue {g['serve.queue_depth']:.0f}")
        if "serve.active_slots" in g:
            parts.append(f"slots {g['serve.active_slots']:.0f}")
        if "serve.drain_ms" in g:
            parts.append(f"drain {g['serve.drain_ms']:.1f}ms")
        if "serve.decode_retraces" in g:
            parts.append(f"compiles {g['serve.decode_retraces']:.0f}")
        # paged KV cache (docs/serving.md "Paged KV cache")
        if "serve.pages_free" in g:
            parts.append(
                f"pages {g['serve.pages_free']:.0f} free"
                f"/{g.get('serve.pages_shared', 0):.0f} shared"
            )
        if "serve.handoff_ms" in g:
            parts.append(f"handoff {g['serve.handoff_ms']:.1f}ms")
        # capacity (docs/observability.md "Capacity"): ledger headroom and
        # page-heat buckets from the worker's metrics tick
        if "mem.headroom_pct" in g:
            parts.append(f"headroom {100 * g['mem.headroom_pct']:.0f}%")
        if "serve.pages_hot" in g:
            parts.append(
                f"heat {g['serve.pages_hot']:.0f}"
                f"/{g.get('serve.pages_warm', 0):.0f}"
                f"/{g.get('serve.pages_cold', 0):.0f} h/w/c"
            )
        if "fleet.healthy_replicas" in g:
            parts.append(f"healthy {g['fleet.healthy_replicas']:.0f}")
        c0 = snap.get("counters") or {}
        if "serve.prefix_hits" in c0:
            parts.append(
                f"prefix {c0['serve.prefix_hits']}/"
                f"{c0.get('serve.prefix_tokens_saved', 0)}tok"
            )
        if c0.get("serve.preemptions"):
            parts.append(f"preempt {c0['serve.preemptions']}")
        # autotuner progress (maggy_tpu/tune): candidate grid, AOT prunes,
        # and the best measured step time so far
        if "tune.candidates" in g:
            parts.append(f"tune {g['tune.candidates']:.0f} cand")
        if "tune.pruned_oom" in g:
            parts.append(f"oom-pruned {g['tune.pruned_oom']:.0f}")
        if "tune.best_step_time" in g:
            parts.append(f"best {g['tune.best_step_time']:.1f}ms/step")
        # resilience counters (maggy_tpu/resilience): what the runtime
        # absorbed — requeued/exhausted trials, quarantines, worker deaths,
        # elastic restarts, auto-resumes, preemption saves
        # elastic membership gauges: epoch/active width and the last
        # reshape-barrier latency (docs/resilience.md)
        if "resilience.active_slices" in g:
            parts.append(
                f"slices {g['resilience.active_slices']:.0f}"
                f"@e{g.get('resilience.membership_epoch', 0):.0f}"
            )
        if "resilience.reshape_ms" in g:
            parts.append(f"reshape {g['resilience.reshape_ms']:.0f}ms")
        c = snap.get("counters") or {}
        res = {
            k[len("resilience."):]: v
            for k, v in c.items()
            if k.startswith("resilience.")
        }
        if res:
            parts.append(
                "resilience "
                + " ".join(f"{k}={v}" for k, v in sorted(res.items()))
            )
        if "checkpoint_fallback" in c:
            parts.append(f"ckpt-fallback {c['checkpoint_fallback']}")
        # autopilot (maggy_tpu/autopilot): the telemetry→config loop's
        # scoreboard — windows diagnosed, guarded re-tunes kept, rollbacks
        if "autopilot.diagnoses" in c:
            parts.append(
                f"autopilot diag={c['autopilot.diagnoses']}"
                f" retune={c.get('autopilot.retunes', 0)}"
                f" rb={c.get('autopilot.rollbacks', 0)}"
            )
        if "flightrec.dumps" in c:
            # a stall dump is a red flag worth surfacing on the panel
            parts.append(f"STALL-DUMPS {c['flightrec.dumps']}")
        if "profcap.captures" in c:
            # an alert armed a profile capture — evidence is on disk
            parts.append(f"PROFCAP {c['profcap.captures']}")
        if not parts:
            continue
        tag = pid if pid == "driver" else f"w{pid}"
        lines.append(f"{tag}: " + "  ".join(parts)[: width - 5])
    return lines


def _latency_parts(sv: dict) -> list:
    """Histogram-derived latency summary for a serve/fleet SSTATS dict:
    TTFT percentiles, TPOT, and SLO attainment when a budget is set
    (docs/observability.md)."""
    parts = []
    if sv.get("ttft_ms_p50") is not None:
        parts.append(f"ttft p50 {sv['ttft_ms_p50']:.0f}ms")
    if sv.get("ttft_ms_p95") is not None:
        parts.append(f"p95 {sv['ttft_ms_p95']:.0f}ms")
    if sv.get("ttft_ms_p99") is not None:
        parts.append(f"p99 {sv['ttft_ms_p99']:.0f}ms")
    if sv.get("tpot_ms_p50") is not None:
        parts.append(f"tpot {sv['tpot_ms_p50']:.1f}ms")
    if sv.get("slo_attainment") is not None:
        parts.append(
            f"slo {100 * sv['slo_attainment']:.1f}%"
            f" ({sv.get('slo_ok', 0)}/{sv.get('slo_ok', 0) + sv.get('slo_miss', 0)})"
        )
    return parts


def _paging_parts(sv: dict) -> list:
    """Paged-KV summary for a serve/fleet SSTATS dict: pool occupancy,
    sharing, and preemptions (docs/serving.md "Paged KV cache"). The
    single-engine dict nests under ``paging``; the fleet aggregate is
    flat (summed over paged replicas)."""
    paging = sv.get("paging") or {}
    parts = []
    if paging.get("paged"):
        parts.append(
            f"pages {paging.get('pages_free', 0)}"
            f"/{paging.get('pages_total', 0)} free"
        )
        if paging.get("pages_shared"):
            parts.append(f"{paging['pages_shared']} shared")
    elif sv.get("pages_total"):
        parts.append(f"pages {sv.get('pages_free', 0)}/{sv['pages_total']} free")
        if sv.get("pages_shared"):
            parts.append(f"{sv['pages_shared']} shared")
    if sv.get("preemptions"):
        parts.append(f"preempt {sv['preemptions']}")
    return parts


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:,.0f}{unit}"
        n /= 1024
    return f"{n:,.0f}GB"  # unreachable; keeps the return type total


def _capacity_parts(sv: dict) -> list:
    """Capacity summary (docs/observability.md "Capacity"): HBM headroom
    from the memory ledger, page-heat buckets, free-pool fragmentation,
    resident-prefix KV, and profile-capture count. Single-engine SSTATS
    nests ``memory``/``profcap``/``prefix_residency`` dicts and
    ``paging.heat``; the fleet aggregate folds the same view (headroom =
    tightest replica) under ``capacity``."""
    parts = []
    mem = sv.get("memory") or {}
    cap = sv.get("capacity") or {}
    paging = sv.get("paging") or {}
    hp = mem.get("headroom_pct")
    if hp is None:
        hp = cap.get("headroom_pct")
    if hp is not None:
        parts.append(f"headroom {100 * float(hp):.0f}%")
    if mem.get("unattributed"):
        parts.append(f"unattrib {_fmt_bytes(mem['unattributed'])}")
    heat = paging.get("heat") or {}
    hot = heat.get("hot", cap.get("pages_hot"))
    warm = heat.get("warm", cap.get("pages_warm"))
    cold = heat.get("cold", cap.get("pages_cold"))
    if hot or warm or cold:
        parts.append(f"heat {hot or 0}/{warm or 0}/{cold or 0} h/w/c")
    frag = (paging.get("fragmentation") or {}).get("frag_ratio")
    if frag is None:
        frag = cap.get("fragmentation")
    if frag:
        parts.append(f"frag {100 * float(frag):.0f}%")
    resid = sv.get("prefix_residency") or {}
    rb = resid.get("resident_bytes", cap.get("resident_bytes"))
    rc = resid.get("resident_prefixes", cap.get("resident_prefixes"))
    if rb:
        parts.append(f"resident {rc or 0}pfx/{_fmt_bytes(rb)}")
    top = resid.get("top") or cap.get("top_prefixes") or []
    if top:
        t = top[0]
        parts.append(f"top {t.get('digest', '?')} x{t.get('hits', 0)}")
    # host-DRAM KV tier (docs/serving.md "Host-DRAM page tier"): pool
    # occupancy plus spill/fill traffic; the fleet aggregate sums the
    # same counters across enabled replicas under capacity.tier
    tier = sv.get("tier") or cap.get("tier") or {}
    if tier.get("enabled") or tier.get("replicas"):
        total = tier.get("host_pages_total", 0)
        free = tier.get("host_pages_free", 0)
        parts.append(
            f"tier {total - free}/{total}pg "
            f"{tier.get('resident_packs', 0)}pk "
            f"s{tier.get('spills', 0)}/f{tier.get('fills', 0)}"
        )
    pc = sv.get("profcap") or {}
    if pc.get("captures"):
        parts.append(f"PROFCAP {pc['captures']}")
    return parts


def _autopilot_line(sv: dict) -> list:
    """One panel line for the serve/fleet autopilot status the scheduler/
    router folds into SSTATS: last verdict, last guarded move, and the
    commit/rollback scoreboard (docs/autotune.md "Continuous tuning")."""
    ap = sv.get("autopilot")
    if not ap:
        return []
    parts = [f"autopilot[{ap.get('phase', '?')}]"]
    if ap.get("bottleneck"):
        parts.append(ap["bottleneck"])
    if ap.get("last_move"):
        parts.append(f"-> {ap['last_move']}")
    parts.append(
        f"(retunes {ap.get('retunes', 0)}, rollbacks {ap.get('rollbacks', 0)})"
    )
    return [" ".join(parts)]


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _spark(values: list, width: int = 16) -> str:
    """Unicode sparkline over the last ``width`` values, scaled to the
    window's own min/max (a trend display, not an absolute scale)."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_BLOCKS[0] * len(vals)
    span = hi - lo
    return "".join(
        _SPARK_BLOCKS[min(7, int((v - lo) / span * 8))] for v in vals
    )


def _trend_lines(sv: dict, width: int) -> list:
    """Sparkline trend block from the router's fleet time-series store
    (``trends`` in SSTATS — docs/observability.md "Time series")."""
    trends = sv.get("trends") or {}
    lines = []
    for name in sorted(trends):
        vals = trends[name]
        if not vals:
            continue
        short = name.split(".", 1)[-1]
        latest = vals[-1]
        shown = f"{latest:,.1f}" if isinstance(latest, float) else str(latest)
        lines.append(f"  ~ {short:<18} {_spark(vals)}  {shown}"[:width])
    return lines


def _alert_lines(sv: dict, width: int) -> list:
    """ALERTS line from the firing set the scheduler/router folds into
    SSTATS (``telemetry/alerts.py``); silent when nothing is firing."""
    alerts = sv.get("alerts") or []
    if not alerts:
        return []
    parts = []
    for a in alerts:
        tag = str(a.get("alert", "?"))
        tag = tag[len("alert."):] if tag.startswith("alert.") else tag
        if a.get("program"):
            tag += f":{a['program']}"
        if a.get("severity") == "critical":
            tag += "(!)"
        if a.get("replica") is not None:
            tag += f"@r{a['replica']}"
        parts.append(tag)
    return _wrap_parts([f"ALERTS[{len(alerts)}]:"] + parts, width)


def _wrap_parts(parts: list, width: int) -> list:
    """Flow ``parts`` onto as many panel lines as needed, breaking only at
    part boundaries — the latency summary outgrew one line, and truncating
    silently would hide the trailing parts (compile counts, SLO)."""
    lines, cur = [], ""
    for part in parts:
        cand = f"{cur}  {part}" if cur else part
        if cur and len(cand) > width:
            lines.append(cur)
            cur = part
        else:
            cur = cand
    if cur:
        lines.append(cur)
    return lines


def render_status(status: dict, width: int = 78) -> str:
    """Format a STATUS snapshot as a plain-ANSI dashboard panel (no external
    TUI dependency — the runtime image carries none)."""
    from maggy_tpu import util

    lines = []
    head = (
        f"{status.get('name', '?')} [{status.get('kind', '?')}] "
        f"state={status.get('state', '?')} app={status.get('app_id', '?')}"
        f"/{status.get('run_id', '?')}"
    )
    lines.append(head[:width])
    elapsed = status.get("elapsed_s")
    if status.get("trials_total") is not None:
        done = status.get("trials_done", 0)
        bar = util.progress_bar(done, status["trials_total"], width=28)
        lines.append(
            f"{bar}  running={status.get('trials_running', 0)} "
            f"stopped={status.get('early_stopped', 0)} "
            f"errors={status.get('errors', 0)}"
            + (f"  {elapsed:.0f}s" if elapsed is not None else "")
        )
        best = status.get("best")
        if best:
            params = ", ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in sorted(best.get("params", {}).items())
            )
            lines.append(
                f"best {status.get('direction', '')} "
                f"{best['metric']:.6g}  ({best['trial_id']})  {params}"[:width]
            )
        seen = status.get("last_seen") or {}
        if seen:  # pod-mode HPO: remote trial workers' heartbeat ages
            lines.append(_heartbeat_line(seen))
        # fault-recovery state: trials waiting out their retry backoff and
        # workers sitting in quarantine (seconds until probation release)
        requeued = status.get("trials_requeued")
        quarantined = status.get("quarantined") or {}
        if requeued or quarantined:
            q = "  ".join(
                f"w{pid}:{secs}s"
                for pid, secs in sorted(quarantined.items(), key=_pid_key)
            )
            lines.append(
                (
                    f"resilience: requeued={requeued or 0}"
                    + (f"  quarantined {q}" if q else "")
                )[:width]
            )
        lines.extend(_telemetry_lines(status, width))
        tail = status.get("controller_log") or []
        if tail:
            lines.append(f"-- {status.get('controller', 'controller')} decisions --")
            lines.extend(line[:width] for line in tail[-8:])
    elif status.get("fleet") is not None:
        # serving fleet panel (maggy_tpu/serve/fleet Router STATUS verb):
        # aggregate line + routing counters + one row per replica
        sv = status.get("serve") or {}
        fleet = status["fleet"]
        routing = fleet.get("routing") or {}
        lines.append(
            f"fleet: queue={sv.get('queue_depth', 0)}"
            f"  done={sv.get('requests_done', 0)}"
            f"  routed={routing.get('routed', 0)}"
            f"  requeued={routing.get('requeued', 0)}"
            f"  shed={routing.get('shed', 0)}"
            f"  respawned={routing.get('respawned', 0)}"
            + (
                f"  handoffs={routing.get('handoffs', 0)}"
                if routing.get("prefilled")
                else ""
            )
            + (f"  {elapsed:.0f}s" if elapsed is not None else "")
        )
        agg = []
        if sv.get("prefix_hits"):
            agg.append(
                f"prefix hits {sv['prefix_hits']} "
                f"({sv.get('prefix_tokens_saved', 0)} tok saved)"
            )
        agg.extend(_paging_parts(sv))
        agg.extend(_capacity_parts(sv))
        agg.extend(_latency_parts(sv))
        lines.extend(_wrap_parts(agg, width))
        lines.extend(line[:width] for line in _autopilot_line(sv))
        autoscale = sv.get("autoscale")
        if autoscale:
            n_up = sum(
                1
                for row in fleet.get("replicas") or []
                if row.get("state") in ("up", "draining", "quarantined")
            )
            last = autoscale.get("last_event") or {}
            lines.append(
                (
                    f"autoscale: {n_up} replicas"
                    f" [{autoscale.get('min_replicas', '?')}"
                    f"..{autoscale.get('max_replicas', '?')}]"
                    f"  phase={autoscale.get('phase', '?')}"
                    + (
                        f"  last={last.get('event', '')}"
                        f"({last.get('reason', '')})"
                        if last
                        else ""
                    )
                    + ("  AT-CAPACITY" if autoscale.get("at_capacity") else "")
                )[:width]
            )
        lines.extend(_alert_lines(sv, width))
        lines.extend(_trend_lines(sv, width))
        for row in fleet.get("replicas") or []:
            bar = util.progress_bar(
                row.get("active_slots", 0), max(row.get("num_slots", 1), 1),
                width=10,
            )
            tag = {
                "up": "up",
                "quarantined": "QUAR",
                "draining": "DRAI",
                "dead": "DEAD",
            }.get(row.get("state"), row.get("state", "?"))
            role = row.get("role")
            lines.append(
                (
                    f"  r{row.get('replica', '?')} [{tag:>4}]"
                    + (f" {role}" if role and role != "any" else "")
                    + f" slots {bar}"
                    f"  queue={row.get('queue_depth', 0)}"
                    f"  done={row.get('requests_done', 0)}"
                    f"  prefix={row.get('prefix_hits', 0)}"
                    + (
                        f"  restarts={row['restarts']}"
                        if row.get("restarts")
                        else ""
                    )
                )[:width]
            )
        lines.extend(_telemetry_lines(status, width))
    elif status.get("serve") is not None:
        # serving engine panel (maggy_tpu/serve ServeServer STATUS verb)
        sv = status["serve"]
        bar = util.progress_bar(
            sv.get("active_slots", 0), max(sv.get("num_slots", 1), 1), width=16
        )
        lines.append(
            f"slots {bar}"
            f"  queue={sv.get('queue_depth', 0)}"
            f"  done={sv.get('requests_done', 0)}"
            f"  failed={sv.get('requests_failed', 0)}"
            + (f"  {elapsed:.0f}s" if elapsed is not None else "")
        )
        parts = [f"{sv.get('tokens_out', 0):,} tokens"]
        if sv.get("tokens_per_sec"):
            parts.append(f"{sv['tokens_per_sec']:,.0f} tok/s")
        parts.extend(_paging_parts(sv))
        parts.extend(_capacity_parts(sv))
        parts.extend(_latency_parts(sv))
        compiles = (sv.get("compile_counts") or {}).get("decode")
        if compiles is not None:
            parts.append(f"decode compiles {compiles}")
        lines.extend(_wrap_parts(parts, width))
        lines.extend(line[:width] for line in _autopilot_line(sv))
        lines.extend(_alert_lines(sv, width))
        lines.extend(_telemetry_lines(status, width))
    elif status.get("workers_done") is not None:
        lines.append(
            f"workers {status['workers_done']}/{status.get('num_executors', '?')} done"
            + (
                f"  evaluator=partition {status['evaluator_partition']}"
                if status.get("evaluator_partition") is not None
                else ""
            )
            + (f"  {elapsed:.0f}s" if elapsed is not None else "")
        )
        if status.get("membership_epoch") is not None:
            # elastic membership (docs/resilience.md): current epoch and
            # which slices are in the data mesh vs the launch width
            active = status.get("active_slices") or []
            total = status.get("num_slices", len(active))
            lines.append(
                (
                    f"membership: epoch={status['membership_epoch']}"
                    f"  slices {len(active)}/{total} active {active}"
                    f"  min={status.get('min_slices', 1)}"
                    f"  mode={status.get('membership_mode', '?')}"
                )[:width]
            )
        seen = status.get("last_seen") or {}
        if seen:
            lines.append(_heartbeat_line(seen))
        lines.extend(_telemetry_lines(status, width))
    return "\n".join(lines)


def monitor(
    host: str, port: int, secret: str, interval: float = 1.0,
    dashboard: bool = False,
) -> int:
    from maggy_tpu.core import rpc
    from maggy_tpu.exceptions import RpcError

    from collections import deque

    try:
        client = rpc.Client((host, port), partition_id=-1, secret=secret)
    except RpcError as e:
        # A SIGKILLed driver cannot unregister, so a registry record may
        # outlive its driver — surface that instead of a raw traceback.
        print(
            f"[monitor] cannot reach driver at {host}:{port}: {e}\n"
            "[monitor] if you attached via --latest/--app, the registry "
            "record may be stale (driver killed before it could unregister)",
            file=sys.stderr,
        )
        return 1
    last_progress = ""
    # the LOG verb destructively drains the driver buffer, so the dashboard
    # accumulates every drained line locally and shows a rolling tail (plain
    # mode prints everything as it arrives)
    log_tail = deque(maxlen=500)
    try:
        while True:
            try:
                reply = client._request({"type": "LOG"})
                # capture the (destructively drained) lines BEFORE the STATUS
                # request — a driver dying between the two must not eat the
                # final log lines that explain why
                if dashboard:
                    log_tail.extend(reply.get("logs") or [])
                status = (
                    client._request({"type": "STATUS"}) if dashboard else None
                )
            except RpcError as e:
                for line in log_tail:
                    print(line, flush=True)
                if "rejected" in str(e):
                    print(f"[monitor] {e}", flush=True)  # e.g. bad secret
                    return 1
                print("[monitor] driver gone; exiting", flush=True)
                return 0
            if dashboard and status is not None:
                panel = render_status(status)
                # clear screen + home, then the panel and the rolling log tail
                sys.stdout.write("\x1b[2J\x1b[H" + panel + "\n")
                for line in list(log_tail)[-12:]:
                    sys.stdout.write(line + "\n")
                sys.stdout.flush()
            else:
                for line in reply.get("logs") or []:
                    print(line, flush=True)
                progress = reply.get("progress") or ""
                if progress and progress != last_progress:
                    print(progress, flush=True)
                    last_progress = progress
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0
    finally:
        client.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("addr", nargs="?", help="driver host:port")
    parser.add_argument("secret", nargs="?", help="experiment secret")
    parser.add_argument("--app", help="auto-attach this app id via the registry")
    parser.add_argument(
        "--latest", action="store_true",
        help="auto-attach the newest registered driver",
    )
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument(
        "--dashboard", action="store_true",
        help="full-screen status panel (STATUS verb) instead of a log tail",
    )
    args = parser.parse_args(argv)
    if args.app or args.latest:
        from maggy_tpu.core.env import EnvSing

        try:
            host, port, secret = resolve_target(EnvSing.get_instance(), args.app)
        except LookupError as e:
            print(f"[monitor] {e}", file=sys.stderr)
            return 1
        print(f"[monitor] attaching to {host}:{port}", flush=True)
        return monitor(host, port, secret, args.interval, dashboard=args.dashboard)
    if not args.addr or args.secret is None:
        parser.error("need <addr> <secret>, or --app/--latest for auto-attach")
    from maggy_tpu.core.pod import _parse_addr

    try:
        host, port = _parse_addr(args.addr)
    except ValueError as e:
        parser.error(str(e))
    return monitor(host, port, args.secret, args.interval, dashboard=args.dashboard)


if __name__ == "__main__":
    sys.exit(main())
