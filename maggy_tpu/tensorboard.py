"""Per-trial TensorBoard/metrics directory registry.

Capability parity with the reference ``maggy/tensorboard.py`` (tensorboard.py:
28-107): user code calls ``tensorboard.logdir()`` inside train_fn to get the
current trial's log directory, and the framework records hyperparameters per
trial. Differences forced by the TPU execution model: executors are threads in
one process (not separate Spark processes), so the registry is thread-local;
and the event writer is optional — metrics always land in ``events.jsonl``,
and additionally in real TF event files when ``tensorboard`` is importable.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

_local = threading.local()


def _env():
    from maggy_tpu.core.env import EnvSing

    return EnvSing.get_instance()


def _register(logdir: str) -> None:
    """Called by the trial executor at trial start (reference tensorboard.py:28-44)."""
    _local.logdir = logdir
    _local.writer = None


def _unregister() -> None:
    writer = getattr(_local, "writer", None)
    if writer is not None:
        try:
            writer.close()
        except Exception:
            pass
    _local.logdir = None
    _local.writer = None


def logdir() -> str:
    """The current trial's log directory; raises outside a trial context."""
    d = getattr(_local, "logdir", None)
    if d is None:
        raise RuntimeError(
            "tensorboard.logdir() is only available inside a running trial."
        )
    return d


def write_hparams(hparams: Dict[str, Any], logdir: Optional[str] = None) -> None:
    """Persist the trial's hyperparameters (reference tensorboard.py:104-107).
    Goes through the Env abstraction so GCS experiment dirs work too."""
    d = logdir or globals()["logdir"]()
    _env().dump(hparams, os.path.join(d, "hparams.json"))


def scalar(tag: str, value: float, step: int) -> None:
    """Log one scalar for the current trial: always to events.jsonl, and to TF
    event files when the tensorboard package is available."""
    d = logdir()
    with _env().open_file(os.path.join(d, "events.jsonl"), "a") as f:
        f.write(
            json.dumps(
                {"tag": tag, "value": float(value), "step": int(step), "ts": time.time()}
            )
            + "\n"
        )
    writer = getattr(_local, "writer", None)
    if writer is None:
        try:
            from tensorboard.summary.writer.event_file_writer import EventFileWriter  # noqa: F401
            from tensorboardX import SummaryWriter  # pragma: no cover

            writer = SummaryWriter(d)
        except Exception:
            writer = False  # probed once, unavailable
        _local.writer = writer
    if writer:
        try:  # pragma: no cover - only with tensorboardX installed
            writer.add_scalar(tag, float(value), int(step))
        except Exception:
            pass
