"""Per-trial TensorBoard/metrics directory registry.

Capability parity with the reference ``maggy/tensorboard.py`` (tensorboard.py:
28-107): user code calls ``tensorboard.logdir()`` inside train_fn to get the
current trial's log directory, and the framework records hyperparameters per
trial. Differences forced by the TPU execution model: executors are threads in
one process (not separate Spark processes), so the registry is thread-local;
and the event writer is optional — metrics always land in ``events.jsonl``,
and additionally in real TF event files when ``tensorboard`` is importable.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

_local = threading.local()


def _env():
    from maggy_tpu.core.env import EnvSing

    return EnvSing.get_instance()


def _register(logdir: str) -> None:
    """Called by the trial executor at trial start (reference tensorboard.py:28-44)."""
    _local.logdir = logdir
    _local.writer = None


def _unregister() -> None:
    writer = getattr(_local, "writer", None)
    if writer is not None:
        try:
            writer.close()
        except Exception:  # noqa: BLE001 - writer already broken; close is best-effort
            pass
    _local.logdir = None
    _local.writer = None


def logdir() -> str:
    """The current trial's log directory; raises outside a trial context."""
    d = getattr(_local, "logdir", None)
    if d is None:
        raise RuntimeError(
            "tensorboard.logdir() is only available inside a running trial."
        )
    return d


def write_hparams(hparams: Dict[str, Any], logdir: Optional[str] = None) -> None:
    """Persist the trial's hyperparameters (reference tensorboard.py:104-107).
    Goes through the Env abstraction so GCS experiment dirs work too. When the
    tensorboard package is available, additionally writes the HParams-plugin
    session-start summary so the trial shows as a session in the dashboard."""
    d = logdir or globals()["logdir"]()
    _env().dump(hparams, os.path.join(d, "hparams.json"))
    try:
        # NB: build the session-start proto by hand — the convenience module
        # tensorboard.plugins.hparams.summary imports all of tensorflow (~8s),
        # which would tax every experiment start; the raw protos are TF-free.
        from tensorboard.plugins.hparams import metadata, plugin_data_pb2

        info = plugin_data_pb2.SessionStartInfo(start_time_secs=time.time())
        for k, v in hparams.items():
            if isinstance(v, bool):
                info.hparams[k].bool_value = v
            elif isinstance(v, (int, float)):
                info.hparams[k].number_value = float(v)
            else:
                info.hparams[k].string_value = str(v)
        _write_tb_summary(
            d,
            _hparams_summary_pb(
                metadata.SESSION_START_INFO_TAG, session_start_info=info
            ),
        )
    except Exception:  # tensorboard absent / proto mismatch — json remains
        pass


def _hparams_summary_pb(tag: str, **plugin_fields):
    """One-tag Summary carrying HParamsPluginData (what the plugin's
    ``summary.experiment_pb``/``session_start_pb`` build, minus their
    tensorflow import)."""
    from tensorboard.compat.proto import summary_pb2
    from tensorboard.plugins.hparams import metadata, plugin_data_pb2

    data = plugin_data_pb2.HParamsPluginData(
        version=metadata.PLUGIN_DATA_VERSION, **plugin_fields
    )
    summ = summary_pb2.Summary()
    summ.value.add(
        tag=tag,
        metadata=summary_pb2.SummaryMetadata(
            plugin_data=summary_pb2.SummaryMetadata.PluginData(
                plugin_name=metadata.PLUGIN_NAME,
                content=data.SerializeToString(),
            )
        ),
    )
    return summ


def write_hparams_config(
    log_dir: str, searchspace, metrics=("metric",)
) -> bool:
    """Write the HParams plugin *experiment* config from a Searchspace so the
    TB HParams dashboard shows typed columns (reference tensorboard.py:47-102
    via tf.summary/hp.hparams_config; this is a pure-proto equivalent with no
    TF execution dependency). Returns False when tensorboard is unavailable."""
    try:
        from google.protobuf import struct_pb2
        from tensorboard.plugins.hparams import api_pb2, metadata
    except Exception:
        return False

    infos = []
    for key, typ in searchspace.names().items():
        vals = searchspace.get(key)
        if typ in ("DOUBLE", "INTEGER"):  # the plugin has no integer interval
            infos.append(
                api_pb2.HParamInfo(
                    name=key,
                    type=api_pb2.DATA_TYPE_FLOAT64,
                    domain_interval=api_pb2.Interval(
                        min_value=float(vals[0]), max_value=float(vals[1])
                    ),
                )
            )
        else:  # DISCRETE / CATEGORICAL
            domain = struct_pb2.ListValue()
            for v in vals:
                if isinstance(v, bool):
                    domain.values.add(bool_value=v)
                elif isinstance(v, (int, float)):
                    domain.values.add(number_value=float(v))
                else:
                    domain.values.add(string_value=str(v))
            if any(isinstance(v, str) for v in vals):
                dtype = api_pb2.DATA_TYPE_STRING
            elif all(isinstance(v, bool) for v in vals):
                dtype = api_pb2.DATA_TYPE_BOOL
            else:
                dtype = api_pb2.DATA_TYPE_FLOAT64
            infos.append(
                api_pb2.HParamInfo(name=key, type=dtype, domain_discrete=domain)
            )
    metric_infos = [
        api_pb2.MetricInfo(name=api_pb2.MetricName(tag=m)) for m in metrics
    ]
    experiment = api_pb2.Experiment(
        hparam_infos=infos,
        metric_infos=metric_infos,
        time_created_secs=time.time(),
    )
    summ = _hparams_summary_pb(metadata.EXPERIMENT_TAG, experiment=experiment)
    return _write_tb_summary(log_dir, summ)


# TFRecord framing for event files, first-party: tensorboard's own
# EventFileWriter resolves its filesystem through tensorboard.compat.tf, which
# imports all of tensorflow (~8s) when TF is installed — an unacceptable tax on
# every experiment start, and forcing its pure-python stub instead would
# repoint tensorboard.compat for the whole process. The format is four fields
# per record: u64le length, masked crc32c(length), data, masked crc32c(data).

_CRC32C_TABLE = None


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), table-driven; records here are tens of bytes."""
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> bytes:
    import struct

    crc = _crc32c(data)
    masked = ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF
    return struct.pack("<I", masked)


def _tfrecord(data: bytes) -> bytes:
    import struct

    length = struct.pack("<Q", len(data))
    return length + _masked_crc(length) + data + _masked_crc(data)


def _write_tb_summary(log_dir: str, summary) -> bool:
    """Append one Summary proto to an event file in ``log_dir``. Goes through
    the Env seam, so remote (gs://) experiment dirs work without tensorflow."""
    try:
        import socket

        from tensorboard.compat.proto import event_pb2

        event = event_pb2.Event(wall_time=time.time())
        # the hparams protos may come from TF's descriptor pool; same wire
        # format, so serialize/parse across
        event.summary.ParseFromString(summary.SerializeToString())
        version = event_pb2.Event(
            wall_time=time.time(), file_version="brain.Event:2"
        )
        import uuid

        # unique per call with no shared counter: executors are threads in one
        # process, and a racy counter + same-microsecond clock could collide
        path = os.path.join(
            log_dir,
            "events.out.tfevents.{:.6f}.{}.{}.mt".format(
                time.time(), socket.gethostname(), uuid.uuid4().hex[:8]
            ),
        )
        env = _env()
        env.mkdir(log_dir)
        with env.open_file(path, "wb") as f:
            f.write(_tfrecord(version.SerializeToString()))
            f.write(_tfrecord(event.SerializeToString()))
        return True
    except Exception:
        return False


def scalar(tag: str, value: float, step: int) -> None:
    """Log one scalar for the current trial: always to events.jsonl, and to TF
    event files when the tensorboard package is available."""
    d = logdir()
    with _env().open_file(os.path.join(d, "events.jsonl"), "a") as f:
        f.write(
            json.dumps(
                {"tag": tag, "value": float(value), "step": int(step), "ts": time.time()}
            )
            + "\n"
        )
    writer = getattr(_local, "writer", None)
    if writer is None:
        try:
            from tensorboard.summary.writer.event_file_writer import EventFileWriter  # noqa: F401
            from tensorboardX import SummaryWriter  # pragma: no cover

            writer = SummaryWriter(d)
        except Exception:
            writer = False  # probed once, unavailable
        _local.writer = writer
    if writer:
        try:  # pragma: no cover - only with tensorboardX installed
            writer.add_scalar(tag, float(value), int(step))
        except Exception:  # noqa: BLE001 - mirror is best-effort; json remains
            pass
