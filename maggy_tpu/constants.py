"""Framework-wide constants.

Capability parity with the reference's ``maggy/constants.py`` (constants.py:23-27):
the set of types a ``train_fn`` may return and a metric may take.
"""

import numpy as np


class USER_FCT:
    """Constraints on user-supplied training functions."""

    # A train_fn may return a scalar metric or a dict containing the
    # optimization key (reference constants.py:23-27).
    RETURN_TYPES = (float, int, np.number, dict)
    NUMERIC_TYPES = (float, int, np.number)


# Name of the metric file written next to an experiment's outputs.
METRIC_FILE = ".metric"
OUTPUTS_FILE = ".outputs.json"
HPARAMS_FILE = ".hparams.json"
TRIAL_FILE = "trial.json"
RESULT_FILE = "result.json"
EXPERIMENT_FILE = "experiment.json"

# RPC defaults.
RPC_BUFSIZE = 1 << 16
RPC_MAX_MESSAGE = 64 << 20  # 64 MiB hard cap on a single framed message
RPC_MAX_RETRIES = 3
RESERVATION_TIMEOUT = 600.0  # seconds (reference rpc.py:282-303)
POLL_INTERVAL = 0.05  # client suggestion-poll interval (reference uses 1s; we poll faster)
