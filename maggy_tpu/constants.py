"""Framework-wide constants.

Capability parity with the reference's ``maggy/constants.py`` (constants.py:23-27):
the set of types a ``train_fn`` may return and a metric may take.
"""

import os

import numpy as np


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ[name])
    except (KeyError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ[name])
    except (KeyError, ValueError):
        return default


class USER_FCT:
    """Constraints on user-supplied training functions."""

    # A train_fn may return a scalar metric or a dict containing the
    # optimization key (reference constants.py:23-27).
    RETURN_TYPES = (float, int, np.number, dict)
    NUMERIC_TYPES = (float, int, np.number)


# Name of the metric file written next to an experiment's outputs.
METRIC_FILE = ".metric"
OUTPUTS_FILE = ".outputs.json"
HPARAMS_FILE = ".hparams.json"
TRIAL_FILE = "trial.json"
RESULT_FILE = "result.json"
EXPERIMENT_FILE = "experiment.json"

# RPC defaults. Retry count and backoff base take env overrides so a pod
# launcher can widen the reconnect window fleet-wide without code changes
# (docs/resilience.md); the actual per-attempt delay is jittered in
# core/rpc.py so workers never reconnect in lockstep after a driver blip.
RPC_BUFSIZE = 1 << 16
RPC_MAX_MESSAGE = 64 << 20  # 64 MiB hard cap on a single framed message
RPC_MAX_RETRIES = _env_int("MAGGY_TPU_RPC_MAX_RETRIES", 3)
RPC_RETRY_BASE = _env_float("MAGGY_TPU_RPC_RETRY_BASE", 0.2)  # seconds
RESERVATION_TIMEOUT = 600.0  # seconds (reference rpc.py:282-303)
POLL_INTERVAL = 0.05  # client suggestion-poll interval (reference uses 1s; we poll faster)
