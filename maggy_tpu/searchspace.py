"""Hyperparameter search space.

Capability parity with the reference ``maggy/searchspace.py`` (searchspace.py:23-479):
four parameter types (DOUBLE/INTEGER/DISCRETE/CATEGORICAL), keyword construction,
``add`` validation, attribute access, random sampling, dict/list conversion, and a
bijective transform into the unit hypercube used by the model-based optimizers
(GP/TPE surrogates operate on the transformed space).

The implementation here is new: the unit-cube transform is vectorized over numpy and
INTEGER/DISCRETE/CATEGORICAL use half-open bucket encodings so that
``inverse_transform(transform(x)) == x`` exactly for every representable value.
"""

from __future__ import annotations

import json
import random
from typing import Any, Dict, Iterator, List, Tuple

import numpy as np



class Searchspace:
    """A set of named hyperparameters, each with a type and a feasible region.

    Construct from keyword arguments, where each value is a ``(type, region)``
    tuple — same shape as the reference API (searchspace.py:23-66)::

        sp = Searchspace(kernel=("INTEGER", [2, 8]), lr=("DOUBLE", [1e-5, 1e-1]))
        sp.add("activation", ("CATEGORICAL", ["relu", "gelu", "silu"]))

    DOUBLE and INTEGER take two-element ``[lower, upper]`` bounds (inclusive);
    DISCRETE takes an ordered list of numeric values; CATEGORICAL a list of
    arbitrary (JSON-serializable) values.
    """

    DOUBLE = "DOUBLE"
    INTEGER = "INTEGER"
    DISCRETE = "DISCRETE"
    CATEGORICAL = "CATEGORICAL"

    _TYPES = (DOUBLE, INTEGER, DISCRETE, CATEGORICAL)

    def __init__(self, **kwargs: Any):
        self._hparam_types: Dict[str, str] = {}
        self._hparam_values: Dict[str, list] = {}
        self._names: List[str] = []
        for name, value in kwargs.items():
            self.add(name, value)

    # ------------------------------------------------------------------ basic API

    def add(self, name: str, value: Any) -> None:
        """Add a hyperparameter; validates name, type and feasible region
        (reference searchspace.py:71-150)."""
        if not isinstance(name, str) or not name:
            raise ValueError(f"Hyperparameter name must be a non-empty str: {name!r}")
        if name.startswith("_") or hasattr(type(self), name):
            # Covers every class attribute/method, so dot access can never shadow API.
            raise ValueError(f"Hyperparameter name is reserved: {name}")
        if name in self._hparam_types:
            raise ValueError(f"Hyperparameter already exists: {name}")

        if not isinstance(value, (tuple, list)) or len(value) != 2:
            raise ValueError(
                "Hyperparameter value has to be of length two and format "
                f"(type, region): {name}, {value!r}"
            )

        param_type = str(value[0]).upper()
        region = value[1]
        if param_type not in self._TYPES:
            raise ValueError(
                f"Hyperparameter type has to be one of {self._TYPES}: {name}, {value[0]!r}"
            )
        if not isinstance(region, (tuple, list)) or len(region) == 0:
            raise ValueError(
                f"Hyperparameter feasible region cannot be empty: {name}, {region!r}"
            )
        region = list(region)

        if param_type in (self.DOUBLE, self.INTEGER):
            if len(region) != 2:
                raise ValueError(
                    "For DOUBLE or INTEGER parameters the region must be "
                    f"[lower, upper]: {name}, {region!r}"
                )
            lo, hi = region
            if param_type == self.DOUBLE:
                if not all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in region):
                    raise ValueError(
                        f"DOUBLE bounds must be numeric: {name}, {region!r}"
                    )
                lo, hi = float(lo), float(hi)
            else:
                if not all(isinstance(v, int) and not isinstance(v, bool) for v in region):
                    raise ValueError(
                        f"INTEGER bounds must be integers: {name}, {region!r}"
                    )
            if lo >= hi:
                raise ValueError(
                    f"Lower bound must be strictly less than upper bound: {name}, {region!r}"
                )
            region = [lo, hi]
        elif param_type == self.DISCRETE:
            if not all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in region):
                raise ValueError(
                    f"DISCRETE values must be numeric: {name}, {region!r}"
                )
            if len(set(region)) != len(region):
                raise ValueError(f"DISCRETE values must be unique: {name}, {region!r}")
            region = sorted(region)
        else:  # CATEGORICAL
            if len(set(map(repr, region))) != len(region):
                raise ValueError(f"CATEGORICAL values must be unique: {name}, {region!r}")

        self._hparam_types[name] = param_type
        self._hparam_values[name] = region
        self._names.append(name)
        # Dot access, same convenience as the reference (searchspace.py:55-57).
        setattr(self, name, region)

    def get(self, name: str, default: Any = None) -> Any:
        return self._hparam_values.get(name, default)

    def get_type(self, name: str) -> str:
        return self._hparam_types[name]

    def names(self) -> Dict[str, str]:
        """Return ``{name: type}`` for all hyperparameters."""
        return dict(self._hparam_types)

    def keys(self) -> List[str]:
        return list(self._names)

    def values(self) -> List[list]:
        return [self._hparam_values[n] for n in self._names]

    def items(self) -> Iterator[Dict[str, Any]]:
        """Iterate dicts of ``{name, type, values}`` (reference searchspace.py iteration)."""
        for n in self._names:
            yield {"name": n, "type": self._hparam_types[n], "values": self._hparam_values[n]}

    def __iter__(self):
        return self.items()

    def __len__(self) -> int:
        return len(self._names)

    def __contains__(self, name: str) -> bool:
        return name in self._hparam_types

    def to_dict(self) -> Dict[str, Tuple[str, list]]:
        """Round-trippable dict: ``Searchspace(**sp.to_dict())`` reproduces ``sp``."""
        return {n: (self._hparam_types[n], self._hparam_values[n]) for n in self._names}

    def json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, payload: str) -> "Searchspace":
        return cls(**{k: tuple(v) for k, v in json.loads(payload).items()})

    def __repr__(self) -> str:
        inner = ", ".join(
            f"{n}=({self._hparam_types[n]}, {self._hparam_values[n]})" for n in self._names
        )
        return f"Searchspace({inner})"

    # ------------------------------------------------------------------ sampling

    def sample(self, rng: random.Random = None) -> Dict[str, Any]:
        """Draw one uniform random configuration (reference searchspace.py:180-208)."""
        rng = rng or random
        out = {}
        for n in self._names:
            t = self._hparam_types[n]
            v = self._hparam_values[n]
            if t == self.DOUBLE:
                out[n] = rng.uniform(v[0], v[1])
            elif t == self.INTEGER:
                out[n] = rng.randint(v[0], v[1])
            else:
                out[n] = v[int(rng.random() * len(v)) % len(v)]
        return out

    def get_random_parameter_values(self, num: int, seed: int = None) -> List[Dict[str, Any]]:
        """Draw ``num`` random configurations."""
        rng = random.Random(seed) if seed is not None else random
        return [self.sample(rng) for _ in range(num)]

    # ------------------------------------------------- model-space transform

    # The optimizer-facing encoding maps every hyperparameter into [0, 1):
    #   DOUBLE      x -> (x - lo) / (hi - lo)
    #   INTEGER     x -> (x - lo + 0.5) / (hi - lo + 1)   (bucket midpoints)
    #   DISCRETE    value at sorted index i -> (i + 0.5) / k
    #   CATEGORICAL value at index i       -> (i + 0.5) / k
    # Inverse maps unit values back by bucketing, so round-trips are exact and any
    # point in the cube decodes to a valid configuration (reference
    # searchspace.py:266-353 provides the same capability via min-max scaling).

    def transform(self, params: Dict[str, Any]) -> np.ndarray:
        """Encode a configuration dict as a vector in the unit hypercube."""
        vec = np.empty(len(self._names), dtype=np.float64)
        for i, n in enumerate(self._names):
            t = self._hparam_types[n]
            v = self._hparam_values[n]
            x = params[n]
            if t == self.DOUBLE:
                vec[i] = (float(x) - v[0]) / (v[1] - v[0])
            elif t == self.INTEGER:
                vec[i] = (int(x) - v[0] + 0.5) / (v[1] - v[0] + 1)
            elif t == self.DISCRETE:
                vec[i] = (v.index(x) + 0.5) / len(v)
            else:
                vec[i] = (v.index(x) + 0.5) / len(v)
        return np.clip(vec, 0.0, 1.0)

    def inverse_transform(self, vec: np.ndarray) -> Dict[str, Any]:
        """Decode a unit-cube vector into a valid configuration dict."""
        vec = np.asarray(vec, dtype=np.float64)
        if vec.shape != (len(self._names),):
            raise ValueError(
                f"Expected vector of shape ({len(self._names)},), got {vec.shape}"
            )
        out = {}
        for i, n in enumerate(self._names):
            t = self._hparam_types[n]
            v = self._hparam_values[n]
            u = min(max(float(vec[i]), 0.0), 1.0)
            if t == self.DOUBLE:
                out[n] = v[0] + u * (v[1] - v[0])
            elif t == self.INTEGER:
                k = v[1] - v[0] + 1
                out[n] = v[0] + min(int(u * k), k - 1)
            else:
                k = len(v)
                out[n] = v[min(int(u * k), k - 1)]
        return out

    def transform_many(self, param_dicts: List[Dict[str, Any]]) -> np.ndarray:
        """Stack multiple configurations into an ``(n, d)`` design matrix."""
        if not param_dicts:
            return np.empty((0, len(self._names)), dtype=np.float64)
        return np.stack([self.transform(p) for p in param_dicts])

    # ------------------------------------------------- dict <-> list converters

    def dict_to_list(self, params: Dict[str, Any]) -> List[Any]:
        """Order parameter values by searchspace insertion order
        (reference searchspace.py:445-479)."""
        return [params[n] for n in self._names]

    def list_to_dict(self, values: List[Any]) -> Dict[str, Any]:
        if len(values) != len(self._names):
            raise ValueError(
                f"Expected {len(self._names)} values, got {len(values)}"
            )
        return dict(zip(self._names, values))

    def contains(self, params: Dict[str, Any]) -> bool:
        """Check that ``params`` names exactly this space and every value is feasible."""
        if set(params) != set(self._names):
            return False
        for n in self._names:
            t = self._hparam_types[n]
            v = self._hparam_values[n]
            x = params[n]
            if isinstance(x, bool) and t in (self.DOUBLE, self.INTEGER):
                return False
            if t == self.DOUBLE:
                if not isinstance(x, (int, float)) or not v[0] <= x <= v[1]:
                    return False
            elif t == self.INTEGER:
                if not isinstance(x, int) or not v[0] <= x <= v[1]:
                    return False
            elif x not in v:
                return False
        return True
