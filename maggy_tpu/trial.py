"""Trial record and state machine.

Capability parity with the reference ``maggy/trial.py`` (trial.py:24-176): the five
states PENDING/SCHEDULED/RUNNING/ERROR/FINALIZED, a deterministic trial id (16-char
md5 prefix over the sorted-params JSON — same scheme as trial.py:110-136 so
*optimization* trial ids are comparable across frameworks; ablation trial ids are
NOT comparable: the reference serializes groups via ``str(list(set))``
(loco.py:249), which depends on set iteration order, so we use a deterministic
``"|".join(sorted(group))`` under the ``ablated_component`` key instead),
thread-safe metric appends deduplicated by step, an early-stop flag, and JSON
(de)serialization.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


def _normalize_key(key: Any):
    """Keys stay native (str/int/float/bool — numpy scalars coerced) so that
    ``json.dumps(..., sort_keys=True)`` sorts and stringifies them exactly
    like the reference does (int keys sort numerically, not lexically);
    arbitrary objects raise instead of silently stringifying to a
    per-process repr."""
    if isinstance(key, (str, bool, int, float)):
        return key
    if isinstance(key, np.integer):
        return int(key)
    if isinstance(key, np.floating):
        return float(key)
    raise TypeError(
        f"Trial param key {key!r} of type {type(key).__name__} is not "
        "JSON-serializable; use str/int/float/bool keys"
    )


def _normalize_value(value: Any) -> Any:
    """Coerce numpy/jax scalars and containers to JSON-native types so that
    np.int64(5) and 5 hash to the same trial id and travel the RPC wire as
    numbers, not strings. Non-JSON-native leaves raise, like the reference
    (trial.py:110-136 json.dumps without a default)."""
    if isinstance(value, (str, bool)) or value is None:
        return value
    if isinstance(value, (int, float)):
        return value
    if isinstance(value, np.integer):
        return int(value)
    if isinstance(value, np.floating):
        return float(value)
    if isinstance(value, np.ndarray):
        if value.ndim == 0:
            return _normalize_value(value.item())
        return [_normalize_value(v) for v in value.tolist()]
    if hasattr(value, "ndim") and hasattr(value, "item"):
        # jax Arrays and other numpy-protocol arrays, any rank
        arr = np.asarray(value)
        return _normalize_value(arr.item() if arr.ndim == 0 else arr)
    if isinstance(value, (list, tuple)):
        return [_normalize_value(v) for v in value]
    if isinstance(value, dict):
        return {_normalize_key(k): _normalize_value(v) for k, v in value.items()}
    raise TypeError(
        f"Trial param value {value!r} of type {type(value).__name__} is not "
        "JSON-serializable; use int/float/str/bool/None or containers thereof"
    )


class Trial:
    PENDING = "PENDING"
    SCHEDULED = "SCHEDULED"
    RUNNING = "RUNNING"
    ERROR = "ERROR"
    FINALIZED = "FINALIZED"

    STATES = (PENDING, SCHEDULED, RUNNING, ERROR, FINALIZED)

    def __init__(
        self,
        params: Dict[str, Any],
        trial_type: str = "optimization",
        info_dict: Optional[Dict[str, Any]] = None,
    ):
        if not isinstance(params, dict):
            raise TypeError(f"Trial params must be a dict, got {type(params).__name__}")
        self.params = _normalize_value(dict(params))
        # params are normalized above; hash directly (compute_id re-normalizes
        # for external callers passing raw dicts)
        self.trial_type = trial_type
        self.trial_id = self._id_of_normalized(self.params)
        self.status = Trial.PENDING
        self.info_dict = dict(info_dict or {})

        self.final_metric: Optional[float] = None
        self.metric_history: List[float] = []
        self.step_history: List[int] = []
        self.start: Optional[float] = None
        self.duration: Optional[float] = None
        self.assigned_to: Optional[int] = None  # partition/executor id

        self._early_stop = False
        self._lock = threading.RLock()

    # ------------------------------------------------------------------ identity

    @staticmethod
    def compute_id(params: Dict[str, Any]) -> str:
        """16-char md5 prefix of the canonical params JSON — bit-identical to
        the reference's ids for JSON-native params (trial.py:110-136 uses
        ``json.dumps(params, sort_keys=True)`` with default separators; the
        reference suite's expected value "3d1cc9fdb1d4d001" passes here).
        Params are normalized first so numpy scalars hash like native ones;
        non-serializable values raise TypeError like the reference."""
        return Trial._id_of_normalized(_normalize_value(params))

    @staticmethod
    def _id_of_normalized(params: Dict[str, Any]) -> str:
        try:
            canonical = json.dumps(params, sort_keys=True)
        except TypeError as e:
            # json.dumps raises an opaque '<' comparison error on mixed-type
            # keys (the reference crashes identically; we just say why)
            if "not supported between instances" in str(e):
                raise TypeError(
                    f"Trial params must not mix key types within one dict "
                    f"(json.dumps sort_keys cannot order them): {params!r}"
                ) from e
            raise
        return hashlib.md5(canonical.encode("utf-8")).hexdigest()[:16]

    # ------------------------------------------------------------------ lifecycle

    def schedule(self, partition_id: int) -> None:
        with self._lock:
            self.status = Trial.SCHEDULED
            self.assigned_to = partition_id

    def begin(self) -> None:
        with self._lock:
            self.status = Trial.RUNNING
            self.start = time.time()

    def finalize(self, final_metric: Optional[float] = None) -> None:
        with self._lock:
            if final_metric is not None:
                self.final_metric = float(final_metric)
            self.status = Trial.FINALIZED
            if self.start is not None:
                self.duration = time.time() - self.start

    def error(self) -> None:
        with self._lock:
            self.status = Trial.ERROR

    def reset_for_retry(self) -> None:
        """Return a trial lost to a transient failure (worker death / RPC
        loss) to PENDING for requeue: identity and params are kept, run
        state — metrics, timing, assignment, early-stop flag — is cleared so
        the retry reports a clean history. The retry counter lives in
        ``info_dict['retries']`` and survives (the driver owns it)."""
        with self._lock:
            self.status = Trial.PENDING
            self.assigned_to = None
            self.start = None
            self.duration = None
            self.metric_history = []
            self.step_history = []
            self._early_stop = False

    # ------------------------------------------------------------------ metrics

    def append_metric(self, metric: float, step: Optional[int] = None) -> bool:
        """Record one (metric, step) observation; duplicate steps are dropped
        (reference trial.py:93-108). Returns True if recorded."""
        with self._lock:
            if step is None:
                step = self.step_history[-1] + 1 if self.step_history else 0
            step = int(step)
            if self.step_history and step <= self.step_history[-1]:
                return False
            self.metric_history.append(float(metric))
            self.step_history.append(step)
            return True

    @property
    def metrics(self) -> List[float]:
        with self._lock:
            return list(self.metric_history)

    def running_avg(self, up_to_step: Optional[int] = None) -> Optional[float]:
        """Mean of metrics observed at steps <= ``up_to_step`` (median-rule substrate)."""
        with self._lock:
            vals = [
                m
                for m, s in zip(self.metric_history, self.step_history)
                if up_to_step is None or s <= up_to_step
            ]
        if not vals:
            return None
        return sum(vals) / len(vals)

    # ------------------------------------------------------------------ early stop

    def set_early_stop(self) -> None:
        with self._lock:
            self._early_stop = True

    def get_early_stop(self) -> bool:
        with self._lock:
            return self._early_stop

    # ------------------------------------------------------------------ serialization

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "trial_id": self.trial_id,
                "trial_type": self.trial_type,
                "params": self.params,
                "status": self.status,
                "final_metric": self.final_metric,
                "metric_history": list(self.metric_history),
                "step_history": list(self.step_history),
                "start": self.start,
                "duration": self.duration,
                "early_stop": self._early_stop,
                "info_dict": self.info_dict,
            }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, default=str)

    @classmethod
    def from_dict(cls, payload: Dict[str, Any]) -> "Trial":
        t = cls(payload["params"], payload.get("trial_type", "optimization"))
        t.status = payload.get("status", Trial.PENDING)
        t.final_metric = payload.get("final_metric")
        t.metric_history = [float(m) for m in payload.get("metric_history", [])]
        t.step_history = [int(s) for s in payload.get("step_history", [])]
        t.start = payload.get("start")
        t.duration = payload.get("duration")
        t._early_stop = bool(payload.get("early_stop", False))
        t.info_dict = payload.get("info_dict", {}) or {}
        return t

    @classmethod
    def from_json(cls, payload: str) -> "Trial":
        return cls.from_dict(json.loads(payload))

    def __repr__(self) -> str:
        return (
            f"Trial(id={self.trial_id}, status={self.status}, "
            f"final_metric={self.final_metric}, params={self.params})"
        )
