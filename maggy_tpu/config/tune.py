"""Autotuner configuration.

``TuneConfig`` declares the system-configuration search the autotuner
(:mod:`maggy_tpu.tune`) explores: candidate mesh shapes (``ShardingSpec``
presets or instances), global batch sizes, microbatch counts, remat policies
and flash tile sizes — plus the two-stage budget controls: the static stage's
HBM budget for AOT pruning and the measured stage's ASHA step schedule.

This is deliberately NOT a :class:`~maggy_tpu.config.base.LagomConfig`: the
autotuner is not an experiment kind of its own — its measured stage *builds*
a ``HyperparameterOptConfig`` over the surviving candidates and runs it
through the ordinary HPO driver, so system tuning reuses the exact trial
machinery hyperparameter tuning does.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union


class TuneConfig:
    """Search space + budgets for :func:`maggy_tpu.tune.tune`.

    :param presets: candidate mesh shapes — preset names (``"dp"``,
        ``"fsdp"``, ``"2d"``, ...) or :class:`ShardingSpec` instances
        (rescaled to the live device count via ``scaled_to``).
    :param batch_sizes: candidate *global* batch sizes.
    :param microbatches: candidate ``Trainer.n_microbatches`` values. Only
        meaningful for presets with a pipeline (``pp``) axis; ``None`` keeps
        the trainer default. Non-pp candidates collapse to ``None``.
    :param remat_policies: candidate remat policies by name (see
        ``maggy_tpu.models.transformer.REMAT_POLICIES``). ``None`` leaves the
        model exactly as configured; a name forces ``remat=True`` with that
        policy (only for models whose config carries those fields).
    :param flash_blocks: candidate flash-attention backward tile sizes as
        ``(block_q, block_k)`` tuples, or ``None`` for the kernel's
        auto-tuned default (applied via the ``MAGGY_TPU_FLASH_BWD_Q/K``
        knobs the bench playbook already uses).
    :param seq_len: sequence length of the synthetic tuning batches.
    :param hbm_budget_bytes: per-device memory budget for the static stage's
        AOT prune. ``None`` asks the device (``memory_stats()["bytes_limit"]``
        where available — TPU/GPU); if the backend reports nothing (CPU),
        no candidate is memory-pruned.
    :param measure: run the measured stage (short trials through the HPO
        driver + ASHA). ``False`` picks the winner from the static
        flops/bytes ranking alone — the cheap mode bench.py uses.
    :param steps_per_unit: train steps per unit of ASHA budget; a trial at
        rung budget ``b`` runs ``b * steps_per_unit`` measured steps.
    :param asha_reduction_factor / asha_resource_min / asha_resource_max:
        the ASHA schedule over those step budgets.
    :param num_measure_trials: base-rung trial count for the measured stage;
        defaults to the number of static-stage survivors.
    :param cache: consult/persist the tuning cache on the env seam
        (``<root>/tune_cache/`` — local or ``gs://`` identically).
    :param max_candidates: hard cap on the enumerated candidate grid.
    :param learning_rate: optimizer LR for the tuning trials (adamw).
    """

    def __init__(
        self,
        presets: Sequence[Union[str, Any]] = ("dp", "fsdp", "2d"),
        batch_sizes: Sequence[int] = (8, 16, 32),
        microbatches: Sequence[Optional[int]] = (None,),
        remat_policies: Sequence[Optional[str]] = (None,),
        flash_blocks: Sequence[Optional[Tuple[int, int]]] = (None,),
        seq_len: int = 128,
        hbm_budget_bytes: Optional[int] = None,
        measure: bool = True,
        steps_per_unit: int = 4,
        asha_reduction_factor: int = 2,
        asha_resource_min: float = 1,
        asha_resource_max: float = 4,
        num_measure_trials: Optional[int] = None,
        cache: bool = True,
        max_candidates: int = 64,
        learning_rate: float = 1e-3,
        name: str = "autotune",
        seed: Optional[int] = 0,
    ):
        if not presets:
            raise ValueError("TuneConfig needs at least one mesh preset")
        if not batch_sizes or any(int(b) < 1 for b in batch_sizes):
            raise ValueError("batch_sizes must be positive ints")
        if seq_len < 2:
            raise ValueError("seq_len must be >= 2 (LM loss needs a target)")
        if steps_per_unit < 1:
            raise ValueError("steps_per_unit must be >= 1")
        if max_candidates < 1:
            raise ValueError("max_candidates must be >= 1")
        self.presets = tuple(presets)
        self.batch_sizes = tuple(int(b) for b in batch_sizes)
        self.microbatches = tuple(microbatches)
        self.remat_policies = tuple(remat_policies)
        self.flash_blocks = tuple(flash_blocks)
        self.seq_len = int(seq_len)
        self.hbm_budget_bytes = (
            None if hbm_budget_bytes is None else int(hbm_budget_bytes)
        )
        self.measure = bool(measure)
        self.steps_per_unit = int(steps_per_unit)
        self.asha_reduction_factor = int(asha_reduction_factor)
        self.asha_resource_min = asha_resource_min
        self.asha_resource_max = asha_resource_max
        self.num_measure_trials = num_measure_trials
        self.cache = bool(cache)
        self.max_candidates = int(max_candidates)
        self.learning_rate = float(learning_rate)
        self.name = name
        self.seed = seed

    def grid_fingerprint(self) -> dict:
        """The search-grid identity folded into the cache key: a cached
        winner is only valid for the grid it was chosen from."""
        def spec_key(p):
            return p if isinstance(p, str) else repr(p)

        return {
            "presets": [spec_key(p) for p in self.presets],
            "batch_sizes": list(self.batch_sizes),
            "microbatches": list(self.microbatches),
            "remat_policies": list(self.remat_policies),
            "flash_blocks": [list(b) if b else None for b in self.flash_blocks],
            "seq_len": self.seq_len,
            "measure": self.measure,
        }
