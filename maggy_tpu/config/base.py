"""Experiment configuration base classes.

Capability parity with the reference config system (``maggy/config/lagom.py:22-35``,
``base_config.py:23-38``): plain Python config objects whose concrete type selects
the experiment driver via singledispatch. Unlike the reference, none of these carry
a "Spark-only" guard — every experiment kind runs locally, on a single TPU host, or
on a pod.
"""

from __future__ import annotations

from typing import Any, Optional


class LagomConfig:
    """Base class for all experiment configs (reference config/lagom.py:22-35)."""

    def __init__(self, name: str, description: str = "", hb_interval: float = 1.0):
        if hb_interval <= 0:
            raise ValueError("hb_interval must be positive")
        self.name = name
        self.description = description
        self.hb_interval = float(hb_interval)


class BaseConfig(LagomConfig):
    """Run a train_fn once, unmodified, under experiment bookkeeping
    (reference config/base_config.py:23-38)."""

    def __init__(
        self,
        name: str = "base",
        description: str = "",
        hb_interval: float = 1.0,
        model: Any = None,
        dataset: Any = None,
        hparams: Optional[dict] = None,
        log_dir: Optional[str] = None,
    ):
        super().__init__(name, description, hb_interval)
        self.model = model
        self.dataset = dataset
        self.hparams = dict(hparams or {})
        self.log_dir = log_dir
