"""Distributed-training experiment config — the TPU-native successor to the
reference's ``TorchDistributedConfig`` (config/torch_distributed.py:28-87) and
``TfDistributedConfig`` (config/tf_distributed.py:26-59).

Where the reference selects among external engines (DDP / DeepSpeed ZeRO /
FairScale FSDP / TF MultiWorkerMirrored), this config declares a sharding layout
(:class:`~maggy_tpu.parallel.spec.ShardingSpec` or a preset string) and the
framework lowers it to pjit/GSPMD over a device mesh. ``zero_lvl`` is accepted for
migration convenience and mapped onto the equivalent GSPMD layout (ZeRO-1/2 ≈
optimizer/grad state sharded with params under fsdp; ZeRO-3 ≈ full fsdp).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Union

from maggy_tpu.config.base import LagomConfig
from maggy_tpu.parallel.spec import ShardingSpec


class DistributedConfig(LagomConfig):
    def __init__(
        self,
        module: Any = None,
        dataset: Any = None,
        hparams: Optional[dict] = None,
        sharding: Union[str, ShardingSpec] = "fsdp",
        mixed_precision: bool = True,
        remat: bool = False,
        zero_lvl: Optional[int] = None,
        zero_stage: Optional[int] = None,
        bucket_mb: Optional[float] = None,
        model: Any = None,
        process_data: Optional[Callable] = None,
        name: str = "tpuDist",
        hb_interval: float = 1.0,
        description: str = "",
        num_executors: Optional[int] = None,
        seed: int = 0,
        log_dir: Optional[str] = None,
        driver_addr: Optional[str] = None,
        data_plane: str = "auto",
        worker_timeout: float = 1800.0,
        coordinator_port: Optional[int] = None,
        evaluator: bool = False,
        max_restarts: int = 0,
        elastic: bool = False,
        min_slices: int = 1,
        num_slices: Optional[int] = None,
    ):
        """:param module: a flax ``nn.Module`` class, instance, or zero-arg factory —
            the analogue of the reference's torch module class argument
            (torch_distributed.py:35, "has to be the class itself").
        :param dataset: arrays / iterator factory / list [train, eval] — consumed via
            signature injection like the reference's dataset list.
        :param hparams: passed through to the train_fn (torch_distributed.py:55).
        :param sharding: ShardingSpec or preset name in
            {"dp","fsdp","zero","tp","sp","ep","2d"}.
        :param mixed_precision: compute in bfloat16 (TPU-native; replaces
            torch.cuda.amp, torch_distributed.py:58).
        :param remat: apply jax.checkpoint to layer stacks (activation
            rematerialization — trades FLOPs for HBM).
        :param zero_lvl: migration shim (reference semantics,
            torch_distributed.py:60-63): 0→dp, 2/3→fsdp; 1→dp with the
            native ZeRO-1 optimizer-state sharding (``zero_stage=1``) —
            the reference's ZeRO-1 is exactly optimizer states sharded
            over data parallelism. Overrides ``sharding`` when set.
        :param zero_stage: native ZeRO stage (0/1) stamped onto the resolved
            :class:`ShardingSpec` (docs/distributed.md "Gradient overlap &
            ZeRO"); overrides the ``zero_lvl`` mapping when both are given.
        :param bucket_mb: gradient-reduction bucket size in MiB stamped onto
            the resolved spec (None = unbucketed).
        :param model: alias for ``module`` matching TfDistributedConfig's field name.
        :param process_data: optional callable applied to the dataset on each worker
            (tf_distributed.py:43 equivalent).
        """
        super().__init__(name, description, hb_interval)
        module = module if module is not None else model
        self.module = module
        self.model = module
        self.dataset = dataset
        self.hparams = dict(hparams or {})
        if zero_lvl is not None:
            if zero_lvl not in (0, 1, 2, 3):
                raise ValueError("zero_lvl must be in 0..3")
            # ZeRO-1 is optimizer-state sharding over pure dp — now native
            # (parallel/overlap.py) instead of approximated by fsdp
            sharding = "dp" if zero_lvl in (0, 1) else "fsdp"
            if zero_lvl == 1 and zero_stage is None:
                zero_stage = 1
        if zero_stage is not None and zero_stage not in (0, 1):
            raise ValueError("zero_stage must be 0 or 1")
        self.zero_stage = zero_stage
        self.bucket_mb = bucket_mb
        self.sharding = sharding
        self.mixed_precision = bool(mixed_precision)
        self.remat = bool(remat)
        self.process_data = process_data
        if num_executors is None and os.environ.get("MAGGY_TPU_NUM_EXECUTORS"):
            # a launcher (maggy_tpu.run) exports the process count so the same
            # script needs no edits to match the launch width
            num_executors = int(os.environ["MAGGY_TPU_NUM_EXECUTORS"])
        self.num_executors = num_executors
        self.seed = int(seed)
        self.log_dir = log_dir
        # Pod mode: every host runs the same script; non-zero hosts connect to
        # the driver here instead of starting their own (env override:
        # MAGGY_TPU_DRIVER="host:port"). The secret rides MAGGY_TPU_SECRET.
        self.driver_addr = driver_addr
        if data_plane not in ("auto", "local"):
            raise ValueError("data_plane must be 'auto' or 'local'")
        # "auto": form one global mesh across pod hosts via jax.distributed;
        # "local": each worker keeps a host-local mesh (independent replicas —
        # also what control-plane tests use)
        self.data_plane = data_plane
        # pod mode: abort the run if a registered worker goes silent this long
        self.worker_timeout = float(worker_timeout)
        # jax.distributed coordinator port on worker 0's host. None derives a
        # per-experiment port from the driver's RPC port so two concurrent pod
        # experiments sharing worker-0's host never collide
        # (MAGGY_TPU_COORDINATOR_PORT is a user-settable env override).
        if coordinator_port is None and os.environ.get("MAGGY_TPU_COORDINATOR_PORT"):
            coordinator_port = int(os.environ["MAGGY_TPU_COORDINATOR_PORT"])
        self.coordinator_port = coordinator_port
        # evaluator=True promotes the last worker to a dedicated evaluation
        # role (the reference designates the last TF worker as evaluator,
        # tf_dist_executor.py:138-144): it joins the control plane but not the
        # training group; the train_fn sees ctx.role == "evaluator" and its
        # outputs land under result["evaluator"] instead of the training mean.
        self.evaluator = bool(evaluator)
        # elastic restart budget (docs/resilience.md): on a TRANSIENT worker
        # death (worker/host loss — never a train_fn exception) the driver
        # re-runs the registration barrier + EXEC_CONFIG exchange for the lost
        # partition and relaunches its train_fn, which picks up the latest
        # checkpoint via Trainer.fit(resume="auto"). 0 (default) keeps the
        # fail-fast abort. Env override: MAGGY_TPU_MAX_RESTARTS.
        if max_restarts == 0 and os.environ.get("MAGGY_TPU_MAX_RESTARTS"):
            max_restarts = int(os.environ["MAGGY_TPU_MAX_RESTARTS"])
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        self.max_restarts = int(max_restarts)
        # Elastic membership (docs/resilience.md "Elastic membership"):
        # instead of burning a restart slot to relaunch a lost slice at the
        # SAME world size, the data mesh reshapes — epoch-numbered
        # membership views, survivors converge on the latest complete
        # checkpoint and continue at reduced width; a rejoining slice
        # reshapes back. min_slices gates how far the mesh may shrink
        # (violation = clean deterministic abort). num_slices > num_executors
        # with one executor simulates that many slices as contiguous
        # partitions of the local device mesh (CPU-testable geometries);
        # with num_executors > 1 each worker process is one slice.
        # Elastic runs need a checkpointer + fit(resume="auto") in the
        # train_fn — the reshape's convergence point is a checkpoint.
        self.elastic = bool(elastic)
        if min_slices < 1:
            raise ValueError("min_slices must be >= 1")
        self.min_slices = int(min_slices)
        if num_slices is not None and num_slices < 1:
            raise ValueError("num_slices must be >= 1")
        self.num_slices = num_slices
        if self.evaluator and self.elastic:
            raise ValueError(
                "elastic=True does not compose with evaluator=True: the "
                "evaluator partition sits outside the training membership"
            )

    def resolve_sharding(self, num_devices: int) -> ShardingSpec:
        import dataclasses

        if isinstance(self.sharding, ShardingSpec):
            spec = (
                self.sharding.scaled_to(num_devices)
                if self.sharding.num_devices != num_devices
                else self.sharding
            )
        else:
            spec = ShardingSpec.preset(self.sharding, num_devices)
        overrides = {}
        if self.zero_stage is not None:
            overrides["zero_stage"] = int(self.zero_stage)
        if self.bucket_mb is not None:
            overrides["bucket_mb"] = float(self.bucket_mb)
        return dataclasses.replace(spec, **overrides) if overrides else spec
