from maggy_tpu.config.base import LagomConfig, BaseConfig
from maggy_tpu.config.hpo import HyperparameterOptConfig
from maggy_tpu.config.ablation import AblationConfig
from maggy_tpu.config.distributed import DistributedConfig
from maggy_tpu.config.tune import TuneConfig

# Convenience alias mirroring the reference's config split (TorchDistributedConfig /
# TfDistributedConfig, config/torch_distributed.py:28 + config/tf_distributed.py:26):
# on TPU there is a single JAX data plane, so one config covers both.
TpuDistributedConfig = DistributedConfig

__all__ = [
    "LagomConfig",
    "BaseConfig",
    "HyperparameterOptConfig",
    "AblationConfig",
    "DistributedConfig",
    "TpuDistributedConfig",
    "TuneConfig",
]
