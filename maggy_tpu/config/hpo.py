"""Hyperparameter-optimization experiment config.

Parity with the reference ``HyperparameterOptConfig``
(config/hyperparameter_optimization.py:33-93) minus the Spark-only guard — HPO runs
anywhere — plus TPU scheduling knobs (``num_executors``, ``devices_per_trial``)
that replace Spark's executor count as the trial-parallelism control.
"""

from __future__ import annotations

from typing import Any, Optional, Union

from maggy_tpu.config.base import LagomConfig
from maggy_tpu.searchspace import Searchspace

DIRECTIONS = ("max", "min")


class HyperparameterOptConfig(LagomConfig):
    def __init__(
        self,
        num_trials: int,
        optimizer: Union[str, Any],
        searchspace: Searchspace,
        optimization_key: str = "metric",
        direction: str = "max",
        es_interval: int = 1,
        es_min: int = 10,
        es_policy: Union[str, Any] = "median",
        name: str = "HPOptimization",
        description: str = "",
        hb_interval: float = 1.0,
        model: Any = None,
        dataset: Any = None,
        num_executors: Optional[int] = None,
        devices_per_trial: int = 1,
        pruner: Optional[Union[str, Any]] = None,
        pruner_config: Optional[dict] = None,
        seed: Optional[int] = None,
        log_dir: Optional[str] = None,
        resume_from: Optional[str] = None,
        sharding: Optional[Any] = None,
        driver_addr: Optional[str] = None,
        worker_timeout: float = 600.0,
        trial_retries: int = 2,
        retry_backoff: float = 0.5,
        quarantine_after: int = 3,
        quarantine_cooldown: float = 300.0,
    ):
        """:param num_trials: total trials to run (pruner may override, as in the
            reference optimization_driver.py:88-93).
        :param optimizer: name in {"randomsearch","gridsearch","asha","tpe","gp","none"}
            or an AbstractOptimizer instance.
        :param searchspace: the Searchspace to explore.
        :param optimization_key: metric name used for ranking trials.
        :param direction: "max" or "min".
        :param es_interval: steps between early-stop checks.
        :param es_min: minimum finalized trials before early stopping activates.
        :param es_policy: "median", "none", or an AbstractEarlyStop instance.
        :param num_executors: trial workers to run concurrently; defaults to the
            number of addressable devices // devices_per_trial.
        :param devices_per_trial: devices leased to each trial (sub-slice size).
        :param pruner: optional "hyperband" or AbstractPruner instance.
        :param seed: RNG seed for samplers/surrogates.
        :param resume_from: path to a previous experiment directory; its
            finalized trials are preloaded and never re-run.
        :param sharding: TrainContext preset ("dp", "fsdp", ...) or ShardingSpec
            for the ``ctx`` injected into train_fns that ask for it; defaults
            to "dp" over the trial's leased devices.
        :param driver_addr: pod mode — remote trial workers connect here
            (``host:port``; usually left to the MAGGY_TPU_DRIVER env var the
            launcher exports). The reference gets cross-host trial executors
            from Spark (spark_driver.py:136-145); here any host running the
            same script with MAGGY_TPU_ROLE=worker adds trial capacity.
        :param worker_timeout: pod mode — seconds of silence after which a
            registered remote worker is presumed dead: its in-flight trial is
            freed and requeued (see ``trial_retries``), and the experiment
            CONTINUES on the remaining capacity (a respawned worker
            re-registers and serves again — ``python -m maggy_tpu.run
            --respawn``).
        :param trial_retries: how many times a trial lost to a TRANSIENT
            failure (worker death / RPC loss) is requeued before it is marked
            ERROR for good. Deterministic failures — an exception raised by
            the train_fn — never retry (docs/resilience.md). Env override:
            ``MAGGY_TPU_TRIAL_RETRIES``.
        :param retry_backoff: base seconds of the exponential (jittered)
            backoff before a requeued trial becomes schedulable again. Env
            override: ``MAGGY_TPU_RETRY_BACKOFF``.
        :param quarantine_after: consecutive lost trials after which a worker
            is quarantined out of scheduling (flaky host protection).
        :param quarantine_cooldown: seconds a quarantined worker sits out
            before re-entering on probation.
        """
        super().__init__(name, description, hb_interval)
        if not isinstance(num_trials, int) or num_trials <= 0:
            raise ValueError("Number of trials should be greater than zero!")
        if not isinstance(searchspace, Searchspace):
            raise TypeError("searchspace must be a Searchspace instance")
        if direction not in DIRECTIONS:
            raise ValueError(f"direction must be one of {DIRECTIONS}, got {direction!r}")
        if devices_per_trial < 1:
            raise ValueError("devices_per_trial must be >= 1")
        self.num_trials = num_trials
        self.optimizer = optimizer
        self.searchspace = searchspace
        self.optimization_key = optimization_key
        self.direction = direction
        self.es_interval = int(es_interval)
        self.es_min = int(es_min)
        self.es_policy = es_policy
        self.model = model
        self.dataset = dataset
        self.num_executors = num_executors
        self.devices_per_trial = int(devices_per_trial)
        self.pruner = pruner
        self.pruner_config = dict(pruner_config or {})
        self.seed = seed
        self.log_dir = log_dir
        self.resume_from = resume_from
        self.sharding = sharding
        self.driver_addr = driver_addr
        self.worker_timeout = float(worker_timeout)
        if trial_retries < 0:
            raise ValueError("trial_retries must be >= 0")
        self.trial_retries = int(trial_retries)
        self.retry_backoff = float(retry_backoff)
        self.quarantine_after = int(quarantine_after)
        self.quarantine_cooldown = float(quarantine_cooldown)
