"""Ablation-study experiment config (reference config/ablation.py:28-67, minus the
Spark-only guard)."""

from __future__ import annotations

from typing import Any, Optional, Union

from maggy_tpu.config.base import LagomConfig


class AblationConfig(LagomConfig):
    def __init__(
        self,
        ablation_study: Any,
        ablator: Union[str, Any] = "loco",
        direction: str = "max",
        name: str = "ablationStudy",
        description: str = "",
        hb_interval: float = 1.0,
        model: Any = None,
        dataset: Any = None,
        num_executors: Optional[int] = None,
        devices_per_trial: int = 1,
        optimization_key: str = "metric",
        log_dir: Optional[str] = None,
        sharding: Optional[Any] = None,
        driver_addr: Optional[str] = None,
        worker_timeout: float = 600.0,
        trial_retries: int = 2,
        retry_backoff: float = 0.5,
        quarantine_after: int = 3,
        quarantine_cooldown: float = 300.0,
    ):
        super().__init__(name, description, hb_interval)
        if direction not in ("max", "min"):
            raise ValueError(f"direction must be 'max' or 'min', got {direction!r}")
        self.ablation_study = ablation_study
        self.ablator = ablator
        self.direction = direction
        self.model = model
        self.dataset = dataset
        self.num_executors = num_executors
        self.devices_per_trial = int(devices_per_trial)
        self.optimization_key = optimization_key
        self.log_dir = log_dir
        self.sharding = sharding
        self.driver_addr = driver_addr
        self.worker_timeout = float(worker_timeout)
        # trial-loss retry/quarantine policy, forwarded to the HPO scheduling
        # machinery the ablation driver reuses (see HyperparameterOptConfig)
        self.trial_retries = int(trial_retries)
        self.retry_backoff = float(retry_backoff)
        self.quarantine_after = int(quarantine_after)
        self.quarantine_cooldown = float(quarantine_cooldown)
