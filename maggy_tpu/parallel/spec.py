"""Mesh/sharding specification.

This is the TPU-native replacement for the reference's backend/zero_lvl knobs
(config/torch_distributed.py:31, 60-63): instead of picking DDP vs FairScale vs
DeepSpeed engines, the user (or a preset) declares a logical device mesh with five
axes — data, fsdp, tensor, seq, expert — and the framework lowers it to a
``jax.sharding.Mesh`` plus NamedSharding rules. XLA then emits the collectives
(psum/all_gather/reduce_scatter/ppermute) over ICI/DCN that NCCL provided in the
reference (§2.9).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

# Canonical mesh-axis names, in layout-priority order. ICI-heavy axes (tensor, seq)
# should map to the innermost/physically-closest devices; `stage` (pipeline:
# point-to-point once per microbatch) and `data` (one gradient all-reduce per
# step) are outermost so their traffic can ride DCN across slices
# (scaling-book recipe).
AXIS_STAGE = "stage"
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
# outermost-of-all data axis for multi-slice topologies: one coordinate per
# ICI-connected slice, so the only collective that crosses it is the one
# gradient all-reduce per step (DCN-tolerant), while fsdp's per-layer
# reduce-scatter/all-gather stays inside a slice (ICI) — hierarchical data
# parallelism per the TPU concurrency-limits recipe (PAPERS.md)
AXIS_SLICE = "slice"

MESH_AXES = (AXIS_STAGE, AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_SEQ, AXIS_TENSOR)
# multi-slice meshes prepend the slice axis; single-slice code never sees it
SLICE_MESH_AXES = (AXIS_SLICE,) + MESH_AXES

# ShardingSpec fields that are mesh-axis extents (the rest are tuning knobs)
_AXIS_FIELDS = ("dp", "fsdp", "tp", "sp", "ep", "pp")


@dataclasses.dataclass(frozen=True)
class ShardingSpec:
    """Logical parallelism degrees. A value of 1 disables the axis.

    ``dp``    data parallelism (batch axis; reference DDP, modules.py:38-65)
    ``fsdp``  parameter/optimizer-state sharding (reference ZeRO-1..3/FSDP,
              optim.py:28-117 + modules.py:68-97)
    ``tp``    tensor parallelism (attention heads / MLP hidden)
    ``sp``    sequence/context parallelism (ring attention; absent in reference,
              SURVEY.md §5.7)
    ``ep``    expert parallelism for MoE (absent in reference, §2.10)
    ``pp``    pipeline parallelism over layer stages (the reference explicitly
              rejects it, modules.py:106-109; provided here as
              parallel/pipeline.py)

    Two non-axis knobs ride along (docs/distributed.md "Gradient overlap &
    ZeRO"): ``zero_stage`` (0 or 1) shards optimizer state over the data
    axis à la ZeRO-1 — the pure-dp complement of ``fsdp``, which already
    shards it — and ``bucket_mb`` bounds the gradient-reduction bucket size
    in MiB (None = unbucketed). Both default to the legacy dense behavior.
    """

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1
    zero_stage: int = 0
    bucket_mb: Optional[float] = None

    def __post_init__(self):
        for f in dataclasses.fields(self):
            if f.name not in _AXIS_FIELDS:
                continue
            v = getattr(self, f.name)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"ShardingSpec.{f.name} must be a positive int, got {v!r}")
        if self.zero_stage not in (0, 1):
            raise ValueError(
                f"ShardingSpec.zero_stage must be 0 or 1, got {self.zero_stage!r}"
            )
        if self.bucket_mb is not None and not float(self.bucket_mb) > 0:
            raise ValueError(
                f"ShardingSpec.bucket_mb must be positive (or None), got "
                f"{self.bucket_mb!r}"
            )

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.ep * self.pp

    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.pp, self.dp, self.fsdp, self.ep, self.sp, self.tp)

    @classmethod
    def preset(cls, name: str, num_devices: int) -> "ShardingSpec":
        """Named presets mirroring the reference's strategy strings.

        "dp" → pure data parallel; "fsdp"/"zero" → ZeRO-3-style full sharding;
        "tp" → tensor parallel; "2d" → fsdp×tp split; "sp" → sequence parallel;
        "ep" → expert parallel with fsdp remainder.
        """
        n = num_devices
        if name in ("dp", "ddp"):
            return cls(dp=n)
        if name in ("fsdp", "zero", "zero3"):
            return cls(fsdp=n)
        if name == "tp":
            return cls(tp=n)
        if name == "sp":
            return cls(sp=n)
        if name == "pp":
            return cls(pp=n)
        if name == "2d":
            tp = _largest_factor_leq(n, max(1, int(n**0.5)))
            return cls(fsdp=n // tp, tp=tp)
        if name == "ep":
            ep = _largest_factor_leq(n, max(1, int(n**0.5)))
            return cls(ep=ep, fsdp=n // ep)
        raise ValueError(f"Unknown sharding preset {name!r}")

    def scaled_to(self, num_devices: int) -> "ShardingSpec":
        """Grow/shrink the dp axis so the spec covers exactly ``num_devices``."""
        rest = self.fsdp * self.tp * self.sp * self.ep * self.pp
        if num_devices % rest != 0:
            raise ValueError(
                f"{num_devices} devices not divisible by non-dp axes product {rest}"
            )
        return dataclasses.replace(self, dp=num_devices // rest)


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """A multi-slice mesh layout: ``n_slices`` ICI domains, each running
    ``slice_spec`` internally, joined by an outer :data:`AXIS_SLICE` data
    axis (DCN on real fleets; simulated device partitions on one host).

    This is the elastic-membership unit of failure: when a slice leaves or
    rejoins, only ``n_slices`` changes — the per-slice layout (and thus the
    per-slice compiled program structure) is preserved, which is what makes
    the reshape a re-placement rather than a re-plan.
    """

    n_slices: int = 1
    slice_spec: ShardingSpec = dataclasses.field(default_factory=ShardingSpec)

    def __post_init__(self):
        if not isinstance(self.n_slices, int) or self.n_slices < 1:
            raise ValueError(
                f"SliceTopology.n_slices must be a positive int, got "
                f"{self.n_slices!r}"
            )

    @property
    def num_devices(self) -> int:
        return self.n_slices * self.slice_spec.num_devices

    @property
    def devices_per_slice(self) -> int:
        return self.slice_spec.num_devices

    def axis_sizes(self) -> Tuple[int, ...]:
        """Extent per :data:`SLICE_MESH_AXES` entry (slice outermost)."""
        return (self.n_slices,) + self.slice_spec.axis_sizes()

    def with_slices(self, n_slices: int) -> "SliceTopology":
        """The same per-slice layout at a different width — the membership
        reshape transition."""
        return dataclasses.replace(self, n_slices=n_slices)


def _largest_factor_leq(n: int, cap: int) -> int:
    for f in range(min(cap, n), 0, -1):
        if n % f == 0:
            return f
    return 1
