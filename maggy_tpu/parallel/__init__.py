from maggy_tpu.parallel.spec import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_TENSOR,
    MESH_AXES,
    ShardingSpec,
)

__all__ = [
    "ShardingSpec",
    "MESH_AXES",
    "AXIS_DATA",
    "AXIS_FSDP",
    "AXIS_EXPERT",
    "AXIS_SEQ",
    "AXIS_TENSOR",
]
