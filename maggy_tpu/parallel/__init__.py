from maggy_tpu.parallel.spec import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_SLICE,
    AXIS_TENSOR,
    MESH_AXES,
    SLICE_MESH_AXES,
    ShardingSpec,
    SliceTopology,
)

__all__ = [
    "ShardingSpec",
    "SliceTopology",
    "MESH_AXES",
    "SLICE_MESH_AXES",
    "AXIS_DATA",
    "AXIS_FSDP",
    "AXIS_EXPERT",
    "AXIS_SEQ",
    "AXIS_SLICE",
    "AXIS_TENSOR",
]
