"""Pipeline parallelism over the ``stage`` mesh axis: GPipe + 1F1B.

The reference explicitly rejects pipeline modules (core/patching/modules.py:
106-109 asserts against DeepSpeed PipelineModule); SURVEY.md §2.10 marks PP a
stretch goal. This is the TPU-native version: layer stages live on different
devices along the ``stage`` mesh axis, activations flow stage→stage via
``ppermute`` (point-to-point — DCN-friendly, hence the axis sits outermost in
MESH_AXES), and microbatches keep every stage busy after the fill phase.

Two schedules:

* :func:`pipeline_apply` — classic GPipe: with S stages and M microbatches the
  loop runs M + S - 1 ticks; at tick t stage s processes microbatch t - s.
  Backward flows through the same schedule by autodiff (ppermute's transpose
  is the reverse permute), so one ``jax.grad`` trains the pipeline — but the
  scan's autodiff residuals grow with the tick count × carry size.
* :func:`pipeline_grads_1f1b` — an explicit one-forward-one-backward training
  schedule (PipeDream-flush order) with per-microbatch rematerialisation:
  each stage keeps only its in-flight stage *inputs* (an S+1-slot ring
  buffer) and re-linearises at backward time, so activation memory is O(S)
  per stage instead of O(M) — the long-context setting. Closed-form SPMD
  clock, derivable from the dependency chain: backward of microbatch m at
  stage s fires at tick ``2S-1-s+2m``; its forward at ``s+m`` during warmup
  (m ≤ S-1-s) and ``2m+s`` in steady state. Each stage performs at most one
  op per tick (fwd/bwd tick parities are opposite), activation hand-offs are
  buffered in the ring, and gradient hand-offs always arrive exactly one
  tick before their consumer — so a single carried buffer suffices.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from maggy_tpu.parallel.spec import AXIS_DATA, AXIS_FSDP, AXIS_STAGE
from maggy_tpu.util import shard_map


def _manual_axes(mesh, axis_name) -> frozenset:
    """The pipeline shard_maps are manual over stage (ppermute hand-offs) and
    data/fsdp (explicit grad/loss psums) ONLY; every other mesh axis —
    `tensor` being the live case (pp x tp) — stays in GSPMD-auto mode, so a
    stage body whose params carry tensor-sharded dims (attn heads / mlp
    hidden / vocab) is tensor-parallelized by XLA inside each stage.

    When every would-be-auto axis is trivial (extent 1) this returns ALL
    mesh axes (full-manual): jax 0.9's partial-manual mode rejects EAGER
    calls on any mesh that has non-manual axes, and full-manual is
    semantically identical there — so eager pipeline_apply keeps working on
    plain pp x dp meshes, and the partial-manual path (always reached
    through the Trainer's jit) engages only when tp/sp/ep is real."""
    manual = frozenset({axis_name, AXIS_DATA, AXIS_FSDP}) & frozenset(
        mesh.axis_names
    )
    shape = dict(mesh.shape)
    if all(shape[a] == 1 for a in mesh.axis_names if a not in manual):
        return frozenset(mesh.axis_names)
    return manual


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    *,
    mesh,
    axis_name: str = AXIS_STAGE,
    out_mode: str = "replicated",
):
    """Run a layer pipeline over the mesh's ``stage`` axis.

    :param stage_fn: ``fn(params_for_one_stage, x) -> y`` — one stage's compute
        (e.g. a scan over its layer chunk). Must keep the activation shape.
    :param stage_params: pytree whose leaves have a leading ``[n_stages]`` axis
        (sharded over ``stage``) — build with :func:`stack_stage_params`.
    :param microbatches: ``[n_micro, mb, ...]`` activations; the ``mb`` axis is
        sharded over (data, fsdp), so a pp x dp mesh pipelines AND
        data-parallelizes (each dp replica pipelines its batch slice).
    :param out_mode: ``"replicated"`` all-reduces the full output buffer so
        every stage holds it (API-compatible default); ``"scatter"`` instead
        reduce-scatters the ``n_micro`` axis over stages — ~2x less interconnect
        traffic, right when the consumer (a loss) reduces anyway. Requires
        ``n_micro % n_stages == 0``.
    :returns: ``[n_micro, mb, ...]`` outputs of the final stage
        (``[n_micro / n_stages, mb, ...]`` per stage for ``"scatter"``).
    """
    if out_mode not in ("replicated", "scatter"):
        raise ValueError(f"out_mode must be 'replicated' or 'scatter', got {out_mode!r}")
    n_stages = mesh.shape[axis_name]
    if n_stages == 1:
        return jax.vmap(lambda x: stage_fn(jax.tree.map(lambda p: p[0], stage_params), x))(
            microbatches
        )
    n_micro = microbatches.shape[0]
    if n_micro < n_stages:
        raise ValueError(
            f"Need at least as many microbatches ({n_micro}) as stages "
            f"({n_stages}) to fill the pipeline."
        )

    def local(params, mb):
        # params leaves: [1, ...] local stage shard; mb: [n_micro, mb, ...]
        params = jax.tree.map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis_name)
        fwd = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            incoming, outputs = carry
            # stage 0 ingests microbatch t (clamped; masked out later)
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            x0 = jax.lax.dynamic_index_in_dim(mb, mb_idx, keepdims=False)
            x = jnp.where(stage == 0, x0, incoming)
            y = stage_fn(params, x)
            # last stage writes its result for microbatch t - (S-1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            valid = (t >= n_stages - 1) & (stage == n_stages - 1)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, jax.lax.dynamic_index_in_dim(outputs, out_idx, keepdims=False)),
                out_idx, 0,
            )
            nxt = jax.lax.ppermute(y, axis_name, fwd)
            return (nxt, updated), None

        init = (jnp.zeros_like(mb[0]), jnp.zeros_like(mb))
        (_, outputs), _ = jax.lax.scan(
            tick, init, jnp.arange(n_micro + n_stages - 1)
        )
        # only the last stage holds real outputs
        outputs = jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs))
        if out_mode == "scatter":
            # reduce-scatter the micro axis over stages: each stage keeps its
            # n_micro/S chunk instead of an all-reduced full buffer
            return jax.lax.psum_scatter(
                outputs, axis_name, scatter_dimension=0, tiled=True
            )
        return jax.lax.psum(outputs, axis_name)

    batch_spec = P(None, (AXIS_DATA, AXIS_FSDP))
    if out_mode == "scatter":
        if n_stages > 1 and n_micro % n_stages:
            raise ValueError(
                f"out_mode='scatter' needs n_micro ({n_micro}) divisible by "
                f"stages ({n_stages})"
            )
        out_spec = P(axis_name, (AXIS_DATA, AXIS_FSDP))
    else:
        out_spec = batch_spec
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name), batch_spec),
        out_specs=out_spec,
        axis_names=_manual_axes(mesh, axis_name),
        check_vma=False,
    )(stage_params, microbatches)


def pipeline_grads_1f1b(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    targets,
    *,
    mesh,
    axis_name: str = AXIS_STAGE,
    first_fn: Optional[Callable] = None,
    stage_takes_raw: bool = False,
    stage_has_aux: bool = False,
):
    """One training step with the 1F1B schedule: returns ``(loss, grads)``.

    :param stage_fn: ``fn(params_for_one_stage, x) -> y``, activation-shape
        and dtype preserving — or ``fn(params, x, raw)`` with
        ``stage_takes_raw=True``: every stage also receives the CURRENT
        microbatch's raw rows from the (stage-replicated) stream, so
        side-channel inputs every layer needs — packed-sequence segment ids,
        per-segment positions — reach all stages without flowing through the
        activation hand-offs.
    :param loss_fn: ``fn(params_for_one_stage, y_final, target) -> scalar`` —
        mean loss of ONE microbatch, computed on the last stage only (no
        output buffer ever forms, let alone gets broadcast). Taking the stage
        params lets a language-model head (final norm + lm_head) live inside
        the loss so its gradients flow on the last stage.
    :param stage_params: leaves ``[n_stages, ...]`` (see
        :func:`stack_stage_params`). The tree must be UNIFORM across stages;
        params used by one stage only (embedding on stage 0, head on the last)
        simply receive zero gradient contributions elsewhere.
    :param microbatches: ``[n_micro, mb, ...]``; ``targets`` any pytree of
        ``[n_micro, ...]`` leaves consumed by ``loss_fn``.
    :param first_fn: optional ``fn(params_for_one_stage, raw_microbatch) -> x``
        applied by stage 0 to turn a raw microbatch (e.g. int token ids) into
        the pipeline's activation dtype/shape — the embedding lookup of a
        language model. Differentiated together with stage 0's chunk, so
        embedding gradients come out in stage 0's param grads. When None the
        microbatches themselves must already be activations.
    :param stage_has_aux: the stage function returns ``(y, aux_scalar)`` —
        a per-stage auxiliary loss (MoE router balancing). Each stage's aux
        joins the objective at ITS OWN backward tick: the VJP is pulled with
        cotangent ``(g, 1.0)`` so aux gradients land in that stage's param
        grads. The return gains a third element: ``(loss, grads, aux)`` with
        ``loss`` the DATA loss and ``aux`` the summed auxiliary term (both
        microbatch means) — the optimized objective is their sum.
    :returns: ``loss`` — mean over all microbatches (replicated), and
        ``grads`` — same structure/sharding as ``stage_params``.

    Memory: each stage stores its in-flight stage inputs in an (S+1)-slot
    ring and re-linearises (recompute + VJP) at its backward tick — O(S)
    activations per stage versus GPipe-autodiff's O(ticks) scan residuals.
    With ``first_fn``, the ring stores raw-microbatch-derived activations for
    stage 0 implicitly: stage 0 re-reads the (cheap, int) microbatch stream at
    backward time and recomputes the embedding inside its VJP.
    """
    if first_fn is None:
        first_fn = lambda params, raw: raw  # noqa: E731 - identity ingest
    base_stage = (
        stage_fn if stage_takes_raw else (lambda p, x, raw: stage_fn(p, x))
    )
    if stage_has_aux:
        run_stage = base_stage  # already (y, aux)
    else:
        run_stage = lambda p, x, raw: (base_stage(p, x, raw), jnp.float32(0))  # noqa: E731
    S = mesh.shape[axis_name]
    M = microbatches.shape[0]
    if S == 1:
        def loss_all(params):
            p0 = jax.tree.map(lambda q: q[0], params)

            def one(x, t):
                y, aux = run_stage(p0, first_fn(p0, x), x)
                return loss_fn(p0, y, t), aux

            data, aux = jax.vmap(one)(microbatches, targets)
            return data.mean() + aux.mean(), (data.mean(), aux.mean())

        (_, (data, aux)), grads = jax.value_and_grad(loss_all, has_aux=True)(
            stage_params
        )
        if stage_has_aux:
            return data, grads, aux
        return data, grads
    if M < S:
        raise ValueError(
            f"Need at least as many microbatches ({M}) as stages ({S})."
        )
    RING = S + 1  # in-flight inputs per stage are bounded by S (see proof in tests)
    T = 2 * M + 2 * S - 2

    def local(params, mbs, tgts):
        params = jax.tree.map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis_name)
        is_last = stage == S - 1
        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        bwd_perm = [(i + 1, i) for i in range(S - 1)]
        # activation shape/dtype comes from first_fn's output, not the raw
        # microbatch stream (they differ when first_fn embeds token ids)
        act = jax.eval_shape(
            first_fn, params, jax.ShapeDtypeStruct(mbs.shape[1:], mbs.dtype)
        )
        zeros_mb = jnp.zeros(act.shape, act.dtype)
        zero_dp = jax.tree.map(jnp.zeros_like, params)

        def ingest(p, raw, x_ring):
            """Stage 0 turns the raw microbatch into an activation; everyone
            else reads the ring. Both branches are computed and where-selected
            (first_fn is a cheap gather), which keeps the select differentiable
            so embedding grads appear exactly on stage 0."""
            return jnp.where(stage == 0, first_fn(p, raw), x_ring)

        def fwd_micro(t, s):
            """Which microbatch (if any) stage s forwards at tick t."""
            warm = t - s
            is_warm = (warm >= 0) & (warm <= S - 1 - s) & (warm < M)
            bey = (t - s) // 2
            is_bey = (
                ((t - s) >= 0)
                & ((t - s) % 2 == 0)
                & (bey > S - 1 - s)
                & (bey < M)
            )
            return jnp.where(is_warm, warm, bey), is_warm | is_bey

        def bwd_micro(t, s):
            tb = t - (2 * S - 1 - s)
            return tb // 2, (tb >= 0) & (tb % 2 == 0) & (tb // 2 < M)

        def tick(carry, t):
            xbuf, y_recv, g_recv, grad_acc, loss_acc, aux_acc = carry

            # 1. bank last tick's arriving activation into the ring
            m_arr, ok_arr = fwd_micro(t - 1, stage - 1)
            ok_arr = ok_arr & (stage > 0) & (t > 0)
            slot = jnp.clip(m_arr, 0, M - 1) % RING
            xbuf = jnp.where(
                ok_arr,
                jax.lax.dynamic_update_index_in_dim(xbuf, y_recv, slot, 0),
                xbuf,
            )

            # 2. forward op (at most one per tick); the last stage's forward
            # output has no consumer (no fwd_perm edge out, and its backward
            # recomputes inside the vjp), so skip it there
            m_f, do_f = fwd_micro(t, stage)
            do_f = do_f & ~is_last
            mf = jnp.clip(m_f, 0, M - 1)
            raw_f = jax.lax.dynamic_index_in_dim(mbs, mf, keepdims=False)
            ring_f = jax.lax.dynamic_index_in_dim(xbuf, mf % RING, keepdims=False)
            y = jax.lax.cond(
                do_f,
                lambda raw, xr: run_stage(params, ingest(params, raw, xr), raw)[0],
                lambda raw, xr: zeros_mb,
                raw_f, ring_f,
            )

            # 3. backward op: re-linearise from the saved stage input (stage 0
            # re-reads the raw microbatch stream and re-embeds inside its VJP)
            m_b, do_b = bwd_micro(t, stage)
            mb_ = jnp.clip(m_b, 0, M - 1)
            raw_b = jax.lax.dynamic_index_in_dim(mbs, mb_, keepdims=False)
            ring_b = jax.lax.dynamic_index_in_dim(xbuf, mb_ % RING, keepdims=False)
            tgt = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, mb_, keepdims=False),
                tgts,
            )

            def run_bwd(raw, xr, g):
                def last_fn(raw, xr, g):
                    def full(p, x):
                        y, aux = run_stage(p, ingest(p, raw, x), raw)
                        return loss_fn(p, y, tgt), aux

                    (lval, aux), pull = jax.vjp(full, params, xr)
                    # both outputs get cotangent 1: loss + aux is the
                    # optimized objective; they stay split for reporting
                    dp, dx = pull((jnp.ones_like(lval), jnp.ones_like(aux)))
                    return dp, dx, lval.astype(jnp.float32), aux.astype(jnp.float32)

                def mid_fn(raw, xr, g):
                    (yv, aux), pull = jax.vjp(
                        lambda p, x: run_stage(p, ingest(p, raw, x), raw),
                        params, xr,
                    )
                    # cotangent 1.0 on the aux output: this stage's router
                    # losses reach its param grads right here
                    dp, dx = pull((g.astype(yv.dtype), jnp.ones_like(aux)))
                    return dp, dx, jnp.float32(0), aux.astype(jnp.float32)

                return jax.lax.cond(is_last, last_fn, mid_fn, raw, xr, g)

            def skip_bwd(raw, xr, g):
                return zero_dp, zeros_mb, jnp.float32(0), jnp.float32(0)

            dp, dx, lval, aval = jax.lax.cond(
                do_b, run_bwd, skip_bwd, raw_b, ring_b, g_recv
            )
            grad_acc = jax.tree.map(lambda a, d: a + d, grad_acc, dp)
            loss_acc = loss_acc + lval
            aux_acc = aux_acc + aval

            # 4. hand off: activations forward, gradients backward
            y_next = jax.lax.ppermute(y, axis_name, fwd_perm)
            g_next = jax.lax.ppermute(dx, axis_name, bwd_perm)
            return (xbuf, y_next, g_next, grad_acc, loss_acc, aux_acc), None

        init = (
            jnp.zeros((RING,) + act.shape, act.dtype),
            zeros_mb,
            zeros_mb,
            zero_dp,
            jnp.float32(0),
            jnp.float32(0),
        )
        (_, _, _, grad_acc, loss_acc, aux_acc), _ = jax.lax.scan(
            tick, init, jnp.arange(T)
        )

        # data-parallel mean over (data, fsdp) replicas, micro mean over M.
        # the stage psums are load-bearing SUMS, not broadcasts: the data
        # loss sits on the last stage, but every stage contributes its own
        # aux at its backward ticks
        dpf = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
        grads = jax.tree.map(
            lambda g: (
                jax.lax.psum(g, (AXIS_DATA, AXIS_FSDP)) / (dpf * M)
            )[None],
            grad_acc,
        )

        def reduce_scalar(v):
            v = jax.lax.psum(v, axis_name)
            return jax.lax.psum(v, (AXIS_DATA, AXIS_FSDP)) / (dpf * M)

        return reduce_scalar(loss_acc), grads, reduce_scalar(aux_acc)

    batch_spec = P(None, (AXIS_DATA, AXIS_FSDP))
    loss, grads, aux = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name), batch_spec, batch_spec),
        out_specs=(P(), P(axis_name), P()),
        axis_names=_manual_axes(mesh, axis_name),
        check_vma=False,
    )(stage_params, microbatches, targets)
    if stage_has_aux:
        return loss, grads, aux
    return loss, grads


def pipeline_forward_loss(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    targets,
    *,
    mesh,
    axis_name: str = AXIS_STAGE,
    first_fn: Optional[Callable] = None,
    stage_takes_raw: bool = False,
    stage_has_aux: bool = False,
):
    """Forward-only GPipe sweep returning ``(loss, aux)`` microbatch means —
    the EVAL counterpart of :func:`pipeline_grads_1f1b` (VERDICT r4 item 9):
    per-device live state is one stage's params plus a single microbatch
    activation, instead of unstacking the whole model replicated on every
    device (which OOMs exactly in the regime pipeline parallelism exists
    for). Same stage_fn/loss_fn/first_fn contracts as the 1F1B schedule;
    no gradients, no activation ring — M + S - 1 ticks."""
    if first_fn is None:
        first_fn = lambda params, raw: raw  # noqa: E731 - identity ingest
    base_stage = (
        stage_fn if stage_takes_raw else (lambda p, x, raw: stage_fn(p, x))
    )
    if stage_has_aux:
        run_stage = base_stage
    else:
        run_stage = lambda p, x, raw: (base_stage(p, x, raw), jnp.float32(0))  # noqa: E731
    S = mesh.shape[axis_name]
    M = microbatches.shape[0]
    if S == 1:
        def one(params):
            p0 = jax.tree.map(lambda q: q[0], params)

            def per_micro(x, t):
                y, aux = run_stage(p0, first_fn(p0, x), x)
                return loss_fn(p0, y, t), aux

            data, aux = jax.vmap(per_micro)(microbatches, targets)
            return data.mean(), aux.mean()

        return one(stage_params)

    def local(params, mbs, tgts):
        params = jax.tree.map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis_name)
        is_last = stage == S - 1
        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        act = jax.eval_shape(
            first_fn, params, jax.ShapeDtypeStruct(mbs.shape[1:], mbs.dtype)
        )
        zeros_mb = jnp.zeros(act.shape, act.dtype)

        def tick(carry, t):
            y_recv, loss_acc, aux_acc = carry
            m = jnp.clip(t - stage, 0, M - 1)
            do = ((t - stage) >= 0) & ((t - stage) < M)
            raw = jax.lax.dynamic_index_in_dim(mbs, m, keepdims=False)
            x = jnp.where(stage == 0, first_fn(params, raw), y_recv)

            def run(raw, x):
                y, aux = run_stage(params, x, raw)
                tgt = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(a, m, keepdims=False),
                    tgts,
                )
                lval = jax.lax.cond(
                    is_last,
                    lambda: loss_fn(params, y, tgt).astype(jnp.float32),
                    lambda: jnp.float32(0),
                )
                return y, lval, aux.astype(jnp.float32)

            def skip(raw, x):
                return zeros_mb, jnp.float32(0), jnp.float32(0)

            y, lval, aval = jax.lax.cond(do, run, skip, raw, x)
            y_next = jax.lax.ppermute(y, axis_name, fwd_perm)
            return (y_next, loss_acc + lval, aux_acc + aval), None

        init = (zeros_mb, jnp.float32(0), jnp.float32(0))
        (_, loss_acc, aux_acc), _ = jax.lax.scan(
            tick, init, jnp.arange(M + S - 1)
        )
        dpf = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]

        def reduce_scalar(v):
            v = jax.lax.psum(v, axis_name)
            return jax.lax.psum(v, (AXIS_DATA, AXIS_FSDP)) / (dpf * M)

        return reduce_scalar(loss_acc), reduce_scalar(aux_acc)

    batch_spec = P(None, (AXIS_DATA, AXIS_FSDP))
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name), batch_spec, batch_spec),
        out_specs=(P(), P()),
        axis_names=_manual_axes(mesh, axis_name),
        check_vma=False,
    )(stage_params, microbatches, targets)


def stack_stage_params(per_layer_params, n_stages: int):
    """Reshape layer-stacked params ``[L, ...]`` into ``[n_stages, L//n_stages,
    ...]`` for :func:`pipeline_apply` (shard the leading axis over 'stage')."""

    def reshape(p):
        L = p.shape[0]
        if L % n_stages:
            raise ValueError(f"{L} layers not divisible by {n_stages} stages")
        return p.reshape(n_stages, L // n_stages, *p.shape[1:])

    return jax.tree.map(reshape, per_layer_params)
