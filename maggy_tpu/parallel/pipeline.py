"""Pipeline parallelism: GPipe-style fill/drain schedule over the ``stage`` axis.

The reference explicitly rejects pipeline modules (core/patching/modules.py:
106-109 asserts against DeepSpeed PipelineModule); SURVEY.md §2.10 marks PP a
stretch goal. This is the TPU-native version: layer stages live on different
devices along the ``stage`` mesh axis, activations flow stage→stage via
``ppermute`` (point-to-point — DCN-friendly, hence the axis sits outermost in
MESH_AXES), and microbatches keep every stage busy after the fill phase.

Schedule (classic GPipe, no 1F1B): with S stages and M microbatches the loop
runs M + S - 1 ticks; at tick t stage s processes microbatch t - s. Backward
flows through the same schedule by autodiff (ppermute's transpose is the
reverse permute), so one ``jax.grad`` around :func:`pipeline_apply` trains the
whole pipeline.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from maggy_tpu.parallel.spec import AXIS_DATA, AXIS_FSDP, AXIS_STAGE


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    *,
    mesh,
    axis_name: str = AXIS_STAGE,
):
    """Run a layer pipeline over the mesh's ``stage`` axis.

    :param stage_fn: ``fn(params_for_one_stage, x) -> y`` — one stage's compute
        (e.g. a scan over its layer chunk). Must keep the activation shape.
    :param stage_params: pytree whose leaves have a leading ``[n_stages]`` axis
        (sharded over ``stage``) — build with :func:`stack_stage_params`.
    :param microbatches: ``[n_micro, mb, ...]`` activations; the ``mb`` axis is
        sharded over (data, fsdp), so a pp x dp mesh pipelines AND
        data-parallelizes (each dp replica pipelines its batch slice).
    :returns: ``[n_micro, mb, ...]`` outputs of the final stage.
    """
    n_stages = mesh.shape[axis_name]
    if n_stages == 1:
        return jax.vmap(lambda x: stage_fn(jax.tree.map(lambda p: p[0], stage_params), x))(
            microbatches
        )
    n_micro = microbatches.shape[0]
    if n_micro < n_stages:
        raise ValueError(
            f"Need at least as many microbatches ({n_micro}) as stages "
            f"({n_stages}) to fill the pipeline."
        )

    def local(params, mb):
        # params leaves: [1, ...] local stage shard; mb: [n_micro, mb, ...]
        params = jax.tree.map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis_name)
        fwd = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            incoming, outputs = carry
            # stage 0 ingests microbatch t (clamped; masked out later)
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            x0 = jax.lax.dynamic_index_in_dim(mb, mb_idx, keepdims=False)
            x = jnp.where(stage == 0, x0, incoming)
            y = stage_fn(params, x)
            # last stage writes its result for microbatch t - (S-1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            valid = (t >= n_stages - 1) & (stage == n_stages - 1)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, jax.lax.dynamic_index_in_dim(outputs, out_idx, keepdims=False)),
                out_idx, 0,
            )
            nxt = jax.lax.ppermute(y, axis_name, fwd)
            return (nxt, updated), None

        init = (jnp.zeros_like(mb[0]), jnp.zeros_like(mb))
        (_, outputs), _ = jax.lax.scan(
            tick, init, jnp.arange(n_micro + n_stages - 1)
        )
        # only the last stage holds real outputs; psum broadcasts them
        outputs = jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs))
        return jax.lax.psum(outputs, axis_name)

    batch_spec = P(None, (AXIS_DATA, AXIS_FSDP))
    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name), batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )(stage_params, microbatches)


def stack_stage_params(per_layer_params, n_stages: int):
    """Reshape layer-stacked params ``[L, ...]`` into ``[n_stages, L//n_stages,
    ...]`` for :func:`pipeline_apply` (shard the leading axis over 'stage')."""

    def reshape(p):
        L = p.shape[0]
        if L % n_stages:
            raise ValueError(f"{L} layers not divisible by {n_stages} stages")
        return p.reshape(n_stages, L // n_stages, *p.shape[1:])

    return jax.tree.map(reshape, per_layer_params)
