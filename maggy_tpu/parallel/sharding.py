"""Logical-axis sharding rules (GSPMD layer).

This file is the TPU-native successor to the reference's entire patching layer
(§2.7: DDP/FSDP/DeepSpeed wrappers, ZeRO optimizer monkey-patches): models
annotate parameters with *logical* axis names via ``flax.linen.with_partitioning``
and the rules below map them to mesh axes. Replication, ZeRO-style state
sharding, tensor parallelism and sequence parallelism are all just different
rule tables — no engine wrappers, no monkey-patching. Optimizer state shards
with its parameters for free (optax state mirrors the param pytree).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

from maggy_tpu.parallel.spec import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_SLICE,
    AXIS_TENSOR,
)

# Logical axis name -> mesh axis (or tuple of mesh axes, or None = replicate).
# Matches the MaxText-style convention: the same model code serves pure-DP
# (everything replicated), ZeRO-3/FSDP ("embed" sharded over fsdp), TP
# ("mlp"/"heads"/"vocab" over tensor) and any 2D/3D combination, depending only
# on the mesh shape — axes of size 1 shard trivially.
DEFAULT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", (AXIS_DATA, AXIS_FSDP)),
    ("activation_seq", AXIS_SEQ),
    ("embed", AXIS_FSDP),
    ("mlp", AXIS_TENSOR),
    ("heads", AXIS_TENSOR),
    ("kv", None),
    ("vocab", AXIS_TENSOR),
    ("expert", AXIS_EXPERT),
    ("norm", None),
    ("conv_spatial", None),
    ("conv_in", None),
    ("conv_out", AXIS_FSDP),
)


def slice_rules(rules=DEFAULT_RULES) -> Tuple[Tuple[str, Any], ...]:
    """The rule table for a slice-topology mesh: ``batch`` additionally
    spans the outer ``slice`` axis, so the per-step gradient sync
    decomposes hierarchically — reduce-scatter/all-gather over ``fsdp``
    inside a slice (ICI), one all-reduce over ``slice`` across slices
    (DCN-tolerant). Every other rule is unchanged: params never shard over
    ``slice``, which is what keeps a membership reshape a pure
    re-placement."""
    out = []
    for name, axis in rules:
        if name == "batch":
            cur = (
                tuple(axis)
                if isinstance(axis, (tuple, list))
                else ((axis,) if axis is not None else ())
            )
            axis = (AXIS_SLICE,) + cur
        out.append((name, axis))
    return tuple(out)


def logical_to_mesh_axes(
    logical_axes: Sequence[Optional[str]], rules=DEFAULT_RULES
) -> Tuple:
    table = dict(rules)
    out = []
    for name in logical_axes:
        out.append(table.get(name) if name is not None else None)
    return tuple(out)


def mesh_extent(mesh, axis) -> int:
    """Total device count behind a mesh-axis assignment (None / name / tuple).

    Axes the mesh does not define count as 1 — an ambient user mesh without
    the framework axis names must degrade (downstream NamedSharding
    construction then decides), never KeyError at trace time."""
    if axis is None:
        return 1
    shape = dict(mesh.shape)
    if isinstance(axis, (tuple, list)):
        n = 1
        for a in axis:
            n *= shape.get(a, 1)
        return n
    return shape.get(axis, 1)


def partition_spec(logical_axes: Sequence[Optional[str]], rules=DEFAULT_RULES):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*logical_to_mesh_axes(logical_axes, rules))


def named_sharding(mesh, logical_axes: Sequence[Optional[str]], rules=DEFAULT_RULES):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, partition_spec(logical_axes, rules))


def _resolve_axis(name, table, mesh_axes):
    """One logical name -> mesh axis assignment. Names already naming mesh
    axes pass through unchanged (boxes rewritten by
    :func:`resolve_boxed_names` re-enter here idempotently); unknown names
    replicate."""
    if name is None:
        return None
    if name in table:
        return table[name]
    if name in mesh_axes:
        return name
    if isinstance(name, (tuple, list)) and all(a in mesh_axes for a in name):
        return tuple(name)
    return None


def _divisible_axes(names, shape, mesh, rules, warn_context=None):
    """Mesh axes for a leaf's dims with the divisibility fallback: axes whose
    extent does not divide the dim replicate (a layout downgrade, never a
    crash) — e.g. 4 attention heads on a tensor=8 mesh."""
    import logging

    table = dict(rules)
    mesh_axes = set(dict(mesh.shape))
    axes = []
    for i, name in enumerate(names):
        axis = _resolve_axis(name, table, mesh_axes)
        ext = mesh_extent(mesh, axis)
        if ext > 1 and shape[i] % ext != 0:
            logging.getLogger(__name__).warning(
                "Axis %d of param (shape %s, logical %s) is not divisible by "
                "mesh axis %r (size %d); replicating that dimension.",
                i, shape, names, axis, ext,
            )
            axis = None
        axes.append(axis)
    return axes


def params_shardings(mesh, abstract_params, rules=DEFAULT_RULES):
    """Map a pytree of (possibly flax-partitioned) abstract leaves to NamedShardings.

    Leaves carrying flax ``nn.Partitioned`` metadata use their logical names;
    plain leaves replicate. Axes whose size does not divide the assigned mesh
    extent fall back to replication with a warning (e.g. 4 attention heads on a
    tensor=8 mesh) — a layout downgrade, never a crash. This is what makes user
    models "obliviously" shardable: annotate once, run under any mesh.
    """
    import flax.linen as nn
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    def leaf_sharding(leaf):
        if not isinstance(leaf, nn.Partitioned):
            return NamedSharding(mesh, PartitionSpec())
        axes = _divisible_axes(leaf.names, leaf.value.shape, mesh, rules)
        return NamedSharding(mesh, PartitionSpec(*axes))

    return jax.tree.map(
        leaf_sharding, abstract_params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )


_logical_partitioned_cls = None


def _logical_partitioned_class():
    global _logical_partitioned_cls
    if _logical_partitioned_cls is None:
        import flax.linen as nn
        from flax import struct

        @struct.dataclass
        class LogicalPartitioned(nn.Partitioned):
            """``nn.Partitioned`` whose unboxing NEVER self-constrains.

            The names here are LOGICAL axes ('embed', 'vocab', ...), resolved
            to mesh axes only by this module's rule tables. Stock flax applies
            the names directly as a ``with_sharding_constraint`` whenever an
            ambient mesh is active (``Partitioned.unbox``) — and raw logical
            names are not mesh axes, so every ``model.init``/``apply`` under
            ``with mesh:`` would be rejected by jax. Placement in this
            framework is decided once, by ``Trainer.make_state``'s
            out_shardings (from :func:`params_shardings`), and GSPMD
            propagates it — the boxes are pure metadata carriers.
            """

            def unbox(self, apply_constraint=True):
                return self.value

        _logical_partitioned_cls = LogicalPartitioned
    return _logical_partitioned_cls


def logical_partitioning(fn, names):
    """Like ``nn.with_partitioning(fn, names)``, but producing
    :class:`LogicalPartitioned` boxes (metadata-only, no unbox-time
    constraint). Every framework model annotates through this."""
    import functools

    cls = _logical_partitioned_class()

    @functools.wraps(fn)
    def init(*args, **kwargs):
        return cls(fn(*args, **kwargs), names)

    return init


def unbox(tree):
    """Strip flax Partitioned boxes, returning raw arrays."""
    import flax.linen as nn
    import jax

    return jax.tree.map(
        lambda x: x.value if isinstance(x, nn.Partitioned) else x,
        tree,
        is_leaf=lambda x: isinstance(x, nn.Partitioned),
    )


def batch_sharding(mesh, rules=DEFAULT_RULES):
    """Sharding for [batch, ...] host data: batch over (data, fsdp)."""
    return named_sharding(mesh, ("batch",), rules)


def constrain_activation(x, logical_axes, rules=DEFAULT_RULES):
    """Pin an activation's layout inside jit via ``with_sharding_constraint``.

    GSPMD propagates shardings from parameters, but on deep mixed meshes
    (tp x fsdp x sp) the residual stream between layers is where propagation
    can drift into accidental all-gathers; pinning it (batch over
    (data, fsdp), seq over sp, embed replicated) keeps collectives where the
    design wants them. No-op without an ambient mesh, on single-device
    meshes, and for axes that do not divide (GSPMD would insert padding —
    a silent layout downgrade is better than a padded one).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from maggy_tpu.parallel.mesh import ambient_mesh

    mesh = ambient_mesh()
    if mesh is None or mesh.size == 1:
        return x
    try:
        # inside a shard_map body (Manual axes) placement is already manual;
        # a constraint built from the Auto physical mesh would trace without
        # raising but poison the region's vjp with a mesh-mismatched op
        am = jax.sharding.get_abstract_mesh()
        manual = getattr(jax.sharding.AxisType, "Manual", None)
        if not am.empty and manual is not None and manual in set(am.axis_types):
            return x
    except AttributeError:
        # removed/not-yet-added introspection API on older jax: detect the
        # manual region through the trace axis-env instead — shard_map binds
        # its manual axes there, so any mesh axis appearing bound means we
        # are inside a manual body and the constraint must be skipped.
        # Anything beyond these two probes must stay loud — silently
        # skipping this guard would let an Auto-mesh constraint poison a
        # Manual region's vjp.
        try:
            from jax._src.core import trace_ctx

            bound = set(getattr(trace_ctx.axis_env, "axis_sizes", {}) or {})
        except (ImportError, AttributeError):
            bound = set()
        if bound & set(mesh.axis_names):
            return x
    axes = list(logical_to_mesh_axes(logical_axes, rules))
    # slice-topology meshes: models pin activations with the DEFAULT rule
    # table, whose batch rule knows nothing of the outer slice axis — a
    # (data, fsdp)-only constraint there would force a cross-slice row
    # gather every layer. Widen batch constraints to include slice so the
    # pin agrees with the input placement.
    if dict(mesh.shape).get(AXIS_SLICE, 1) > 1:
        for i, (name, axis) in enumerate(zip(logical_axes, axes)):
            if name == "batch" and axis is not None:
                cur = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
                if AXIS_SLICE not in cur:
                    axes[i] = (AXIS_SLICE,) + cur
    for i, axis in enumerate(axes):
        ext = mesh_extent(mesh, axis)
        if ext > 1 and x.shape[i] % ext:
            axes[i] = None
    try:
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, PartitionSpec(*axes))
        )
    except Exception:  # manual (shard_map) regions reject constraints
        return x
