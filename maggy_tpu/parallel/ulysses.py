"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

The second context-parallel scheme SURVEY.md §5.7 calls for: activations arrive
sharded on sequence; two ``all_to_all`` collectives re-shard them to
head-parallel (full sequence, H/n heads per device), attention runs locally
with any kernel, and the inverse all-to-all restores sequence sharding. Ideal
when n divides the head count and sequence lengths are moderate — one pair of
all-to-alls costs less than a full KV ring rotation for short S.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from maggy_tpu.ops.attention import _repeat_kv, blockwise_attention
from maggy_tpu.parallel.spec import AXIS_SEQ
from maggy_tpu.util import shard_map


def _local_ulysses(
    q, k, v, seg, *, axis_name: str, num_shards: int, causal: bool,
    attn_fn: Callable, use_segments: bool,
):
    # local: [B, C, H, D] with C = S/n; re-shard to [B, S, H/n, D]
    def seq_to_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qh = seq_to_heads(q)
    kh = seq_to_heads(k)
    vh = seq_to_heads(v)
    if use_segments:
        # head-parallel attention sees the FULL sequence, so every device
        # needs the full [B, S] segment ids — an all_gather of the int
        # shard (a few KB, nothing next to the qkv all-to-alls)
        seg_full = jax.lax.all_gather(seg, axis_name, axis=1, tiled=True)
        out = attn_fn(qh, kh, vh, causal=causal, segment_ids=seg_full)
    else:
        out = attn_fn(qh, kh, vh, causal=causal)
    return heads_to_seq(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh,
    causal: bool = True,
    axis_name: str = AXIS_SEQ,
    attn_fn: Optional[Callable] = None,
    segment_ids=None,
):
    """Global-view Ulysses attention: q [B,S,H,D] sharded on S over
    ``axis_name``; requires n | H and n | Kh (the all-to-all splits heads).

    ``segment_ids`` [B, S] (sharded on S) enables packed sequences: the local
    head-parallel attention receives the all-gathered full-length ids and
    masks across segment boundaries."""
    num_shards = mesh.shape[axis_name]
    h, kh = q.shape[2], k.shape[2]
    if num_shards > 1 and kh % num_shards != 0:
        # broadcast GQA heads so the all-to-all can split them
        k = _repeat_kv(k, h)
        v = _repeat_kv(v, h)
        kh = h
    inner = attn_fn or (
        lambda q, k, v, causal=True, segment_ids=None: blockwise_attention(
            q, k, v, causal=causal, segment_ids=segment_ids
        )
    )
    if num_shards == 1:
        return inner(q, k, v, causal=causal, segment_ids=segment_ids)
    if h % num_shards != 0:
        raise ValueError(
            f"Ulysses needs the seq-axis size ({num_shards}) to divide the head "
            f"count ({h}); use ring attention instead."
        )
    use_segments = segment_ids is not None
    if not use_segments:
        segment_ids = jnp.zeros(q.shape[:2], jnp.int32)  # uniform dummy
    spec = P(None, axis_name, None, None)
    fn = functools.partial(
        _local_ulysses,
        axis_name=axis_name,
        num_shards=num_shards,
        causal=causal,
        attn_fn=inner,
        use_segments=use_segments,
    )
    return shard_map(
        fn, mesh=mesh,
        in_specs=(spec, spec, spec, P(None, axis_name)),
        out_specs=spec, check_vma=False,
    )(q, k, v, segment_ids)


def make_ulysses_attention(mesh, axis_name: str = AXIS_SEQ, attn_fn=None):
    def attn(q, k, v, *, causal: bool = True, segment_ids=None):
        return ulysses_attention(
            q, k, v, mesh=mesh, causal=causal, axis_name=axis_name,
            attn_fn=attn_fn, segment_ids=segment_ids,
        )

    return attn
