"""Bucketed gradient collectives + ZeRO-1 optimizer-state sharding.

The reference delegated all of this to DeepSpeed (11 wrapped ZeRO optimizers,
core/patching/optim.py) and NCCL's stream-ordered all-reduce; here the same
two levers are native pieces of the mesh machinery:

* **Bucketing** (veScale/Lagom recipe, PAPERS.md): the param tree is cut into
  size-bounded buckets in *reverse* flatten order — the order backward
  produces gradients, last layers first — and each bucket is flattened into
  one vector and reduced with its own collective. Per-bucket collectives are
  independent of the still-running remainder of backward, which is exactly
  the freedom XLA's latency-hiding scheduler needs to hoist them into the
  compute (this jax version has no public async collective start/done pair;
  the per-bucket independence plus :func:`latency_hiding_flags` is the
  portable spelling). Reduction is **per mesh axis**: the intra-slice
  ``data`` reduce-scatter/all-reduce (ICI) issues first, the cross-slice
  all-reduce (DCN) second, so the slow DCN hop of PR 9's hierarchical sync
  overlaps independently of the fast one.
* **ZeRO-1** (``zero_stage=1``): optimizer state (adam mu/nu and any other
  optax mirror of the params) lives as the *flat bucket vectors*, sharded
  over the ``data`` axis. Each rank reduce-scatters the bucket gradient,
  updates only its shard, and all-gathers the updated params — optimizer
  memory per device shrinks by ~1/data_width. Checkpoint compatibility
  across ``zero_stage`` and world-size changes is handled by the conversion
  helpers below plus :func:`maggy_tpu.train.checkpoint.restore_zero_compat`.

Scope: the overlap step runs the model under a *manual* shard_map over the
batch axes (``slice``, ``data``). Meshes with non-trivial GSPMD-auto axes
(fsdp/tensor/seq/expert) fall back to the dense path with a one-time
warning — mixing a manual subgroup with auto param sharding hard-crashes
this XLA's SPMD partitioner (hlo_sharding_util ``IsManualSubgroup`` check),
and under fsdp the optimizer state is already sharded by the rule table
anyway (ZeRO-1 is the pure-dp complement of fsdp, not an addition to it).

Caveat (documented contract, docs/distributed.md): under ``zero_stage=1``
the optax transformation sees flat *shards*, so optimizers whose update
couples parameters across the tree (global-norm clipping, per-path masks)
compute those couplings per shard. Plain adam/adamw/sgd are exact.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "Bucket",
    "BucketPlan",
    "plan_buckets",
    "flatten_buckets",
    "unflatten_buckets",
    "flatten_opt_state",
    "unflatten_opt_state",
    "reflatten_opt_state",
    "opt_state_bytes_per_device",
    "latency_hiding_flags",
    "measure_step_times",
    "record_overlap_gauges",
]


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One reduction unit: a contiguous (in reverse flatten order) run of
    same-dtype leaves, flattened into a single padded vector."""

    name: str  # flat-tree key, "b000" ... (zero-padded: dict key order == plan order)
    indices: Tuple[int, ...]  # positions in the ORIGINAL tree-flatten leaf list
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]  # element counts per leaf, parallel to indices
    dtype: str
    size: int  # sum(sizes), before padding
    padded_size: int  # size rounded up to a multiple of the plan's pad_to


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """The full bucketing of one param tree. Pure shape metadata — built at
    trace time from abstract/concrete leaves alike, never holds arrays."""

    buckets: Tuple[Bucket, ...]
    n_leaves: int
    pad_to: int  # ZeRO shard count the padding makes every bucket divide

    @property
    def padded_sizes(self) -> frozenset:
        return frozenset(b.padded_size for b in self.buckets)


def _leaf_meta(leaf) -> Tuple[Tuple[int, ...], int, str]:
    shape = tuple(getattr(leaf, "shape", ()))
    size = math.prod(shape) if shape else 1
    return shape, size, str(getattr(leaf, "dtype", "float32"))


def plan_buckets(
    params: Any, bucket_mb: Optional[float], pad_to: int = 1
) -> BucketPlan:
    """Partition ``params``'s leaves into size-bounded reverse-order buckets.

    ``bucket_mb`` bounds each bucket's payload in MiB (None/inf = one bucket
    per dtype — the unbucketed-but-flat layout ZeRO uses by default); a
    single leaf above the bound still gets its own bucket. Leaves are walked
    in REVERSE tree-flatten order so bucket 0 holds the params whose grads
    backward produces first (output head / last layers) — its collective can
    start while the rest of backward is still running. Consecutive leaves of
    different dtype never share a bucket (one flat vector, one dtype).
    ``pad_to`` rounds every bucket up so a ZeRO reduce-scatter over that many
    shards divides evenly.
    """
    import jax

    leaves = jax.tree.leaves(params)
    if not leaves:
        raise ValueError("plan_buckets: empty param tree")
    if pad_to < 1:
        raise ValueError(f"plan_buckets: pad_to must be >= 1, got {pad_to}")
    cap = (
        float("inf")
        if bucket_mb is None or not math.isfinite(float(bucket_mb))
        else float(bucket_mb) * 2**20
    )
    metas = [_leaf_meta(l) for l in leaves]
    buckets = []
    cur: list = []
    cur_bytes = 0.0
    cur_dtype = None

    def close():
        if not cur:
            return
        idxs = tuple(i for i, _ in cur)
        shapes = tuple(m[0] for _, m in cur)
        sizes = tuple(m[1] for _, m in cur)
        total = sum(sizes)
        padded = -(-total // pad_to) * pad_to
        buckets.append(
            Bucket(
                name=f"b{len(buckets):03d}",
                indices=idxs,
                shapes=shapes,
                sizes=sizes,
                dtype=cur_dtype,
                size=total,
                padded_size=padded,
            )
        )
        cur.clear()

    for i in range(len(leaves) - 1, -1, -1):
        shape, size, dtype = metas[i]
        import numpy as np

        nbytes = size * np.dtype(dtype).itemsize
        if cur and (dtype != cur_dtype or cur_bytes + nbytes > cap):
            close()
            cur_bytes = 0.0
        cur_dtype = dtype
        cur_bytes += nbytes
        cur.append((i, metas[i]))
    close()
    return BucketPlan(
        buckets=tuple(buckets), n_leaves=len(leaves), pad_to=int(pad_to)
    )


def flatten_buckets(tree: Any, plan: BucketPlan) -> Dict[str, Any]:
    """``{bucket name: flat padded vector}`` for a tree matching the plan
    (params, grads, or any optax mirror of them). Dict insertion order is
    plan order (reverse-topological), and the zero-padded names keep
    tree-flatten (key-sorted) order identical to it."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(tree)
    if len(leaves) != plan.n_leaves:
        raise ValueError(
            f"flatten_buckets: tree has {len(leaves)} leaves, plan expects "
            f"{plan.n_leaves}"
        )
    out = {}
    for b in plan.buckets:
        segs = [jnp.ravel(leaves[i]) for i in b.indices]
        vec = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
        if b.padded_size != b.size:
            vec = jnp.concatenate(
                [vec, jnp.zeros((b.padded_size - b.size,), vec.dtype)]
            )
        out[b.name] = vec
    return out


def unflatten_buckets(
    flats: Dict[str, Any], plan: BucketPlan, template: Any
) -> Any:
    """Inverse of :func:`flatten_buckets`: rebuild a tree with ``template``'s
    structure (params/grads tree — boxes and all) from the flat vectors."""
    import jax

    treedef = jax.tree.structure(template)
    leaves: list = [None] * plan.n_leaves
    for b in plan.buckets:
        vec = flats[b.name]
        off = 0
        for i, shape, size in zip(b.indices, b.shapes, b.sizes):
            leaves[i] = vec[off : off + size].reshape(shape)
            off += size
    return jax.tree.unflatten(treedef, leaves)


# ------------------------------------------------------------- optax states


def _is_tree_like(x, struct) -> bool:
    import jax

    try:
        return jax.tree.structure(x) == struct
    except Exception:  # noqa: BLE001 - foreign nodes: simply not a match
        return False


def flatten_opt_state(opt_state: Any, plan: BucketPlan, params_template: Any):
    """Convert a dense optax state (mirrors of the param tree) into the flat
    ZeRO layout: every subtree structurally identical to the param tree
    becomes a ``{bucket: vector}`` dict; loose leaves (adam count, ...) pass
    through untouched."""
    import jax

    pstruct = jax.tree.structure(params_template)

    def conv(x):
        return flatten_buckets(x, plan) if _is_tree_like(x, pstruct) else x

    return jax.tree.map(
        conv, opt_state, is_leaf=lambda x: _is_tree_like(x, pstruct)
    )


def unflatten_opt_state(opt_state: Any, plan: BucketPlan, params_template: Any):
    """Inverse of :func:`flatten_opt_state`: flat ``{bucket: vector}`` dicts
    become param-tree mirrors again (padding dropped)."""
    import jax

    fstruct = jax.tree.structure({b.name: 0 for b in plan.buckets})

    def conv(x):
        return (
            unflatten_buckets(x, plan, params_template)
            if _is_tree_like(x, fstruct)
            else x
        )

    return jax.tree.map(
        conv, opt_state, is_leaf=lambda x: _is_tree_like(x, fstruct)
    )


def reflatten_opt_state(
    opt_state: Any,
    old_plan: BucketPlan,
    new_plan: BucketPlan,
    params_template: Any,
):
    """Re-bucket a flat ZeRO state across plans (bucket_mb or data-width
    change): old flats -> dense mirrors -> new flats. Padding is rebuilt for
    the new plan, so any world-size transition whose layouts are otherwise
    compatible round-trips exactly."""
    dense = unflatten_opt_state(opt_state, old_plan, params_template)
    return flatten_opt_state(dense, new_plan, params_template)


def opt_state_bytes_per_device(abstract_state, state_shardings) -> int:
    """Per-device bytes of the optimizer state implied by its shardings —
    an ahead-of-time accounting from shapes alone (``shard_shape``), no
    allocation. The ZeRO-1 acceptance check: this shrinks ~1/data_width."""
    import math as _math

    import jax
    import numpy as np

    total = 0
    for leaf, s in zip(
        jax.tree.leaves(abstract_state.opt_state),
        jax.tree.leaves(state_shardings.opt_state),
    ):
        shape = tuple(getattr(leaf, "shape", ()))
        shard = s.shard_shape(shape) if hasattr(s, "shard_shape") else shape
        total += _math.prod(shard) * np.dtype(leaf.dtype).itemsize
    return int(total)


# ------------------------------------------------------------ measurement


def latency_hiding_flags() -> Tuple[str, ...]:
    """XLA flags that let the scheduler hoist the per-bucket collectives
    into the remaining backward on real TPU backends (must be in XLA_FLAGS
    *before* backend init — the CPU test backend ignores them). The
    bucketed step is built so these are sufficient: each bucket's reduction
    depends only on its own grads, never on later buckets'."""
    return (
        "--xla_tpu_enable_latency_hiding_scheduler=true",
        "--xla_tpu_enable_async_collective_fusion=true",
        "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
        "--xla_tpu_overlap_compute_collective_tc=true",
    )


def measure_step_times(
    entries: Dict[str, Tuple[Any, Any]], batch, repeats: int = 5
) -> Dict[str, float]:
    """Min-of-``repeats`` wall time (ms) per labelled step variant.

    ``entries`` maps label -> ``(step_fn, state)`` where ``step_fn(state,
    batch) -> (state, metrics)`` is a compiled train step and ``state`` is
    that variant's own TrainState (steps donate their input, so variants
    must not share one). The first call per variant is the untimed
    compile/warmup; timed calls feed the returned state back in."""
    import time as _time

    import jax

    out = {}
    for label, (fn, state) in entries.items():
        state, metrics = fn(state, batch)
        jax.block_until_ready(metrics)  # compile + warmup
        best = float("inf")
        for _ in range(max(1, int(repeats))):
            t0 = _time.perf_counter()
            state, metrics = fn(state, batch)
            jax.block_until_ready((state, metrics))
            best = min(best, (_time.perf_counter() - t0) * 1e3)
        out[label] = best
    return out


def record_overlap_gauges(
    times: Dict[str, float], manual_axes, telemetry_recorder=None
) -> Dict[str, float]:
    """Fold measured step times into the ``train.comm_*`` gauges.

    ``times`` needs ``dense`` (unbucketed GSPMD step), ``bucketed`` (full
    overlap step) and ``nocomm`` (overlap step with every reduction
    stripped — pure compute); optional ``only_<axis>`` entries (reduction
    over one mesh axis only) yield the per-axis ICI-vs-DCN exposure
    gauges. total comm = dense - nocomm; exposed = bucketed - nocomm;
    overlapped = total - exposed."""
    from maggy_tpu import telemetry

    tel = telemetry_recorder if telemetry_recorder is not None else telemetry.get()
    nocomm = times["nocomm"]
    total = max(times["dense"] - nocomm, 0.0)
    exposed = max(times["bucketed"] - nocomm, 0.0)
    overlapped = max(total - exposed, 0.0)
    tel.gauge("train.comm_exposed_ms", exposed)
    tel.gauge("train.comm_overlapped_ms", overlapped)
    out = {
        "comm_total_ms": total,
        "comm_exposed_ms": exposed,
        "comm_overlapped_ms": overlapped,
    }
    for ax in manual_axes:
        key = f"only_{ax}"
        if key in times:
            ax_exposed = max(times[key] - nocomm, 0.0)
            tel.gauge(f"train.comm_exposed_ms.{ax}", ax_exposed)
            out[f"comm_exposed_ms_{ax}"] = ax_exposed
    return out
