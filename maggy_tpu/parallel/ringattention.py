"""Ring attention: sequence/context parallelism over the ICI ring.

Absent from the reference (SURVEY.md §5.7) and first-class here: the global
sequence is sharded over the ``seq`` mesh axis; each device computes attention
for its query shard while KV shards rotate around the ring via
``jax.lax.ppermute`` (XLA lowers neighbor permutes onto ICI links and overlaps
them with the per-step compute). Per-device memory stays O(S/n · S/n) per
block and the full [S, S] score matrix never exists anywhere.

The per-step math is the shared online-softmax block update from
:mod:`maggy_tpu.ops.attention`, so ring attention is numerically the blockwise
schedule with blocks distributed over devices.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from maggy_tpu.ops import attention as ops_attn
from maggy_tpu.parallel.spec import AXIS_SEQ


def _local_ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    num_shards: int,
    causal: bool,
):
    """Runs on each device under shard_map: q [B,C,H,D], k/v [B,C,Kh,D] local
    seq shards. KV rotates at its native (grouped) head count — broadcasting to
    the query head count happens per-step on the compute side, so GQA pays
    h/kh times less ICI traffic."""
    b, c, h, d = q.shape
    scale = 1.0 / (d**0.5)
    my_idx = jax.lax.axis_index(axis_name)
    q_pos = my_idx * c + jnp.arange(c)

    def body(step, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (my_idx - step) % num_shards  # which KV chunk we hold this step
        k_pos = src * c + jnp.arange(c)
        if causal:
            mask = (q_pos[None, None, :, None] >= k_pos[None, None, None, :])
        else:
            mask = jnp.ones((1, 1, c, c), bool)
        acc, m, l = ops_attn.online_block_update(
            (acc, m, l),
            q,
            ops_attn._repeat_kv(k_cur, h),
            ops_attn._repeat_kv(v_cur, h),
            mask,
            scale,
        )
        # rotate KV to the next device; device i receives chunk from i-1
        perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    carry = (*ops_attn.init_carry(b, h, c, d), k, v)
    acc, m, l, _, _ = jax.lax.fori_loop(0, num_shards, body, carry)
    return ops_attn._finalize(acc, l, q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh,
    causal: bool = True,
    axis_name: str = AXIS_SEQ,
    segment_ids=None,
    impl: str = "xla",
    interpret=None,
):
    """Global-view ring attention: q [B,S,H,D], k/v [B,S,Kh,D] sharded on S.

    Call under ``jit`` with the mesh active; works as the Decoder's
    ``attention_fn`` when the sharding spec has ``sp > 1``.

    :param impl: ``"xla"`` — the shard_map/ppermute ring (XLA schedules the
        rotation; fully differentiable). ``"pallas"`` — the
        :mod:`maggy_tpu.ops.ring_flash` kernel: the KV rotation is issued
        in-kernel via ``make_async_remote_copy`` and explicitly overlapped
        with the block compute, forward AND backward (the bwd ring rotates
        (k, v, dk, dv) together, recomputing probabilities from the saved
        LSE). ``"auto"`` — the XLA ring unless the mesh is on TPU *and*
        ``MAGGY_TPU_RING_PALLAS=1`` is set: the RDMA kernel has not yet been
        timed on real multi-chip ICI, so an untimed kernel is never the
        silent default training path (it stays one env var away, and the
        ``bench.py`` ring microbench records the comparison when hardware
        allows).
    :param interpret: pallas only — run under the TPU interpret machine
        (defaults to True off-TPU so CPU meshes can test the kernel).
    """
    if segment_ids is not None:
        raise NotImplementedError("ring attention does not support segment_ids yet")
    if impl == "auto":
        # resolve from the mesh's devices, not the process default backend —
        # a CPU mesh created on a TPU-capable host must not pick pallas
        on_tpu = mesh.devices.flat[0].platform == "tpu"
        opt_in = os.environ.get("MAGGY_TPU_RING_PALLAS") == "1"
        impl = "pallas" if (on_tpu and opt_in) else "xla"
    if impl not in ("xla", "pallas"):
        raise ValueError(f"impl must be 'xla', 'pallas', or 'auto', got {impl!r}")
    num_shards = mesh.shape[axis_name]
    if num_shards == 1:
        return ops_attn.blockwise_attention(q, k, v, causal=causal)

    if impl == "pallas":
        return _pallas_ring(
            q, k, v, mesh=mesh, causal=causal, axis_name=axis_name,
            interpret=interpret,
        )
    return _xla_ring(q, k, v, mesh=mesh, causal=causal, axis_name=axis_name)


def _xla_ring(q, k, v, *, mesh, causal, axis_name):
    num_shards = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)
    fn = functools.partial(
        _local_ring_attention,
        axis_name=axis_name,
        num_shards=num_shards,
        causal=causal,
    )
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def _pallas_ring(q, k, v, *, mesh, causal, axis_name, interpret):
    from maggy_tpu.ops.ring_flash import ring_flash_attention

    if interpret is None:
        interpret = mesh.devices.flat[0].platform != "tpu"
    # the kernel carries its own custom_vjp (ring backward with rotating
    # dk/dv accumulators) — nothing to wrap here
    return ring_flash_attention(
        q, k, v, mesh=mesh, causal=causal, axis_name=axis_name,
        interpret=interpret,
    )


def make_ring_attention(mesh, axis_name: str = AXIS_SEQ, impl: str = "auto"):
    """Build an ``attention_fn`` for DecoderConfig: same signature as
    ``default_attention``. ``impl="auto"`` trains through the XLA ppermute
    ring; set ``MAGGY_TPU_RING_PALLAS=1`` on a TPU mesh to opt into the RDMA
    Pallas kernel (fwd+bwd) once it has a recorded win on real ICI."""

    def attn(q, k, v, *, causal: bool = True, segment_ids=None):
        return ring_attention(
            q, k, v, mesh=mesh, causal=causal, axis_name=axis_name,
            segment_ids=segment_ids, impl=impl,
        )

    return attn
