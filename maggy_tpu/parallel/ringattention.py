"""Ring attention: sequence/context parallelism over the ICI ring.

Absent from the reference (SURVEY.md §5.7) and first-class here: the global
sequence is sharded over the ``seq`` mesh axis; each device computes attention
for its query shard while KV shards rotate around the ring via
``jax.lax.ppermute`` (XLA lowers neighbor permutes onto ICI links and overlaps
them with the per-step compute). Per-device memory stays O(S/n · S/n) per
block and the full [S, S] score matrix never exists anywhere.

The per-step math is the shared online-softmax block update from
:mod:`maggy_tpu.ops.attention`, so ring attention is numerically the blockwise
schedule with blocks distributed over devices.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from maggy_tpu.ops import attention as ops_attn
from maggy_tpu.parallel.spec import AXIS_SEQ
from maggy_tpu.util import shard_map


def _local_ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    seg: jax.Array,
    *,
    axis_name: str,
    num_shards: int,
    causal: bool,
    use_segments: bool,
):
    """Runs on each device under shard_map: q [B,C,H,D], k/v [B,C,Kh,D] local
    seq shards. KV rotates at its native (grouped) head count — broadcasting to
    the query head count happens per-step on the compute side, so GQA pays
    h/kh times less ICI traffic. With ``use_segments``, the [B,C] segment-id
    shard rotates alongside KV and scores are masked where query and key
    segments differ (packed sequences, SURVEY §5.7)."""
    b, c, h, d = q.shape
    scale = 1.0 / (d**0.5)
    my_idx = jax.lax.axis_index(axis_name)
    q_pos = my_idx * c + jnp.arange(c)

    def body(step, carry):
        acc, m, l, k_cur, v_cur, seg_cur = carry
        src = (my_idx - step) % num_shards  # which KV chunk we hold this step
        k_pos = src * c + jnp.arange(c)
        if causal:
            mask = (q_pos[None, None, :, None] >= k_pos[None, None, None, :])
        else:
            mask = jnp.ones((1, 1, c, c), bool)
        if use_segments:
            mask = mask & (
                seg[:, None, :, None] == seg_cur[:, None, None, :]
            )
        acc, m, l = ops_attn.online_block_update(
            (acc, m, l),
            q,
            ops_attn._repeat_kv(k_cur, h),
            ops_attn._repeat_kv(v_cur, h),
            mask,
            scale,
        )
        # rotate KV (and its segment ids) to the next device; device i
        # receives the chunk from i-1
        perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        seg_nxt = (
            jax.lax.ppermute(seg_cur, axis_name, perm) if use_segments else seg_cur
        )
        return acc, m, l, k_nxt, v_nxt, seg_nxt

    carry = (*ops_attn.init_carry(b, h, c, d), k, v, seg)
    acc, m, l, _, _, _ = jax.lax.fori_loop(0, num_shards, body, carry)
    return ops_attn._finalize(acc, l, q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh,
    causal: bool = True,
    axis_name: str = AXIS_SEQ,
    segment_ids=None,
    impl: str = "xla",
    interpret=None,
):
    """Global-view ring attention: q [B,S,H,D], k/v [B,S,Kh,D] sharded on S.

    Call under ``jit`` with the mesh active; works as the Decoder's
    ``attention_fn`` when the sharding spec has ``sp > 1``.

    :param impl: ``"xla"`` — the shard_map/ppermute ring (XLA schedules the
        rotation; fully differentiable). ``"pallas"`` — the
        :mod:`maggy_tpu.ops.ring_flash` kernel: the KV rotation is issued
        in-kernel via ``make_async_remote_copy`` and explicitly overlapped
        with the block compute, forward AND backward (the bwd ring rotates
        (k, v, dk, dv) together, recomputing probabilities from the saved
        LSE). ``"auto"`` — the XLA ring unless the mesh is on TPU *and*
        ``MAGGY_TPU_RING_PALLAS=1`` is set: the RDMA kernel has not yet been
        timed on real multi-chip ICI, so an untimed kernel is never the
        silent default training path (it stays one env var away, and the
        ``bench.py`` ring microbench records the comparison when hardware
        allows).
    :param interpret: pallas only — run under the TPU interpret machine
        (defaults to True off-TPU so CPU meshes can test the kernel).
    :param segment_ids: optional [B, S] int ids for packed sequences (sharded
        on S like q/k/v); tokens only attend within their own segment. The
        segment-id shard rotates around the ring with its KV shard. Supported
        on the XLA ring; the Pallas kernel rejects it for now.
    """
    if impl == "auto":
        # resolve from the mesh's devices, not the process default backend —
        # a CPU mesh created on a TPU-capable host must not pick pallas
        on_tpu = mesh.devices.flat[0].platform == "tpu"
        opt_in = os.environ.get("MAGGY_TPU_RING_PALLAS") == "1"
        impl = "pallas" if (on_tpu and opt_in and segment_ids is None) else "xla"
    if impl not in ("xla", "pallas"):
        raise ValueError(f"impl must be 'xla', 'pallas', or 'auto', got {impl!r}")
    num_shards = mesh.shape[axis_name]
    if num_shards == 1:
        return ops_attn.blockwise_attention(
            q, k, v, causal=causal, segment_ids=segment_ids
        )

    if impl == "pallas":
        if segment_ids is not None:
            raise NotImplementedError(
                "the Pallas RDMA ring kernel does not support segment_ids; "
                "use impl='xla' (or 'auto', which routes packed batches there)"
            )
        return _pallas_ring(
            q, k, v, mesh=mesh, causal=causal, axis_name=axis_name,
            interpret=interpret,
        )
    return _xla_ring(
        q, k, v, segment_ids, mesh=mesh, causal=causal, axis_name=axis_name
    )


def _xla_ring(q, k, v, segment_ids, *, mesh, causal, axis_name):
    num_shards = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)
    seg_spec = P(None, axis_name)
    use_segments = segment_ids is not None
    if not use_segments:
        # uniform dummy (never read): keeps one shard_map signature
        segment_ids = jnp.zeros(q.shape[:2], jnp.int32)
    fn = functools.partial(
        _local_ring_attention,
        axis_name=axis_name,
        num_shards=num_shards,
        causal=causal,
        use_segments=use_segments,
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec, seg_spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v, segment_ids)


def _pallas_ring(q, k, v, *, mesh, causal, axis_name, interpret):
    from maggy_tpu.ops.ring_flash import ring_flash_attention

    if interpret is None:
        interpret = mesh.devices.flat[0].platform != "tpu"
    # the kernel carries its own custom_vjp (ring backward with rotating
    # dk/dv accumulators) — nothing to wrap here
    return ring_flash_attention(
        q, k, v, mesh=mesh, causal=causal, axis_name=axis_name,
        interpret=interpret,
    )


def make_ring_attention(mesh, axis_name: str = AXIS_SEQ, impl: str = "auto"):
    """Build an ``attention_fn`` for DecoderConfig: same signature as
    ``default_attention``. ``impl="auto"`` trains through the XLA ppermute
    ring; set ``MAGGY_TPU_RING_PALLAS=1`` on a TPU mesh to opt into the RDMA
    Pallas kernel (fwd+bwd) once it has a recorded win on real ICI."""

    def attn(q, k, v, *, causal: bool = True, segment_ids=None):
        return ring_attention(
            q, k, v, mesh=mesh, causal=causal, axis_name=axis_name,
            segment_ids=segment_ids, impl=impl,
        )

    return attn
