"""Device-mesh construction.

The TPU-native replacement for the reference's NCCL/Spark topology plumbing
(§2.9): a ``jax.sharding.Mesh`` over the five canonical axes of
:class:`~maggy_tpu.parallel.spec.ShardingSpec`. XLA emits the collectives; the
axis ordering below decides which collectives ride ICI vs DCN.

Axis order (outer→inner): data, fsdp, expert, seq, tensor. ``jax.devices()``
orders TPU devices so that physically adjacent chips are adjacent in the list;
putting ``tensor`` (all-reduce every layer) innermost keeps its collectives on
the shortest ICI paths, while ``data`` (one gradient all-reduce per step)
outermost tolerates DCN hops across slices — the scaling-book layout recipe.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from maggy_tpu.parallel.spec import MESH_AXES, ShardingSpec


def make_mesh(spec: ShardingSpec, devices: Optional[List] = None):
    """Build a Mesh for ``spec``; validates the device count matches."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if spec.num_devices != len(devices):
        raise ValueError(
            f"ShardingSpec covers {spec.num_devices} devices but {len(devices)} "
            f"are provided; use spec.scaled_to({len(devices)})."
        )
    arr = np.asarray(devices).reshape(spec.axis_sizes())
    return Mesh(arr, MESH_AXES)


def ambient_mesh():
    """The Mesh made current via ``with mesh:`` (None outside any context).

    Lets shape-dispatching ops (e.g. auto_attention) discover the mesh a
    Trainer step is tracing under without explicit plumbing. Guarded: the
    accessor is private JAX API, and dispatchers treat None as "no mesh"
    (falling back to fully-partitionable XLA ops), so a JAX reorganization
    degrades performance, never correctness."""
    try:
        from jax._src.mesh import thread_resources

        m = thread_resources.env.physical_mesh
        return None if m.empty else m
    except Exception:
        return None


def mesh_for(num_devices: Optional[int] = None, sharding="fsdp", devices=None):
    """Convenience: resolve a preset/spec against the available devices."""
    import jax

    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    if isinstance(sharding, ShardingSpec):
        spec = (
            sharding
            if sharding.num_devices == len(devices)
            else sharding.scaled_to(len(devices))
        )
    else:
        spec = ShardingSpec.preset(sharding, len(devices))
    return make_mesh(spec, devices), spec
