"""Device-mesh construction.

The TPU-native replacement for the reference's NCCL/Spark topology plumbing
(§2.9): a ``jax.sharding.Mesh`` over the five canonical axes of
:class:`~maggy_tpu.parallel.spec.ShardingSpec`. XLA emits the collectives; the
axis ordering below decides which collectives ride ICI vs DCN.

Axis order (outer→inner): data, fsdp, expert, seq, tensor. ``jax.devices()``
orders TPU devices so that physically adjacent chips are adjacent in the list;
putting ``tensor`` (all-reduce every layer) innermost keeps its collectives on
the shortest ICI paths, while ``data`` (one gradient all-reduce per step)
outermost tolerates DCN hops across slices — the scaling-book layout recipe.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from maggy_tpu.parallel.spec import (
    MESH_AXES,
    SLICE_MESH_AXES,
    ShardingSpec,
    SliceTopology,
)


def make_mesh(spec: ShardingSpec, devices: Optional[List] = None):
    """Build a Mesh for ``spec``; validates the device count matches."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if spec.num_devices != len(devices):
        raise ValueError(
            f"ShardingSpec covers {spec.num_devices} devices but {len(devices)} "
            f"are provided; use spec.scaled_to({len(devices)})."
        )
    arr = np.asarray(devices).reshape(spec.axis_sizes())
    return Mesh(arr, MESH_AXES)


def make_slice_mesh(topology: SliceTopology, devices: Optional[List] = None):
    """Build a Mesh with the outer ``slice`` axis for ``topology``.

    ``devices`` must list the active slices' devices slice-contiguously
    (slice 0's devices, then slice 1's, ...) — ``slice_device_groups``
    produces exactly that ordering for simulated slices, and
    ``jax.devices()`` already orders a real multi-slice fleet this way
    (slice-major). Elastic reshape = call again with the surviving slices'
    devices and ``topology.with_slices(len(survivors))``.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if topology.num_devices != len(devices):
        raise ValueError(
            f"SliceTopology covers {topology.num_devices} devices "
            f"({topology.n_slices} slice(s) x {topology.devices_per_slice}) "
            f"but {len(devices)} are provided"
        )
    arr = np.asarray(devices).reshape(topology.axis_sizes())
    return Mesh(arr, SLICE_MESH_AXES)


def slice_device_groups(n_slices: int, devices: Optional[List] = None) -> List[list]:
    """Partition a device list into ``n_slices`` contiguous simulated
    slices (slice-major order, matching ``make_slice_mesh``'s expectation).

    This generalizes the dryrun machinery: with
    ``xla_force_host_platform_device_count=16`` a 4-slice x 4-chip elastic
    geometry runs entirely on the CPU mesh, so membership reshape and the
    cross-slice collective layout are testable without a fleet. The device
    count must divide evenly — ragged slices would make the reshape's
    per-slice program shapes diverge.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if n_slices < 1:
        raise ValueError(f"n_slices must be >= 1, got {n_slices}")
    if len(devices) % n_slices != 0:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_slices} equal "
            "slices; adjust num_slices or the device count"
        )
    per = len(devices) // n_slices
    return [devices[i * per : (i + 1) * per] for i in range(n_slices)]


def ambient_mesh():
    """The Mesh made current via ``with mesh:`` (None outside any context).

    Lets shape-dispatching ops (e.g. auto_attention) discover the mesh a
    Trainer step is tracing under without explicit plumbing. Guarded: the
    accessor is private JAX API, and dispatchers treat None as "no mesh"
    (falling back to fully-partitionable XLA ops), so a JAX reorganization
    degrades performance, never correctness."""
    try:
        from jax._src.mesh import thread_resources

        m = thread_resources.env.physical_mesh
        return None if m.empty else m
    except Exception:
        return None


def mesh_for(num_devices: Optional[int] = None, sharding="fsdp", devices=None):
    """Convenience: resolve a preset/spec against the available devices."""
    import jax

    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    if isinstance(sharding, ShardingSpec):
        spec = (
            sharding
            if sharding.num_devices == len(devices)
            else sharding.scaled_to(len(devices))
        )
    else:
        spec = ShardingSpec.preset(sharding, len(devices))
    return make_mesh(spec, devices), spec
