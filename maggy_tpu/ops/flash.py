"""Pallas TPU flash attention — forward AND backward (training-grade).

Same online-softmax math as :mod:`maggy_tpu.ops.attention`, hand-tiled for the
MXU. The forward runs grid (batch*heads, q_blocks, k_blocks) with fp32 running
statistics in VMEM scratch; causal blocks are skipped wholesale and the [S, S]
score matrix never leaves VMEM tiles. The backward is the standard TPU
two-kernel split (FlashAttention-2 recurrence): a dQ kernel accumulating over
KV blocks and a dK/dV kernel accumulating over Q blocks, both recomputing the
probabilities from the saved per-row log-sum-exp instead of storing them.
``delta = rowsum(dO * O)`` is recomputed per tile from the O/dO blocks so the
only extra residual is the [BH, S] LSE (stored in column layout
``[BH, n_q, block_q, 1]`` so neither direction ever needs a sublane<->lane
relayout).

This makes the kernel a drop-in for the *training* hot path — the gap the
round-1 verdict called out (training previously fell back to the XLA fused
dense path, which materializes [B, H, S, S] fp32 logits in HBM).

Falls back to the interpreter off-TPU so tests run on CPU meshes; shapes that
do not tile evenly fall back to ``blockwise_attention`` (differentiable).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from maggy_tpu.ops.attention import NEG_INF, _repeat_kv, blockwise_attention
from maggy_tpu.util import shard_map

_LANES = 128


def _tile_mask(q_start, k_start, block_q, block_k):
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return (q_start + rows) >= (k_start + cols)


# --------------------------------------------------------------------- forward


def _fwd_kernel(
    *refs,
    scale, causal, block_q, block_k, segmented,
):
    if segmented:
        (q_ref, k_ref, v_ref, qseg_ref, kseg_ref,
         o_ref, lse_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # causal: skip blocks strictly above the diagonal (always "needed" otherwise)
    needed = (k_start <= q_start + block_q - 1) if causal else (ki >= 0)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        mask = _tile_mask(q_start, k_start, block_q, block_k) if causal else None
        if segmented:
            smask = qseg_ref[0][:, None] == kseg_ref[0][None, :]
            mask = smask if mask is None else (mask & smask)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, :1]
        m_cur = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_cur)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_cur)
        l_new = l_ref[:, :1] * corr + p.sum(axis=1, keepdims=True)
        # stats stored replicated across lanes (full-width VMEM stores)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * corr + pv

    @pl.when(ki == nk - 1)
    def _finalize():
        l_fin = l_ref[:, :1]
        denom = jnp.maximum(l_fin, 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        # rows with no visible key get lse=+inf so the backward's
        # exp(s - lse) is exactly zero for them
        lse_ref[0, 0] = jnp.where(
            l_fin > 0, m_ref[:, :1] + jnp.log(denom), jnp.inf
        )


def _fwd_call(q, k, v, segs, *, causal, block_q, block_k, group, heads, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q, sk // block_k)
    segmented = segs is not None
    # GQA lives in the index map: q-head row i reads KV row i // group, so the
    # repeated [B,S,H,D] K/V never materialize in HBM (review finding r2);
    # segment ids are per (batch, seq) — row i // heads — shared by all heads
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, qi, ki: (i, qi, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, qi, ki: (i // group, ki, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, qi, ki: (i // group, ki, 0), memory_space=pltpu.VMEM),
    ]
    operands = [q, k, v]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda i, qi, ki: (i // heads, qi), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k), lambda i, qi, ki: (i // heads, ki), memory_space=pltpu.VMEM),
        ]
        operands += [segs, segs]
    return pl.pallas_call(
        functools.partial(
            _fwd_kernel,
            scale=1.0 / d**0.5,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            segmented=segmented,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, qi, ki: (i, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda i, qi, ki: (i, qi, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq // block_q, block_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)


# -------------------------------------------------------------------- backward


def _recompute_p_ds(
    q, k, v, o, do, lse, *, scale, causal, q_start, k_start, qseg=None, kseg=None
):
    """Shared tile math: probabilities from the saved LSE, then
    dS = P * (dP - delta) * scale with delta recomputed from the O/dO tiles.
    The full forward mask (causal AND segments) must be re-applied — exp(s -
    lse) is not zero for positions the forward masked out."""
    block_q, block_k = q.shape[0], k.shape[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    p = jnp.exp(s - lse)  # lse [block_q, 1]
    mask = _tile_mask(q_start, k_start, block_q, block_k) if causal else None
    if qseg is not None:
        smask = qseg[:, None] == kseg[None, :]
        mask = smask if mask is None else (mask & smask)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=1, keepdims=True
    )
    ds = p * (dp - delta) * scale
    return p, ds


def _dq_kernel(
    *refs,
    scale, causal, block_q, block_k, segmented,
):
    if segmented:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, qseg_ref, kseg_ref,
         dq_ref, acc_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, acc_ref = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    needed = (k_start <= q_start + block_q - 1) if causal else (ki >= 0)

    @pl.when(needed)
    def _compute():
        k = k_ref[0]
        _, ds = _recompute_p_ds(
            q_ref[0], k, v_ref[0], o_ref[0], do_ref[0], lse_ref[0, 0],
            scale=scale, causal=causal, q_start=q_start, k_start=k_start,
            qseg=qseg_ref[0] if segmented else None,
            kseg=kseg_ref[0] if segmented else None,
        )
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(
    *refs,
    scale, causal, block_q, block_k, segmented,
):
    if segmented:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, qseg_ref, kseg_ref,
         dk_ref, dv_ref, dk_acc_ref, dv_acc_ref) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
         dk_ref, dv_ref, dk_acc_ref, dv_acc_ref) = refs
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # causal: a KV block only receives gradient from Q blocks at/after the diagonal
    needed = (q_start + block_q - 1 >= k_start) if causal else (qi >= 0)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        p, ds = _recompute_p_ds(
            q, k_ref[0], v_ref[0], o_ref[0], do, lse_ref[0, 0],
            scale=scale, causal=causal, q_start=q_start, k_start=k_start,
            qseg=qseg_ref[0] if segmented else None,
            kseg=kseg_ref[0] if segmented else None,
        )
        # dV += P^T dO ; dK += dS^T Q — contract the q dim of both operands
        dv_acc_ref[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc_ref[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _bwd_call(
    q, k, v, o, do, lse, segs,
    *, causal, block_q, block_k, group, heads, interpret,
):
    bh, sq, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / d**0.5
    segmented = segs is not None
    q_spec = pl.BlockSpec((1, block_q, d), lambda i, qi, ki: (i, qi, 0), memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, block_k, d), lambda i, qi, ki: (i // group, ki, 0), memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec(
        (1, 1, block_q, 1), lambda i, qi, ki: (i, qi, 0, 0), memory_space=pltpu.VMEM
    )
    in_specs = [q_spec, k_spec, k_spec, q_spec, q_spec, lse_spec]
    operands = [q, k, v, o, do, lse]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda i, qi, ki: (i // heads, qi), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k), lambda i, qi, ki: (i // heads, ki), memory_space=pltpu.VMEM),
        ]
        operands += [segs, segs]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, segmented=segmented,
        ),
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*operands)

    # dkv grid: KV blocks outer, Q blocks inner (accumulate across Q). Outputs
    # are per *q-head* ([BH, S, D]); a KV block cannot accumulate across grid-i
    # revisits, so the group sum down to [B*Kh, S, D] happens in the caller.
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda i, ki, qi: (i, qi, 0), memory_space=pltpu.VMEM)
    k_spec2 = pl.BlockSpec((1, block_k, d), lambda i, ki, qi: (i // group, ki, 0), memory_space=pltpu.VMEM)
    o_spec2 = pl.BlockSpec((1, block_k, d), lambda i, ki, qi: (i, ki, 0), memory_space=pltpu.VMEM)
    lse_spec2 = pl.BlockSpec(
        (1, 1, block_q, 1), lambda i, ki, qi: (i, qi, 0, 0), memory_space=pltpu.VMEM
    )
    in_specs2 = [q_spec2, k_spec2, k_spec2, q_spec2, q_spec2, lse_spec2]
    operands2 = [q, k, v, o, do, lse]
    if segmented:
        in_specs2 += [
            pl.BlockSpec((1, block_q), lambda i, ki, qi: (i // heads, qi), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k), lambda i, ki, qi: (i // heads, ki), memory_space=pltpu.VMEM),
        ]
        operands2 += [segs, segs]
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, segmented=segmented,
        ),
        grid=(bh, sk // block_k, sq // block_q),
        in_specs=in_specs2,
        out_specs=[o_spec2, o_spec2],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands2)
    return dq, dk, dv


@functools.lru_cache(maxsize=None)
def _flash_core(
    causal: bool, block_q: int, block_k: int, bwd_block_q: int,
    bwd_block_k: int, group: int, heads: int, interpret: bool,
    segmented: bool,
):
    """Differentiable flash attention on q [B*H, S, D], k/v [B*Kh, S, D]
    (GQA group = H // Kh handled by kernel index maps — the repeated K/V
    never exist, in HBM or as residuals). With ``segmented``, a fourth
    [B, S] int32 operand masks attention across packed-sequence
    boundaries (zero cotangent). Backward tiles are independent of the
    forward's — the dq/dkv kernels hold 6+ operands per tile, so their VMEM
    sweet spot can differ (tools/tune_flash.py sweeps both on silicon)."""

    kw = dict(causal=causal, block_q=block_q, block_k=block_k, group=group,
              heads=heads, interpret=interpret)
    bwd_kw = dict(kw, block_q=bwd_block_q, block_k=bwd_block_k)

    @jax.custom_vjp
    def core(q, k, v, segs):
        return _fwd_call(q, k, v, segs if segmented else None, **kw)[0]

    def core_fwd(q, k, v, segs):
        o, lse = _fwd_call(q, k, v, segs if segmented else None, **kw)
        return o, (q, k, v, segs, o, lse)

    def core_bwd(res, g):
        q, k, v, segs, o, lse = res
        if bwd_block_q != block_q:
            # the LSE residual is stored chunked by the FORWARD's q tile
            # ([BH, n_q, block_q, 1], contiguous in sq) — re-chunk for the
            # backward's tiling
            bh_, _, _, _ = lse.shape
            sq_ = q.shape[1]
            lse = lse.reshape(bh_, sq_ // bwd_block_q, bwd_block_q, 1)
        dq, dk_h, dv_h = _bwd_call(
            q, k, v, o, g.astype(o.dtype), lse,
            segs if segmented else None, **bwd_kw,
        )
        if group > 1:
            # dkv kernel emits per-q-head grads; sum each GQA group in fp32
            bh, sk, d = dk_h.shape

            def gsum(x, dtype):
                x = x.reshape(bh // group, group, sk, d).astype(jnp.float32)
                return x.sum(axis=1).astype(dtype)

            dk_h, dv_h = gsum(dk_h, k.dtype), gsum(dv_h, v.dtype)
        return dq, dk_h, dv_h, None  # int segment ids: no cotangent

    core.defvjp(core_fwd, core_bwd)
    return core


def _env_tile(name: str):
    """Optional hardware-tuned backward tile override (set by the watchdog
    playbook after a tools/tune_flash.py sweep on live silicon; see
    tools/tpu_playbook.py). Invalid values are ignored, not fatal."""
    val = os.environ.get(name, "")
    try:
        n = int(val)
    except ValueError:
        return None
    return n if n > 0 else None


def _pick_divisor(s: int, cap: int) -> int:
    """Largest power-of-two-stepped divisor of ``s`` that is ≤ cap (floor 8;
    the floor can be a non-divisor for odd/tiny s, which the alignment check
    in _flash_attention_jit then routes to blockwise)."""
    b = min(cap, s)
    while s % b:
        b //= 2
    return max(b, 8)


def _snap_tile(tile, s: int):
    """Snap an env-sourced tile to the largest sublane-aligned (multiple of
    8) real divisor of the call's sequence ≤ tile, so a size tuned at one
    geometry cannot silently demote a differently-shaped call to the
    blockwise fallback (an explicit function argument, by contrast, is
    honored verbatim). Returns None — meaning 'use the auto default' — when
    no aligned divisor exists."""
    if not tile:
        return None
    b = min(tile, s)
    b -= b % 8
    while b >= 8:
        if s % b == 0:
            return b
        b -= 8
    return None


def _auto_blocks(sq: int, sk: int) -> tuple:
    """Largest MXU-friendly tile sizes that divide the sequence. Measured in
    the full train step on v5e (BENCH_NOTES round 2): 512-row q tiles are
    ~2.7x faster than the FlashAttention-conventional 128 (66.9k vs 24.6k
    tok/s at S=1024 — small tiles leave the MXU idle between grid steps);
    k tiles of 512, widening to 1024 at long S, were best of the sweep."""
    return _pick_divisor(sq, 512), _pick_divisor(sk, 1024 if sk >= 4096 else 512)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    segment_ids=None,
) -> jax.Array:
    """q [B,S,H,D], k/v [B,S,Kh,D] → [B,S,H,D]. Differentiable (custom VJP).
    ``block_q``/``block_k`` default to the measured-fastest tiling for the
    sequence length (``_auto_blocks``); ``bwd_block_q``/``bwd_block_k``
    default to the forward's and can be tuned independently (the backward
    kernels carry 6+ operand tiles, so their VMEM sweet spot differs —
    tools/tune_flash.py; MAGGY_TPU_FLASH_BWD_Q/_K carry a measured winner
    into processes that never pass tiles explicitly, resolved here OUTSIDE
    the jit cache so an env change cannot hit a stale compilation).
    ``segment_ids`` [B, S] masks attention across packed-sequence
    boundaries in-kernel."""
    if bwd_block_q is None:
        bwd_block_q = _snap_tile(_env_tile("MAGGY_TPU_FLASH_BWD_Q"), q.shape[1])
    if bwd_block_k is None:
        bwd_block_k = _snap_tile(_env_tile("MAGGY_TPU_FLASH_BWD_K"), k.shape[1])
    return _flash_attention_jit(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        bwd_block_q=bwd_block_q, bwd_block_k=bwd_block_k,
        interpret=interpret, segment_ids=segment_ids,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "block_q", "block_k", "bwd_block_q", "bwd_block_k", "interpret",
    ),
)
def _flash_attention_jit(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    segment_ids=None,
) -> jax.Array:
    b, sq, h, d = q.shape
    kh = k.shape[2]
    sk = k.shape[1]
    auto_q, auto_k = _auto_blocks(sq, sk)
    block_q = min(block_q, sq) if block_q else auto_q
    block_k = min(block_k, sk) if block_k else auto_k
    bwd_block_q = min(bwd_block_q, sq) if bwd_block_q else block_q
    bwd_block_k = min(bwd_block_k, sk) if bwd_block_k else block_k
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # fall back unless blocks tile evenly AND stay sublane-aligned (multiple
    # of 8 rows) — Mosaic cannot lower arbitrary-row tiles. Segment-id tiles
    # [1, block] put the block in the lane dim, so compiled (non-interpret)
    # segmented runs additionally need lane-aligned blocks.
    blocks = (block_q, block_k, bwd_block_q, bwd_block_k)
    unaligned = (
        sq % block_q or sk % block_k or sq % bwd_block_q or sk % bwd_block_k
        or d % _LANES or any(bq % 8 for bq in blocks)
    )
    seg_unaligned = segment_ids is not None and not interpret and any(
        bq % _LANES for bq in blocks
    )
    if unaligned or seg_unaligned:
        return blockwise_attention(
            q, k, v, causal=causal, segment_ids=segment_ids
        )  # repeats GQA itself

    qr = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * kh, sk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * kh, sk, d)

    segmented = segment_ids is not None
    segs = (
        segment_ids.astype(jnp.int32)
        if segmented
        else jnp.zeros((b, sq), jnp.int32)  # placeholder, never read
    )
    out = _flash_core(
        causal, block_q, block_k, bwd_block_q, bwd_block_k, h // kh, h,
        interpret, segmented,
    )(qr, kr, vr, segs)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def sharded_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh,
    causal: bool = True,
    interpret: Optional[bool] = None,
    segment_ids: Optional[jax.Array] = None,
):
    """Run the Pallas kernel per-shard under ``shard_map`` over ``mesh``.

    A ``pallas_call`` has no SPMD partitioning rule, so inside a GSPMD-sharded
    jit it must run in a manual (shard_map) region: batch shards over
    (data, fsdp), heads over tensor, seq/head_dim stay local. Returns ``None``
    when the mesh layout is incompatible (seq/stage axes in use, or shapes not
    divisible) — the caller falls back to the XLA dense path. sp>1 meshes
    should use ring attention instead.
    """
    from jax.sharding import PartitionSpec as P

    from maggy_tpu.parallel.spec import (
        AXIS_DATA,
        AXIS_FSDP,
        AXIS_SEQ,
        AXIS_STAGE,
        AXIS_TENSOR,
    )

    shape = dict(mesh.shape)
    dpf = shape.get(AXIS_DATA, 1) * shape.get(AXIS_FSDP, 1)
    tp = shape.get(AXIS_TENSOR, 1)
    b, sq, h, d = q.shape
    kh = k.shape[2]
    if (
        shape.get(AXIS_SEQ, 1) != 1
        or shape.get(AXIS_STAGE, 1) != 1
        or b % dpf
        or h % tp
        or kh % tp
    ):
        return None
    spec = P((AXIS_DATA, AXIS_FSDP), None, AXIS_TENSOR, None)
    fn = functools.partial(flash_attention, causal=causal, interpret=interpret)
    if segment_ids is None:
        return shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)
    seg_spec = P((AXIS_DATA, AXIS_FSDP), None)
    return shard_map(
        lambda q, k, v, s: fn(q, k, v, segment_ids=s),
        mesh=mesh, in_specs=(spec, spec, spec, seg_spec), out_specs=spec,
        check_vma=False,
    )(q, k, v, segment_ids)
