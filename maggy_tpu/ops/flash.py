"""Pallas TPU flash-attention forward kernel.

Same online-softmax math as :mod:`maggy_tpu.ops.attention`, hand-tiled for the
MXU: grid (batch*heads, q_blocks, k_blocks) with fp32 running statistics in
VMEM scratch, causal blocks skipped wholesale, and the [S, S] score matrix
never leaving VMEM tiles. Inference/scoring path — for training use
``blockwise_attention`` (differentiable) or ring attention (distributed).

Falls back to the interpreter off-TPU so tests run on CPU meshes; shapes that
do not tile evenly fall back to ``blockwise_attention``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from maggy_tpu.ops.attention import NEG_INF, _repeat_kv, blockwise_attention

_LANES = 128


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # causal: skip blocks strictly above the diagonal (always "needed" otherwise)
    needed = (k_start <= q_start + block_q - 1) if causal else (ki >= 0)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, :1]
        m_cur = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_cur)
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_cur)
        l_new = l_ref[:, :1] * corr + p.sum(axis=1, keepdims=True)
        # stats stored replicated across lanes (full-width VMEM stores)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * corr + pv

    @pl.when(ki == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    segment_ids=None,
) -> jax.Array:
    """q [B,S,H,D], k/v [B,S,Kh,D] → [B,S,H,D]."""
    if segment_ids is not None:
        return blockwise_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    b, sq, h, d = q.shape
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k or d % _LANES:
        return blockwise_attention(q, k, v, causal=causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qr = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    grid = (b * h, sq // block_q, sk // block_k)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            scale=1.0 / d**0.5,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, block_q, d), lambda i, qi, ki: (i, qi, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda i, qi, ki: (i, ki, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda i, qi, ki: (i, ki, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda i, qi, ki: (i, qi, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
