"""Blockwise attention with online softmax.

The memory-efficient attention substrate (absent from the reference — SURVEY.md
§5.7 'green-field, required by the north star'): instead of materializing the
[S, S] score matrix, KV is processed in blocks with running (max, denominator,
accumulator) statistics — the FlashAttention/blockwise-attention recurrence.
The same block-update rule drives three consumers:

* :func:`blockwise_attention` — single-device, ``lax.scan`` over KV blocks
  (XLA fuses it; ``jax.checkpoint`` on the body keeps the backward at block
  granularity too);
* :func:`maggy_tpu.parallel.ringattention.ring_attention` — the scan runs over
  *devices*, rotating KV shards along the ``seq`` ICI ring with ``ppermute``;
* :mod:`maggy_tpu.ops.flash` — the Pallas TPU kernel, same math in VMEM tiles.

All statistics are fp32 regardless of input dtype.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _repeat_kv(k: jax.Array, n_heads: int) -> jax.Array:
    """GQA: broadcast KV heads up to the query head count."""
    kh = k.shape[2]
    if kh == n_heads:
        return k
    return jnp.repeat(k, n_heads // kh, axis=2)


def online_block_update(
    carry: Tuple[jax.Array, jax.Array, jax.Array],
    q: jax.Array,
    k_blk: jax.Array,
    v_blk: jax.Array,
    mask: Optional[jax.Array],
    scale: float,
):
    """One online-softmax step over a KV block.

    carry = (acc [B,H,Q,D] fp32, m [B,H,Q] fp32 running max,
             l [B,H,Q] fp32 running denominator); q [B,Q,H,D];
    k_blk/v_blk [B,Kb,H,D]; mask broadcastable to [B,H,Q,Kb] (True = attend).
    """
    acc, m, l = carry
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
    ) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # fully-masked-so-far rows keep m = NEG_INF; exp(NEG_INF - NEG_INF) would be
    # exp(0)=1, so clamp the shift to stay a true no-op for those rows
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk, preferred_element_type=jnp.float32
    )
    acc_new = acc * corr[..., None] + pv
    return acc_new, m_new, l_new


def _finalize(acc: jax.Array, l: jax.Array, dtype) -> jax.Array:
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B,H,Q,D]
    return out.transpose(0, 2, 1, 3).astype(dtype)  # [B,Q,H,D]


# public surface for cross-module consumers (flash kernel, ring, KV-cache
# decode) — same objects, stable contracts
repeat_kv = _repeat_kv
finalize = _finalize


def init_carry(b: int, h: int, q: int, d: int):
    return (
        jnp.zeros((b, h, q, d), jnp.float32),
        jnp.full((b, h, q), NEG_INF, jnp.float32),
        jnp.zeros((b, h, q), jnp.float32),
    )


@functools.partial(
    jax.jit, static_argnames=("causal", "block_k", "remat_blocks")
)
def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    block_k: int = 512,
    remat_blocks: bool = True,
) -> jax.Array:
    """Memory-efficient attention, drop-in for
    :func:`maggy_tpu.models.transformer.default_attention`.

    q [B,S,H,D]; k/v [B,S,Kh,D] (GQA broadcast internally); never materializes
    more than [B,H,S,block_k] scores.
    """
    b, sq, h, d = q.shape
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    sk = k.shape[1]
    block_k = min(block_k, sk)
    n_blocks = (sk + block_k - 1) // block_k
    pad = n_blocks * block_k - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        if segment_ids is not None:
            segment_ids = jnp.pad(segment_ids, ((0, 0), (0, pad)), constant_values=-1)

    scale = 1.0 / (d**0.5)
    q_pos = jnp.arange(sq)
    kv_pos = jnp.arange(n_blocks * block_k)

    k_blocks = k.reshape(b, n_blocks, block_k, h, d).transpose(1, 0, 2, 3, 4)
    v_blocks = v.reshape(b, n_blocks, block_k, h, d).transpose(1, 0, 2, 3, 4)
    kpos_blocks = kv_pos.reshape(n_blocks, block_k)
    if segment_ids is not None:
        seg_blocks = segment_ids.reshape(b, n_blocks, block_k).transpose(1, 0, 2)
    else:
        seg_blocks = jnp.zeros((n_blocks, 1, 1), jnp.int32)  # unused placeholder

    def body(carry, blk):
        k_blk, v_blk, kpos, seg = blk
        mask = jnp.ones((1, 1, sq, block_k), bool)
        if causal:
            mask = mask & (q_pos[None, None, :, None] >= kpos[None, None, None, :])
        mask = mask & (kpos < sk)[None, None, None, :]  # padding
        if segment_ids is not None:
            qseg = segment_ids[:, :sq]
            mask = mask & (qseg[:, None, :, None] == seg[:, None, None, :])
        return online_block_update(carry, q, k_blk, v_blk, mask, scale), None

    if remat_blocks:
        body = jax.checkpoint(body, prevent_cse=False)

    carry = init_carry(b, h, sq, d)
    xs = (k_blocks, v_blocks, kpos_blocks, seg_blocks)
    (acc, _, l), _ = jax.lax.scan(body, carry, xs)
    return _finalize(acc, l, q.dtype)
