"""Pallas ring attention: KV rotation via explicit inter-chip RDMA (fwd+bwd).

The shard_map ring in :mod:`maggy_tpu.parallel.ringattention` leaves the
KV rotation to XLA's ``ppermute`` scheduling. This kernel issues the rotation
itself with ``pltpu.make_async_remote_copy`` and overlaps it with the block
compute explicitly: at ring step ``s`` each device STARTS the RDMA of its
current KV chunk to its right neighbor, computes online-softmax attention on
that same chunk while the copy is in flight, then acknowledges consumption so
the left neighbor may overwrite the just-freed slot (2-slot double buffer with
per-cell flow control — no global lockstep).

The BACKWARD is a ring kernel too (``jax.custom_vjp`` wired in
:func:`ring_flash_attention`): q/o/do and the saved per-row LSE stay local;
(k, v, dk, dv) rotate together. At each step a device recomputes the
probabilities of its q shard against the visiting KV chunk from the LSE
(FlashAttention-2 recurrence — no [S, S] matrix anywhere), accumulates dQ
locally and folds its dK/dV contribution into the accumulators traveling WITH
the chunk. k/v sends still overlap the compute (read-only); dk/dv sends start
right after it and overlap the next step's receive+compute. The final
rotation delivers each chunk's finished dK/dV straight into its home device's
output buffer.

Memory plan (VMEM is ~16MB/core): q/o and the f32 accumulators live in HBM
(``pltpu.ANY``); the kernel stages one q row-tile and one KV chunk at a time
into VMEM scratch. Communication buffers are per-(batch, kv-head) HBM slots so
grid cells may skew across devices without clobbering each other. Causal runs
skip fully-masked chunks (the compute, not the rotation).

No equivalent exists in the reference (SURVEY.md §5.7 — sequence parallelism
is absent there); the layout matches ``parallel/ringattention.py`` so the two
implementations are interchangeable and cross-checked in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from maggy_tpu.util import shard_map

NEG_INF = -1e30

# pallas-TPU API names across jax versions (new: MemorySpace/CompilerParams,
# old <= 0.4.x: TPUMemorySpace/TPUCompilerParams — same members, minus kwargs
# the old dataclass doesn't know, which _compiler_params drops)
_MEMSPACE = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace


def _compiler_params(**kwargs):
    import dataclasses as _dc

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    known = {f.name for f in _dc.fields(cls)}
    return cls(**{k: v for k, v in kwargs.items() if k in known})


def _interpret_mode(flag: bool):
    """pallas_call interpret argument: the TPU interpret machine
    (InterpretParams, emulates remote DMAs) where available, else the plain
    boolean interpreter of older jax."""
    if not flag:
        return False
    params = getattr(pltpu, "InterpretParams", None)
    return params() if params is not None else True


def _neighbor(mesh, axis_name: str, offset: int):
    """Mesh coordinates of the ring neighbor at ``offset`` along ``axis_name``
    (same pattern as pallas's reference all-gather kernel)."""
    idx = lax.axis_index(axis_name)
    # static axis extent from the mesh (lax.axis_size only exists on new jax)
    size = dict(mesh.shape)[axis_name]
    nxt = lax.rem(idx + offset + size, size)
    return tuple(
        nxt if name == axis_name else lax.axis_index(name)
        for name in mesh.axis_names
    )


def _ring_kernel(
    q_ref,       # ANY [B, C, KH, G, D]
    k_ref,       # ANY [B, C, KH, D]
    v_ref,       # ANY [B, C, KH, D]
    o_ref,       # ANY [B, C, KH, G, D]
    kbuf,        # ANY [B, KH, 2, C, D]   ring comm buffer (k)
    vbuf,        # ANY [B, KH, 2, C, D]   ring comm buffer (v)
    acc_ref,     # ANY [B, C, KH, G, D] f32
    m_ref,       # ANY [B, C, KH, G] f32
    l_ref,       # ANY [B, C, KH, G] f32
    q_st,        # VMEM [QT, G, D]
    k_st,        # VMEM [C, D]
    v_st,        # VMEM [C, D]
    acc_st,      # VMEM [QT, G, D] f32
    ml_st,       # VMEM [2, QT, G] f32   (m, l)
    send_k,      # DMA sems [B, KH]
    send_v,
    recv_k,      # DMA sems [B, KH, 2]
    recv_v,
    ack,         # REGULAR sems [B, KH]
    copy_sem,    # DMA sems [8] for local HBM<->VMEM staging
    *,
    mesh,
    axis_name: str,
    num_shards: int,
    causal: bool,
    q_tile: int,
):
    b = pl.program_id(0)
    kh = pl.program_id(1)
    C = k_st.shape[0]
    G = q_st.shape[1]
    n_qt = C // q_tile
    my = lax.axis_index(axis_name)
    left = _neighbor(mesh, axis_name, -1)
    right = _neighbor(mesh, axis_name, +1)
    scale = 1.0 / (q_st.shape[2] ** 0.5)

    # one barrier per kernel launch: neighbors must have entered the kernel
    # (buffers out of their previous op's live ranges) before any RDMA lands
    @pl.when((b == 0) & (kh == 0))
    def _startup_barrier():
        bar = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bar, 1, device_id=left)
        pltpu.semaphore_signal(bar, 1, device_id=right)
        pltpu.semaphore_wait(bar, 2)

    def _stage_kv(step):
        """current chunk -> VMEM (step 0 reads the local input directly)."""
        cur = lax.rem(step, 2)

        @pl.when(step == 0)
        def _():
            cp_k = pltpu.make_async_copy(
                k_ref.at[b, :, kh, :], k_st, copy_sem.at[0]
            )
            cp_v = pltpu.make_async_copy(
                v_ref.at[b, :, kh, :], v_st, copy_sem.at[1]
            )
            cp_k.start(); cp_v.start(); cp_k.wait(); cp_v.wait()

        @pl.when(step > 0)
        def _():
            cp_k = pltpu.make_async_copy(kbuf.at[b, kh, cur], k_st, copy_sem.at[0])
            cp_v = pltpu.make_async_copy(vbuf.at[b, kh, cur], v_st, copy_sem.at[1])
            cp_k.start(); cp_v.start(); cp_k.wait(); cp_v.wait()

    def _compute_chunk(step):
        """Online-softmax update of every q row-tile against the staged KV
        chunk; runs while this step's RDMA is in flight."""
        src = lax.rem(my - step + num_shards, num_shards)  # owner of the chunk
        k_pos = src * C + lax.broadcasted_iota(jnp.int32, (1, 1, C), 2)

        def tile_body(qt, _):
            row0 = qt * q_tile
            cp_q = pltpu.make_async_copy(
                q_ref.at[b, pl.ds(row0, q_tile), kh], q_st, copy_sem.at[2]
            )
            cp_q.start()

            @pl.when(step == 0)
            def _():
                acc_st[...] = jnp.zeros_like(acc_st)
                ml_st[0] = jnp.full_like(ml_st[0], NEG_INF)
                ml_st[1] = jnp.zeros_like(ml_st[1])

            @pl.when(step > 0)
            def _():
                cp_a = pltpu.make_async_copy(
                    acc_ref.at[b, pl.ds(row0, q_tile), kh], acc_st, copy_sem.at[3]
                )
                cp_m = pltpu.make_async_copy(
                    m_ref.at[b, pl.ds(row0, q_tile), kh], ml_st.at[0], copy_sem.at[4]
                )
                cp_l = pltpu.make_async_copy(
                    l_ref.at[b, pl.ds(row0, q_tile), kh], ml_st.at[1], copy_sem.at[5]
                )
                cp_a.start(); cp_m.start(); cp_l.start()
                cp_a.wait(); cp_m.wait(); cp_l.wait()

            cp_q.wait()

            q = q_st[...].astype(jnp.float32)          # [QT, G, D]
            k = k_st[...].astype(jnp.float32)          # [C, D]
            logits = jax.lax.dot_general(
                q.reshape(q_tile * G, -1), k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).reshape(q_tile, G, C) * scale            # [QT, G, C]
            if causal:
                q_pos = (
                    my * C + row0
                    + lax.broadcasted_iota(jnp.int32, (q_tile, 1, 1), 0)
                )
                logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)

            m_prev = ml_st[0]                          # [QT, G]
            l_prev = ml_st[1]
            m_new = jnp.maximum(m_prev, logits.max(axis=-1))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(logits - m_new[..., None])     # [QT, G, C]
            l_new = l_prev * alpha + p.sum(axis=-1)
            pv = jax.lax.dot_general(
                p.reshape(q_tile * G, C), v_st[...].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).reshape(q_tile, G, -1)
            acc_st[...] = acc_st[...] * alpha[..., None] + pv
            ml_st[0] = m_new
            ml_st[1] = l_new

            # persist accumulators for the next ring step
            cp_a = pltpu.make_async_copy(
                acc_st, acc_ref.at[b, pl.ds(row0, q_tile), kh], copy_sem.at[3]
            )
            cp_m = pltpu.make_async_copy(
                ml_st.at[0], m_ref.at[b, pl.ds(row0, q_tile), kh], copy_sem.at[4]
            )
            cp_l = pltpu.make_async_copy(
                ml_st.at[1], l_ref.at[b, pl.ds(row0, q_tile), kh], copy_sem.at[5]
            )
            cp_a.start(); cp_m.start(); cp_l.start()
            cp_a.wait(); cp_m.wait(); cp_l.wait()
            return 0

        lax.fori_loop(0, n_qt, tile_body, 0)

    def _rdma_desc(s, buf, s_sem, r_sem):
        """The descriptor of the RDMA started at step ``s`` — every device
        runs the same program, so waiting on OUR descriptor's recv side waits
        for the LEFT neighbor's symmetric send to land (the same SPMD idiom as
        pallas's reference all-gather kernel)."""
        src = lax.rem(s, 2)
        dst = lax.rem(s + 1, 2)
        return pltpu.make_async_remote_copy(
            buf.at[b, kh, src], buf.at[b, kh, dst],
            s_sem.at[b, kh], r_sem.at[b, kh, dst],
            device_id=right,
        )

    def step_body(s, _):
        cur = lax.rem(s, 2)
        nxt = lax.rem(s + 1, 2)

        # chunk s arrived? (step 0 computes on the local input)
        @pl.when(s > 0)
        def _():
            _rdma_desc(s - 1, kbuf, send_k, recv_k).wait_recv()
            _rdma_desc(s - 1, vbuf, send_v, recv_v).wait_recv()

        _stage_kv(s)

        # rotate: start sending the chunk we hold, then compute on it
        @pl.when(s < num_shards - 1)
        def _():
            # flow control: right must have consumed its `nxt` slot (its
            # compute of step s-1); its ack arrives on OUR ack sem
            @pl.when(s > 0)
            def _():
                pltpu.semaphore_wait(ack.at[b, kh], 1)

            def _send(src_first, src_later, buf, s_sem, r_sem):
                @pl.when(s == 0)
                def _():
                    pltpu.make_async_remote_copy(
                        src_first, buf.at[b, kh, nxt],
                        s_sem.at[b, kh], r_sem.at[b, kh, nxt],
                        device_id=right,
                    ).start()

                @pl.when(s > 0)
                def _():
                    pltpu.make_async_remote_copy(
                        src_later, buf.at[b, kh, nxt],
                        s_sem.at[b, kh], r_sem.at[b, kh, nxt],
                        device_id=right,
                    ).start()

            _send(k_ref.at[b, :, kh, :], kbuf.at[b, kh, cur], kbuf, send_k, recv_k)
            _send(v_ref.at[b, :, kh, :], vbuf.at[b, kh, cur], vbuf, send_v, recv_v)

        # the overlapped work: attention on the chunk while RDMA flies
        src = lax.rem(my - s + num_shards, num_shards)
        skip = causal & (src > my)  # chunk entirely in the causal future

        @pl.when(jnp.logical_not(skip))
        def _():
            _compute_chunk(s)

        @pl.when(s < num_shards - 1)
        def _():
            # outgoing copy must have left our buffer before the left
            # neighbor is allowed to overwrite it (our ack)
            _rdma_desc(s, kbuf, send_k, recv_k).wait_send()
            _rdma_desc(s, vbuf, send_v, recv_v).wait_send()

        # acks consumed at steps 1..N-2 by the left's sender — produce exactly
        # that many (a leftover count would fail the kernel's sem-drain check)
        @pl.when(s < num_shards - 2)
        def _():
            pltpu.semaphore_signal(ack.at[b, kh], 1, device_id=left)

        return 0

    lax.fori_loop(0, num_shards, step_body, 0)

    # finalize: o = acc / l
    def out_tile(qt, _):
        row0 = qt * q_tile
        cp_a = pltpu.make_async_copy(
            acc_ref.at[b, pl.ds(row0, q_tile), kh], acc_st, copy_sem.at[3]
        )
        cp_l = pltpu.make_async_copy(
            l_ref.at[b, pl.ds(row0, q_tile), kh], ml_st.at[1], copy_sem.at[5]
        )
        cp_a.start(); cp_l.start(); cp_a.wait(); cp_l.wait()
        l = jnp.maximum(ml_st[1], 1e-30)[..., None]
        q_st[...] = (acc_st[...] / l).astype(q_st.dtype)  # reuse q staging
        cp_o = pltpu.make_async_copy(
            q_st, o_ref.at[b, pl.ds(row0, q_tile), kh], copy_sem.at[6]
        )
        cp_o.start(); cp_o.wait()
        return 0

    lax.fori_loop(0, n_qt, out_tile, 0)


def _ring_flash_local(q, k, v, *, mesh, axis_name, num_shards, causal,
                      q_tile, interpret, return_stats=False):
    """Per-device body (under shard_map): q [B, C, H, D], k/v [B, C, KH, D].
    ``return_stats`` also returns the running-softmax (m, l) — the backward
    derives its per-row LSE residual from them."""
    B, C, H, D = q.shape
    KH = k.shape[2]
    G = H // KH
    qg = q.reshape(B, C, KH, G, D)

    kernel = functools.partial(
        _ring_kernel,
        mesh=mesh,
        axis_name=axis_name,
        num_shards=num_shards,
        causal=causal,
        q_tile=q_tile,
    )
    f32 = jnp.float32
    out_shapes = (
        jax.ShapeDtypeStruct((B, C, KH, G, D), q.dtype),   # o
        jax.ShapeDtypeStruct((B, KH, 2, C, D), k.dtype),   # kbuf
        jax.ShapeDtypeStruct((B, KH, 2, C, D), v.dtype),   # vbuf
        jax.ShapeDtypeStruct((B, C, KH, G, D), f32),       # acc
        jax.ShapeDtypeStruct((B, C, KH, G), f32),          # m
        jax.ShapeDtypeStruct((B, C, KH, G), f32),          # l
    )
    any_spec = pl.BlockSpec(memory_space=_MEMSPACE.ANY)
    o = pl.pallas_call(
        kernel,
        grid=(B, KH),
        in_specs=[any_spec] * 3,
        out_specs=[any_spec] * 6,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((q_tile, G, D), q.dtype),       # q_st
            pltpu.VMEM((C, D), k.dtype),               # k_st
            pltpu.VMEM((C, D), v.dtype),               # v_st
            pltpu.VMEM((q_tile, G, D), f32),           # acc_st
            pltpu.VMEM((2, q_tile, G), f32),           # ml_st
            pltpu.SemaphoreType.DMA((B, KH)),          # send_k
            pltpu.SemaphoreType.DMA((B, KH)),          # send_v
            pltpu.SemaphoreType.DMA((B, KH, 2)),       # recv_k
            pltpu.SemaphoreType.DMA((B, KH, 2)),       # recv_v
            pltpu.SemaphoreType.REGULAR((B, KH)),      # ack
            pltpu.SemaphoreType.DMA((8,)),             # local staging sems
        ],
        compiler_params=_compiler_params(
            collective_id=7, has_side_effects=True
        ),
        interpret=_interpret_mode(interpret),
    )(qg, k, v)
    if return_stats:
        return o[0].reshape(B, C, H, D), o[4], o[5]
    return o[0].reshape(B, C, H, D)


# -------------------------------------------------------------------- backward


def _ring_bwd_kernel(
    q_ref,       # ANY [B, C, KH, G, D]
    k_ref,       # ANY [B, C, KH, D]
    v_ref,       # ANY [B, C, KH, D]
    o_ref,       # ANY [B, C, KH, G, D]
    do_ref,      # ANY [B, C, KH, G, D]
    lse_ref,     # ANY [B, C, KH, G] f32
    dq_ref,      # ANY [B, C, KH, G, D] f32 (local accumulator + output)
    dkfin,       # ANY [B, C, KH, D] f32 (final dK, delivered by left's RDMA)
    dvfin,       # ANY [B, C, KH, D] f32
    kbuf,        # ANY [B, KH, 2, C, D]       ring comm buffers
    vbuf,        # ANY [B, KH, 2, C, D]
    dkbuf,       # ANY [B, KH, 2, C, D] f32   rotating dK/dV accumulators
    dvbuf,       # ANY [B, KH, 2, C, D] f32
    q_st,        # VMEM [QT, G, D]
    o_st,        # VMEM [QT, G, D]
    do_st,       # VMEM [QT, G, D]
    dq_st,       # VMEM [QT, G, D] f32
    lse_st,      # VMEM [QT, G] f32
    k_st,        # VMEM [C, D]
    v_st,        # VMEM [C, D]
    dk_st,       # VMEM [C, D] f32
    dv_st,       # VMEM [C, D] f32
    send_k,      # DMA sems [B, KH]
    send_v,
    send_dk,
    send_dv,
    recv_k,      # DMA sems [B, KH, 2]
    recv_v,
    recv_dk,
    recv_dv,
    recv_dkf,    # DMA sems [B, KH] (final home delivery)
    recv_dvf,
    ack_kv,      # REGULAR sems [B, KH]
    ack_dkv,
    copy_sem,    # DMA sems [10] local HBM<->VMEM staging
    *,
    mesh,
    axis_name: str,
    num_shards: int,
    causal: bool,
    q_tile: int,
):
    b = pl.program_id(0)
    kh = pl.program_id(1)
    C = k_st.shape[0]
    G = q_st.shape[1]
    n_qt = C // q_tile
    my = lax.axis_index(axis_name)
    left = _neighbor(mesh, axis_name, -1)
    right = _neighbor(mesh, axis_name, +1)
    scale = 1.0 / (q_st.shape[2] ** 0.5)

    @pl.when((b == 0) & (kh == 0))
    def _startup_barrier():
        bar = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bar, 1, device_id=left)
        pltpu.semaphore_signal(bar, 1, device_id=right)
        pltpu.semaphore_wait(bar, 2)

    def _stage(step):
        """Visiting chunk (k, v) + its traveling (dk, dv) accumulators ->
        VMEM. Step 0 reads the local input; dk/dv start at zero there."""
        cur = lax.rem(step, 2)

        @pl.when(step == 0)
        def _():
            cp_k = pltpu.make_async_copy(k_ref.at[b, :, kh, :], k_st, copy_sem.at[0])
            cp_v = pltpu.make_async_copy(v_ref.at[b, :, kh, :], v_st, copy_sem.at[1])
            cp_k.start(); cp_v.start(); cp_k.wait(); cp_v.wait()
            dk_st[...] = jnp.zeros_like(dk_st)
            dv_st[...] = jnp.zeros_like(dv_st)

        @pl.when(step > 0)
        def _():
            cps = [
                pltpu.make_async_copy(kbuf.at[b, kh, cur], k_st, copy_sem.at[0]),
                pltpu.make_async_copy(vbuf.at[b, kh, cur], v_st, copy_sem.at[1]),
                pltpu.make_async_copy(dkbuf.at[b, kh, cur], dk_st, copy_sem.at[2]),
                pltpu.make_async_copy(dvbuf.at[b, kh, cur], dv_st, copy_sem.at[3]),
            ]
            for cp in cps:
                cp.start()
            for cp in cps:
                cp.wait()

    def _compute_chunk(step):
        """dQ / dK / dV contributions of every local q row-tile against the
        staged chunk, probabilities recomputed from the saved LSE."""
        src = lax.rem(my - step + num_shards, num_shards)
        k_pos = src * C + lax.broadcasted_iota(jnp.int32, (1, 1, C), 2)
        k = k_st[...].astype(jnp.float32)          # [C, D]
        v = v_st[...].astype(jnp.float32)

        def tile_body(qt, _):
            row0 = qt * q_tile
            cps = [
                pltpu.make_async_copy(
                    q_ref.at[b, pl.ds(row0, q_tile), kh], q_st, copy_sem.at[4]
                ),
                pltpu.make_async_copy(
                    o_ref.at[b, pl.ds(row0, q_tile), kh], o_st, copy_sem.at[5]
                ),
                pltpu.make_async_copy(
                    do_ref.at[b, pl.ds(row0, q_tile), kh], do_st, copy_sem.at[6]
                ),
                pltpu.make_async_copy(
                    lse_ref.at[b, pl.ds(row0, q_tile), kh], lse_st, copy_sem.at[7]
                ),
            ]
            for cp in cps:
                cp.start()

            @pl.when(step == 0)
            def _():
                dq_st[...] = jnp.zeros_like(dq_st)

            @pl.when(step > 0)
            def _():
                cp_dq = pltpu.make_async_copy(
                    dq_ref.at[b, pl.ds(row0, q_tile), kh], dq_st, copy_sem.at[8]
                )
                cp_dq.start(); cp_dq.wait()

            for cp in cps:
                cp.wait()

            q = q_st[...].astype(jnp.float32)      # [QT, G, D]
            do = do_st[...].astype(jnp.float32)
            o = o_st[...].astype(jnp.float32)
            logits = jax.lax.dot_general(
                q.reshape(q_tile * G, -1), k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).reshape(q_tile, G, C) * scale        # [QT, G, C]
            # probabilities from the saved LSE (lse=+inf rows -> p=0)
            p = jnp.exp(logits - lse_st[...][..., None])
            if causal:
                q_pos = (
                    my * C + row0
                    + lax.broadcasted_iota(jnp.int32, (q_tile, 1, 1), 0)
                )
                p = jnp.where(q_pos >= k_pos, p, 0.0)
            dp = jax.lax.dot_general(
                do.reshape(q_tile * G, -1), v,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).reshape(q_tile, G, C)
            delta = jnp.sum(do * o, axis=-1)       # [QT, G]
            ds = p * (dp - delta[..., None]) * scale

            dq_st[...] = dq_st[...] + jax.lax.dot_general(
                ds.reshape(q_tile * G, C), k,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).reshape(q_tile, G, -1)
            # dK += dS^T Q ; dV += P^T dO — contract the q-row dim (GQA groups
            # fold into the same contraction, summing the group for free)
            dk_st[...] = dk_st[...] + jax.lax.dot_general(
                ds.reshape(q_tile * G, C), q.reshape(q_tile * G, -1),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dv_st[...] = dv_st[...] + jax.lax.dot_general(
                p.reshape(q_tile * G, C), do.reshape(q_tile * G, -1),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

            cp_dq = pltpu.make_async_copy(
                dq_st, dq_ref.at[b, pl.ds(row0, q_tile), kh], copy_sem.at[8]
            )
            cp_dq.start(); cp_dq.wait()
            return 0

        lax.fori_loop(0, n_qt, tile_body, 0)

    def _rdma_desc(s, buf, s_sem, r_sem):
        src = lax.rem(s, 2)
        dst = lax.rem(s + 1, 2)
        return pltpu.make_async_remote_copy(
            buf.at[b, kh, src], buf.at[b, kh, dst],
            s_sem.at[b, kh], r_sem.at[b, kh, dst],
            device_id=right,
        )

    def _fin_desc(buf, fin, s_sem, r_sem):
        """Last rotation: the finished dK/dV chunk goes straight into its home
        device's output buffer (we hold right's chunk at step N-1)."""
        src = lax.rem(num_shards - 1, 2)
        return pltpu.make_async_remote_copy(
            buf.at[b, kh, src], fin.at[b, :, kh, :],
            s_sem.at[b, kh], r_sem.at[b, kh],
            device_id=right,
        )

    def step_body(s, _):
        cur = lax.rem(s, 2)
        nxt = lax.rem(s + 1, 2)

        @pl.when(s > 0)
        def _():
            _rdma_desc(s - 1, kbuf, send_k, recv_k).wait_recv()
            _rdma_desc(s - 1, vbuf, send_v, recv_v).wait_recv()
            _rdma_desc(s - 1, dkbuf, send_dk, recv_dk).wait_recv()
            _rdma_desc(s - 1, dvbuf, send_dv, recv_dv).wait_recv()

        _stage(s)

        # k/v are read-only: rotate them BEFORE the compute so the RDMA flies
        # under it (same as the forward)
        @pl.when(s < num_shards - 1)
        def _():
            @pl.when(s > 0)
            def _():
                pltpu.semaphore_wait(ack_kv.at[b, kh], 1)

            def _send(src_first, src_later, buf, s_sem, r_sem):
                @pl.when(s == 0)
                def _():
                    pltpu.make_async_remote_copy(
                        src_first, buf.at[b, kh, nxt],
                        s_sem.at[b, kh], r_sem.at[b, kh, nxt],
                        device_id=right,
                    ).start()

                @pl.when(s > 0)
                def _():
                    pltpu.make_async_remote_copy(
                        src_later, buf.at[b, kh, nxt],
                        s_sem.at[b, kh], r_sem.at[b, kh, nxt],
                        device_id=right,
                    ).start()

            _send(k_ref.at[b, :, kh, :], kbuf.at[b, kh, cur], kbuf, send_k, recv_k)
            _send(v_ref.at[b, :, kh, :], vbuf.at[b, kh, cur], vbuf, send_v, recv_v)

        src = lax.rem(my - s + num_shards, num_shards)
        skip = causal & (src > my)  # chunk entirely in the causal future

        @pl.when(jnp.logical_not(skip))
        def _():
            _compute_chunk(s)

        # persist the (possibly pass-through) accumulators into the slot we
        # are about to send from
        cp_dk = pltpu.make_async_copy(dk_st, dkbuf.at[b, kh, cur], copy_sem.at[2])
        cp_dv = pltpu.make_async_copy(dv_st, dvbuf.at[b, kh, cur], copy_sem.at[3])
        cp_dk.start(); cp_dv.start(); cp_dk.wait(); cp_dv.wait()

        # dk/dv rotate AFTER the compute (read-modify-write); the send overlaps
        # the next step's receive + compute
        @pl.when(s < num_shards - 1)
        def _():
            @pl.when(s > 0)
            def _():
                pltpu.semaphore_wait(ack_dkv.at[b, kh], 1)

            _rdma_desc(s, dkbuf, send_dk, recv_dk).start()
            _rdma_desc(s, dvbuf, send_dv, recv_dv).start()

        @pl.when(s == num_shards - 1)
        def _():
            _fin_desc(dkbuf, dkfin, send_dk, recv_dkf).start()
            _fin_desc(dvbuf, dvfin, send_dv, recv_dvf).start()

        @pl.when(s < num_shards - 1)
        def _():
            _rdma_desc(s, kbuf, send_k, recv_k).wait_send()
            _rdma_desc(s, vbuf, send_v, recv_v).wait_send()
            _rdma_desc(s, dkbuf, send_dk, recv_dk).wait_send()
            _rdma_desc(s, dvbuf, send_dv, recv_dv).wait_send()

        @pl.when(s == num_shards - 1)
        def _():
            _fin_desc(dkbuf, dkfin, send_dk, recv_dkf).wait_send()
            _fin_desc(dvbuf, dvfin, send_dv, recv_dvf).wait_send()

        # ack accounting mirrors the forward: consumed by the left's sends at
        # steps 1..N-2, produced after our wait_send at steps 0..N-3
        @pl.when(s < num_shards - 2)
        def _():
            pltpu.semaphore_signal(ack_kv.at[b, kh], 1, device_id=left)
            pltpu.semaphore_signal(ack_dkv.at[b, kh], 1, device_id=left)

        return 0

    lax.fori_loop(0, num_shards, step_body, 0)

    # our own dK/dV land from the left's final rotation
    _fin_desc(dkbuf, dkfin, send_dk, recv_dkf).wait_recv()
    _fin_desc(dvbuf, dvfin, send_dv, recv_dvf).wait_recv()


def _ring_bwd_local(q, k, v, o, do, lse, *, mesh, axis_name, num_shards,
                    causal, q_tile, interpret):
    """Per-device backward body (under shard_map): q/o/do [B, C, H, D],
    k/v [B, C, KH, D], lse [B, C, KH, G] f32 -> (dq, dk, dv)."""
    B, C, H, D = q.shape
    KH = k.shape[2]
    G = H // KH
    qg = q.reshape(B, C, KH, G, D)
    og = o.reshape(B, C, KH, G, D)
    dog = do.reshape(B, C, KH, G, D)

    kernel = functools.partial(
        _ring_bwd_kernel,
        mesh=mesh,
        axis_name=axis_name,
        num_shards=num_shards,
        causal=causal,
        q_tile=q_tile,
    )
    f32 = jnp.float32
    out_shapes = (
        jax.ShapeDtypeStruct((B, C, KH, G, D), f32),       # dq
        jax.ShapeDtypeStruct((B, C, KH, D), f32),          # dkfin
        jax.ShapeDtypeStruct((B, C, KH, D), f32),          # dvfin
        jax.ShapeDtypeStruct((B, KH, 2, C, D), k.dtype),   # kbuf
        jax.ShapeDtypeStruct((B, KH, 2, C, D), v.dtype),   # vbuf
        jax.ShapeDtypeStruct((B, KH, 2, C, D), f32),       # dkbuf
        jax.ShapeDtypeStruct((B, KH, 2, C, D), f32),       # dvbuf
    )
    any_spec = pl.BlockSpec(memory_space=_MEMSPACE.ANY)
    out = pl.pallas_call(
        kernel,
        grid=(B, KH),
        in_specs=[any_spec] * 6,
        out_specs=[any_spec] * 7,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((q_tile, G, D), q.dtype),       # q_st
            pltpu.VMEM((q_tile, G, D), o.dtype),       # o_st
            pltpu.VMEM((q_tile, G, D), do.dtype),      # do_st
            pltpu.VMEM((q_tile, G, D), f32),           # dq_st
            pltpu.VMEM((q_tile, G), f32),              # lse_st
            pltpu.VMEM((C, D), k.dtype),               # k_st
            pltpu.VMEM((C, D), v.dtype),               # v_st
            pltpu.VMEM((C, D), f32),                   # dk_st
            pltpu.VMEM((C, D), f32),                   # dv_st
            pltpu.SemaphoreType.DMA((B, KH)),          # send_k
            pltpu.SemaphoreType.DMA((B, KH)),          # send_v
            pltpu.SemaphoreType.DMA((B, KH)),          # send_dk
            pltpu.SemaphoreType.DMA((B, KH)),          # send_dv
            pltpu.SemaphoreType.DMA((B, KH, 2)),       # recv_k
            pltpu.SemaphoreType.DMA((B, KH, 2)),       # recv_v
            pltpu.SemaphoreType.DMA((B, KH, 2)),       # recv_dk
            pltpu.SemaphoreType.DMA((B, KH, 2)),       # recv_dv
            pltpu.SemaphoreType.DMA((B, KH)),          # recv_dkf
            pltpu.SemaphoreType.DMA((B, KH)),          # recv_dvf
            pltpu.SemaphoreType.REGULAR((B, KH)),      # ack_kv
            pltpu.SemaphoreType.REGULAR((B, KH)),      # ack_dkv
            pltpu.SemaphoreType.DMA((10,)),            # local staging sems
        ],
        compiler_params=_compiler_params(
            collective_id=8, has_side_effects=True
        ),
        interpret=_interpret_mode(interpret),
    )(qg, k, v, og, dog, lse)
    dq = out[0].reshape(B, C, H, D).astype(q.dtype)
    dk = out[1].astype(k.dtype)
    dv = out[2].astype(v.dtype)
    return dq, dk, dv


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh,
    causal: bool = True,
    axis_name: str = "seq",
    q_tile: int = 256,
    interpret: bool = False,
):
    """Ring attention with in-kernel RDMA rotation — differentiable.

    :param q: [B, S, H, D] sharded on S over ``axis_name``; k/v [B, S, KH, D].
    :param q_tile: VMEM row-tile; the per-device chunk must divide by it.
    :param interpret: run under the TPU interpret machine (CPU testing —
        remote DMAs and semaphores are simulated faithfully).

    Gradients run through :func:`_ring_bwd_kernel` — a second ring in which
    (k, v, dk, dv) rotate together and the probabilities are recomputed from
    the forward's saved LSE, so training at ``sp > 1`` stays on the RDMA path
    both directions (round-2 verdict item 2).
    """
    from jax.sharding import PartitionSpec as P

    num_shards = mesh.shape[axis_name]
    if num_shards == 1:
        from maggy_tpu.ops import attention as ops_attn

        return ops_attn.blockwise_attention(q, k, v, causal=causal)
    chunk = q.shape[1] // num_shards
    tile = min(q_tile, chunk)
    if chunk % tile:
        raise ValueError(f"per-device chunk {chunk} not divisible by q_tile {tile}")

    spec = P(None, axis_name, None, None)
    stat_spec = P(None, axis_name, None, None)
    kw = dict(
        mesh=mesh,
        axis_name=axis_name,
        num_shards=num_shards,
        causal=causal,
        q_tile=tile,
        interpret=interpret,
    )

    def _fwd_stats(q, k, v):
        return shard_map(
            functools.partial(_ring_flash_local, return_stats=True, **kw),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=(spec, stat_spec, stat_spec),
            check_vma=False,
        )(q, k, v)

    @jax.custom_vjp
    def attn(q, k, v):
        return _fwd_stats(q, k, v)[0]

    def attn_fwd(q, k, v):
        o, m, l = _fwd_stats(q, k, v)
        # rows with no visible key carry lse=+inf so exp(s - lse) == 0
        lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), jnp.inf)
        return o, (q, k, v, o, lse)

    def attn_bwd(res, g):
        q, k, v, o, lse = res
        return shard_map(
            functools.partial(_ring_bwd_local, **kw),
            mesh=mesh,
            in_specs=(spec, spec, spec, spec, spec, stat_spec),
            out_specs=(spec, spec, spec),
            check_vma=False,
        )(q, k, v, o, g.astype(o.dtype), lse)

    attn.defvjp(attn_fwd, attn_bwd)
    return attn(q, k, v)
