from maggy_tpu.ops.attention import blockwise_attention, online_block_update

__all__ = ["blockwise_attention", "online_block_update"]


def __getattr__(name):
    import importlib

    if name == "flash_attention":
        return importlib.import_module("maggy_tpu.ops.flash").flash_attention
    raise AttributeError(f"module 'maggy_tpu.ops' has no attribute {name!r}")
