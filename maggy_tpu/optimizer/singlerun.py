"""SingleRun: ``optimizer=None`` path — run num_trials empty-parameter trials
(reference optimizer/singlerun.py:21-37)."""

from __future__ import annotations

from typing import Optional, Union

from maggy_tpu.optimizer.abstractoptimizer import AbstractOptimizer
from maggy_tpu.trial import Trial


class SingleRun(AbstractOptimizer):
    def initialize(self) -> None:
        self._remaining = self.num_trials

    def get_suggestion(self, trial: Optional[Trial] = None) -> Union[Trial, str, None]:
        if self._remaining <= 0:
            return None
        self._remaining -= 1
        # Distinct params per trial so trial ids do not collide.
        return self.create_trial(
            {"run": self.num_trials - self._remaining - 1}, sample_type="single"
        )
