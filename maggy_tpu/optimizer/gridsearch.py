"""Grid search over DISCRETE/CATEGORICAL axes (reference optimizer/gridsearch.py:23-92).

Continuous (DOUBLE) axes are gridded with ``grid_points`` evenly spaced values —
a capability the reference rejects outright (gridsearch.py:83-92); INTEGER axes
enumerate their full range when small, else ``grid_points`` evenly spaced ints.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Union

import numpy as np

from maggy_tpu.optimizer.abstractoptimizer import AbstractOptimizer
from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial


class GridSearch(AbstractOptimizer):
    def __init__(self, grid_points: int = 5, **kwargs):
        super().__init__(**kwargs)
        self.grid_points = int(grid_points)

    @classmethod
    def axis_values(cls, searchspace: Searchspace, grid_points: int = 5) -> List[list]:
        axes = []
        for item in searchspace.items():
            t, v = item["type"], item["values"]
            if t in (Searchspace.DISCRETE, Searchspace.CATEGORICAL):
                axes.append(list(v))
            elif t == Searchspace.INTEGER:
                lo, hi = v
                if hi - lo + 1 <= grid_points:
                    axes.append(list(range(lo, hi + 1)))
                else:
                    axes.append(sorted({int(round(x)) for x in np.linspace(lo, hi, grid_points)}))
            else:  # DOUBLE
                axes.append([float(x) for x in np.linspace(v[0], v[1], grid_points)])
        return axes

    @classmethod
    def get_num_trials(cls, searchspace: Searchspace, grid_points: int = 5) -> int:
        """Cartesian-product size; consumed by the driver to override num_trials
        (reference gridsearch.py:33-43 + optimization_driver.py:91-93)."""
        n = 1
        for axis in cls.axis_values(searchspace, grid_points):
            n *= len(axis)
        return n

    def initialize(self) -> None:
        names = self.searchspace.keys()
        axes = self.axis_values(self.searchspace, self.grid_points)
        self._buffer = [dict(zip(names, combo)) for combo in itertools.product(*axes)]

    def get_suggestion(self, trial: Optional[Trial] = None) -> Union[Trial, str, None]:
        if self._buffer:
            return self.create_trial(self._buffer.pop(0), sample_type="grid")
        return None
