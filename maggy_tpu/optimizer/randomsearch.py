"""Uniform random search (reference optimizer/randomsearch.py:23-113).

Pre-samples ``num_trials`` de-duplicated configurations at initialization; with a
pruner attached, configurations are drawn on demand with the pruner's budgets
(promoted trials re-use their original params, reference randomsearch.py:47-90).
"""

from __future__ import annotations

import logging
from typing import Optional, Union

from maggy_tpu.optimizer.abstractoptimizer import IDLE, AbstractOptimizer
from maggy_tpu.trial import Trial


class RandomSearch(AbstractOptimizer):
    def initialize(self) -> None:
        self._buffer = []
        if self.pruner is None:
            seen = set()
            attempts = 0
            # Oversample to dodge duplicate configs in small discrete spaces.
            while len(self._buffer) < self.num_trials and attempts < self.num_trials * 50:
                params = self.searchspace.sample(self._py_rng)
                tid = Trial.compute_id(params)
                if tid not in seen:
                    seen.add(tid)
                    self._buffer.append(params)
                attempts += 1
            if len(self._buffer) < self.num_trials:
                # Space has fewer unique configs than num_trials. Repeats would
                # collide in the id-keyed trial_store, so run what exists.
                logging.getLogger(__name__).warning(
                    "Searchspace holds only %d unique configurations; running %d "
                    "trials instead of the requested %d.",
                    len(self._buffer), len(self._buffer), self.num_trials,
                )

    def get_suggestion(self, trial: Optional[Trial] = None) -> Union[Trial, str, None]:
        if self.pruner is not None:
            return self._pruner_suggestion(trial)
        if self._buffer:
            return self.create_trial(self._buffer.pop(0), sample_type="random")
        return None

    def _pruner_suggestion(self, trial: Optional[Trial]) -> Union[Trial, str, None]:
        decision = self.pruner.pruning_routine()
        if decision == "IDLE":
            return IDLE
        if decision is None:
            return None

        def fresh():
            for _ in range(50):
                params = self.searchspace.sample(self._py_rng)
                if not self.hparams_exist(params):
                    return params, "random"
            return None, "random"

        return self.pruner_trial(decision, fresh)
