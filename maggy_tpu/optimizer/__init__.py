"""Optimizer registry (reference optimization_driver.py:49-57 controller_dict)."""

from maggy_tpu.optimizer.abstractoptimizer import IDLE, AbstractOptimizer
from maggy_tpu.optimizer.asha import Asha
from maggy_tpu.optimizer.gridsearch import GridSearch
from maggy_tpu.optimizer.randomsearch import RandomSearch
from maggy_tpu.optimizer.singlerun import SingleRun

__all__ = [
    "AbstractOptimizer",
    "IDLE",
    "RandomSearch",
    "GridSearch",
    "SingleRun",
    "Asha",
    "get_optimizer",
]


def get_optimizer(name_or_instance, **kwargs) -> AbstractOptimizer:
    """Resolve an optimizer by registry name or pass through an instance."""
    if isinstance(name_or_instance, AbstractOptimizer):
        return name_or_instance
    if name_or_instance is None:
        return SingleRun(**kwargs)
    name = str(name_or_instance).lower()
    if name in ("randomsearch", "random"):
        return RandomSearch(**kwargs)
    if name in ("gridsearch", "grid"):
        return GridSearch(**kwargs)
    if name in ("none", "singlerun"):
        return SingleRun(**kwargs)
    if name == "asha":
        return Asha(**kwargs)
    if name in ("tpe", "gp"):
        try:
            if name == "tpe":
                from maggy_tpu.optimizer.bayes.tpe import TPE as cls
            else:
                from maggy_tpu.optimizer.bayes.gp import GP as cls
        except ImportError as e:
            raise NotImplementedError(
                f"The {name!r} optimizer requires the bayes module: {e}"
            ) from e
        return cls(**kwargs)
    raise ValueError(
        f"Unknown optimizer {name_or_instance!r}; expected one of "
        "randomsearch, gridsearch, asha, tpe, gp, none or an AbstractOptimizer."
    )


def get_earlystop(name_or_instance):
    from maggy_tpu.earlystop import AbstractEarlyStop, MedianStoppingRule, NoStoppingRule

    if isinstance(name_or_instance, type) and issubclass(name_or_instance, AbstractEarlyStop):
        return name_or_instance
    if isinstance(name_or_instance, AbstractEarlyStop):
        return name_or_instance
    name = str(name_or_instance).lower()
    if name == "median":
        return MedianStoppingRule
    if name in ("none", "nostop"):
        return NoStoppingRule
    raise ValueError(f"Unknown early-stop policy {name_or_instance!r}")
