"""Tree-structured Parzen Estimator (BOHB-style).

First-party numpy implementation replacing the reference's statsmodels
KDEMultivariate dependency (reference optimizer/bayes/tpe.py:31-266; §2.9).
Observations are split at the ``gamma`` quantile into good/bad sets with the
BOHB counting rule, per-dimension Gaussian KDEs (Scott bandwidth, widened by
``bw_factor`` when sampling) model each set in the unit cube, and the proposal
maximizes EI = pdf_good / pdf_bad over candidates drawn from the good KDE —
truncated normals keep every candidate inside [0, 1].
"""

from __future__ import annotations

import math
from typing import Tuple

import numpy as np

from maggy_tpu.optimizer.bayes.base import BaseAsyncBO


def _scott_bw(X: np.ndarray) -> np.ndarray:
    n, d = X.shape
    sigma = X.std(axis=0) + 1e-3
    return sigma * n ** (-1.0 / (d + 4))


class _KDE:
    """Product of per-dimension Gaussian kernels over points in the unit cube."""

    def __init__(self, X: np.ndarray, bw: np.ndarray):
        self.X = X
        self.bw = np.maximum(bw, 1e-3)

    def marginal(self, n_dims: int) -> "_KDE":
        """Marginal over the first ``n_dims`` (per-dim product kernels
        marginalize by dropping factors)."""
        return _KDE(self.X[:, :n_dims], self.bw[:n_dims])

    def pdf(self, Q: np.ndarray) -> np.ndarray:
        # [q, n, d] standardized distances
        z = (Q[:, None, :] - self.X[None, :, :]) / self.bw
        kern = np.exp(-0.5 * z * z) / (self.bw * math.sqrt(2 * math.pi))
        return np.maximum(kern.prod(-1).mean(-1), 1e-32)

    def sample(self, rng: np.random.Generator, n: int, bw_factor: float) -> np.ndarray:
        idx = rng.integers(0, len(self.X), n)
        centers = self.X[idx]
        bw = self.bw * bw_factor
        out = np.empty_like(centers)
        for j in range(centers.shape[1]):
            # truncated normal per dimension via resampling, clip as backstop
            col = rng.normal(centers[:, j], bw[j])
            bad = (col < 0) | (col > 1)
            retry = 0
            while bad.any() and retry < 8:
                col[bad] = rng.normal(centers[bad, j], bw[j])
                bad = (col < 0) | (col > 1)
                retry += 1
            out[:, j] = np.clip(col, 0.0, 1.0)
        return out


class TPE(BaseAsyncBO):
    def __init__(
        self,
        gamma: float = 0.15,
        num_samples: int = 64,
        bw_factor: float = 3.0,
        min_points: int = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if not 0 < gamma < 1:
            raise ValueError("gamma must be in (0, 1)")
        self.gamma = gamma
        self.num_samples = int(num_samples)
        self.bw_factor = float(bw_factor)
        self.min_points = min_points

    def _split(self, X: np.ndarray, y: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """BOHB split: n_good = max(d+1, gamma*n), n_bad = max(d+1, rest)
        (reference tpe.py:191-221)."""
        d = X.shape[1]
        n = len(X)
        order = np.argsort(y)  # ascending: best (smallest) first
        n_good = max(d + 1, int(math.ceil(self.gamma * n)))
        n_good = min(n_good, n - 1) if n > 1 else n
        good = X[order[:n_good]]
        bad = X[order[n_good:]]
        if len(bad) < d + 1:
            bad = X[order[max(0, n - (d + 1)) :]]
        return good, bad

    def fit_model(self, X: np.ndarray, y: np.ndarray):
        d = X.shape[1]
        need = self.min_points if self.min_points is not None else 2 * (d + 1)
        if len(X) < need:
            raise ValueError("not enough observations for TPE")
        good, bad = self._split(X, y)
        return (_KDE(good, _scott_bw(good)), _KDE(bad, _scott_bw(bad)))

    def sample_from_model(self, model, fixed_last=None) -> np.ndarray:
        kde_good, kde_bad = model
        cand = kde_good.sample(self.rng, self.num_samples, self.bw_factor)
        if fixed_last is not None:
            # score over the free dims only: a pinned budget coordinate far
            # from the observed budgets would zero both pdfs and flatten EI
            d_free = cand.shape[1] - 1
            free = cand[:, :d_free]
            ei = kde_good.marginal(d_free).pdf(free) / kde_bad.marginal(d_free).pdf(free)
            return free[int(np.argmax(ei))]
        ei = kde_good.pdf(cand) / kde_bad.pdf(cand)
        return cand[int(np.argmax(ei))]
