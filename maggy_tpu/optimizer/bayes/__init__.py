from maggy_tpu.optimizer.bayes.base import BaseAsyncBO
from maggy_tpu.optimizer.bayes.gp import GP
from maggy_tpu.optimizer.bayes.tpe import TPE

__all__ = ["BaseAsyncBO", "GP", "TPE"]
