"""Gaussian-process surrogate with Matern-5/2 kernel and EI/PI/LCB acquisitions.

First-party numpy implementation replacing the reference's skopt dependency
(reference optimizer/bayes/gp.py:34-373 wraps sklearn's GaussianProcessRegressor
with ConstantKernel x Matern(nu=2.5); §2.9 requires re-implementation). Kernel
hyperparameters (amplitude, ARD lengthscales, noise) are fit by maximizing the
log marginal likelihood with multi-restart L-BFGS-B; the acquisition is
optimized by dense random sampling plus a local refinement, all in the unit
cube the Searchspace transform defines.
"""

from __future__ import annotations

import math

import numpy as np

from maggy_tpu.optimizer.bayes.base import BaseAsyncBO

_SQRT5 = math.sqrt(5.0)


def _matern52(X1: np.ndarray, X2: np.ndarray, lengthscales: np.ndarray) -> np.ndarray:
    d = (X1[:, None, :] - X2[None, :, :]) / lengthscales
    r = np.sqrt(np.maximum((d * d).sum(-1), 1e-30))
    sr = _SQRT5 * r
    return (1.0 + sr + sr * sr / 3.0) * np.exp(-sr)


class _FittedGP:
    def __init__(self, X, y, amp2, lengthscales, noise2):
        self.X = X
        self.y_mean = y.mean()
        self.y_std = y.std() + 1e-12
        self.y = (y - self.y_mean) / self.y_std
        self.amp2 = amp2
        self.lengthscales = lengthscales
        self.noise2 = noise2
        K = amp2 * _matern52(X, X, lengthscales) + noise2 * np.eye(len(X))
        self.L = np.linalg.cholesky(K + 1e-10 * np.eye(len(X)))
        self.alpha = np.linalg.solve(
            self.L.T, np.linalg.solve(self.L, self.y)
        )

    def predict(self, Xs: np.ndarray):
        Ks = self.amp2 * _matern52(Xs, self.X, self.lengthscales)
        mu = Ks @ self.alpha
        v = np.linalg.solve(self.L, Ks.T)
        var = np.maximum(self.amp2 - (v * v).sum(0), 1e-12)
        return mu * self.y_std + self.y_mean, np.sqrt(var) * self.y_std

    def log_marginal_likelihood(self):
        return float(
            -0.5 * self.y @ self.alpha
            - np.log(np.diag(self.L)).sum()
            - 0.5 * len(self.y) * math.log(2 * math.pi)
        )


def _norm_pdf(z):
    return np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)


def _norm_cdf(z):
    return 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))


class GP(BaseAsyncBO):
    """Async GP-BO. ``acq_fun`` in {"ei", "pi", "lcb", "asy_ts"}; minimizes
    internally. ``asy_ts`` is asynchronous Thompson sampling (reference
    gp.py:158-162): every proposal draws one function sample from the GP
    posterior over a candidate set and takes its argmin — naturally diverse
    under parallel workers, no liar needed. ``imputation="kb"`` (kriging
    believer, reference gp.py:329-373) imputes busy trials at the posterior
    mean of a GP fitted on the finished observations."""

    def __init__(
        self,
        acq_fun: str = "ei",
        acq_samples: int = 1024,
        kappa: float = 1.96,
        xi: float = 0.01,
        n_restarts: int = 3,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if acq_fun not in ("ei", "pi", "lcb", "asy_ts"):
            raise ValueError("acq_fun must be ei, pi, lcb or asy_ts")
        self.acq_fun = acq_fun
        self.acq_samples = int(acq_samples)
        self.kappa = kappa
        self.xi = xi
        self.n_restarts = int(n_restarts)

    # ------------------------------------------------------------------ fitting

    def fit_model(self, X: np.ndarray, y: np.ndarray) -> _FittedGP:
        d = X.shape[1]

        def nll(theta):
            amp2 = math.exp(theta[0])
            ls = np.exp(theta[1 : 1 + d])
            noise2 = math.exp(theta[-1])
            try:
                gp = _FittedGP(X, y, amp2, ls, noise2)
            except np.linalg.LinAlgError:
                return 1e10
            return -gp.log_marginal_likelihood()

        best_theta, best_val = None, np.inf
        starts = [np.zeros(d + 2)]
        for _ in range(self.n_restarts - 1):
            starts.append(
                np.concatenate(
                    [
                        self.rng.uniform(-1, 1, 1),
                        self.rng.uniform(-2, 1, d),
                        self.rng.uniform(-8, -2, 1),
                    ]
                )
            )
        bounds = [(-4, 4)] + [(-5, 3)] * d + [(-10, 0)]
        try:
            from scipy.optimize import minimize

            for x0 in starts:
                res = minimize(nll, x0, method="L-BFGS-B", bounds=bounds)
                if res.fun < best_val:
                    best_val, best_theta = res.fun, res.x
        except ImportError:  # pragma: no cover - scipy ships with jax images
            for x0 in starts:
                val = nll(x0)
                if val < best_val:
                    best_val, best_theta = val, x0
        theta = best_theta if best_theta is not None else np.zeros(d + 2)
        return _FittedGP(
            X,
            y,
            math.exp(theta[0]),
            np.exp(theta[1 : 1 + d]),
            math.exp(theta[-1]),
        )

    # ------------------------------------------------------------------ acquisition

    def _acquisition(self, model: _FittedGP, Xs: np.ndarray) -> np.ndarray:
        """Lower is better (we pick argmin)."""
        mu, sigma = model.predict(Xs)
        if self.acq_fun == "lcb":
            return mu - self.kappa * sigma
        y_best = model.y.min() * model.y_std + model.y_mean
        z = (y_best - mu - self.xi) / sigma
        if self.acq_fun == "ei":
            ei = (y_best - mu - self.xi) * _norm_cdf(z) + sigma * _norm_pdf(z)
            return -ei
        return -_norm_cdf(z)  # pi

    def _thompson_draw(self, model: _FittedGP, Xs: np.ndarray) -> np.ndarray:
        """One joint sample from the GP posterior at ``Xs`` (standardized y
        space is fine — argmin is scale-invariant)."""
        mu, _ = model.predict(Xs)
        Ks = model.amp2 * _matern52(Xs, model.X, model.lengthscales)
        v = np.linalg.solve(model.L, Ks.T)
        cov = model.amp2 * _matern52(Xs, Xs, model.lengthscales) - v.T @ v
        jitter = 1e-8 * max(model.amp2, 1.0)
        for _ in range(3):  # roundoff can defeat a fixed jitter at large amp2
            try:
                Lp = np.linalg.cholesky(cov + jitter * np.eye(len(Xs)))
                return mu + (Lp @ self.rng.standard_normal(len(Xs))) * model.y_std
            except np.linalg.LinAlgError:
                jitter *= 1e3
        # joint draw unsalvageable: independent marginal draws still rank
        # candidates usefully and never crash the suggestion loop
        mu, sigma = model.predict(Xs)
        return mu + sigma * self.rng.standard_normal(len(Xs))

    def _impute_busy(self, X_done, y_done, X_busy) -> np.ndarray:
        if self.imputation != "kb":
            return super()._impute_busy(X_done, y_done, X_busy)
        try:
            believer = self.fit_model(X_done, y_done)
            mu, _ = believer.predict(X_busy)
            return np.asarray(mu)
        except Exception:  # singular kernel etc. — constant fallback
            return super()._impute_busy(X_done, y_done, X_busy)

    def sample_from_model(self, model: _FittedGP, fixed_last=None) -> np.ndarray:
        d = model.X.shape[1]
        d_free = d - 1 if fixed_last is not None else d

        def embed(x_free):
            if fixed_last is None:
                return x_free
            pad = np.full((*x_free.shape[:-1], 1), fixed_last)
            return np.concatenate([x_free, pad], axis=-1)

        if self.acq_fun == "asy_ts":
            # joint posterior sampling is O(n^3) in the candidate count
            n = min(self.acq_samples, 512)
            Xs = self.rng.random((n, d_free))
            draw = self._thompson_draw(model, embed(Xs))
            return Xs[int(np.argmin(draw))]

        Xs = self.rng.random((self.acq_samples, d_free))
        acq = self._acquisition(model, embed(Xs))
        x0 = Xs[int(np.argmin(acq))]
        # local refinement of the incumbent candidate (free dims only)
        try:
            from scipy.optimize import minimize

            res = minimize(
                lambda x: float(self._acquisition(model, embed(x)[None, :])[0]),
                x0,
                method="L-BFGS-B",
                bounds=[(0.0, 1.0)] * d_free,
            )
            if res.success and res.fun <= float(
                self._acquisition(model, embed(x0)[None, :])[0]
            ):
                return np.asarray(res.x)
        except ImportError:  # pragma: no cover
            pass
        return x0
