"""Asynchronous Bayesian-optimization base.

Capability parity with the reference ``maggy/optimizer/bayes/base.py:26-681``:
a random warmup buffer, an exploration ``random_fraction``, per-budget surrogate
models, busy-trial imputation (constant liar) so parallel workers do not pile
onto the same optimum, and duplicate-config rejection with a bounded random
fallback. Surrogates live in numpy (GP) — no skopt/statsmodels (§2.9).

Async contract: ``get_suggestion`` is called by the driver's digestion thread
whenever a worker needs a config; observations are whatever sits in
``final_store`` at that moment — there is no synchronous batch.
"""

from __future__ import annotations

from abc import abstractmethod
from typing import Dict, Optional, Union

import numpy as np

from maggy_tpu.optimizer.abstractoptimizer import IDLE, AbstractOptimizer
from maggy_tpu.trial import Trial


class BaseAsyncBO(AbstractOptimizer):
    def __init__(
        self,
        num_warmup_trials: int = 15,
        random_fraction: float = 0.33,
        imputation: str = "cl_min",
        **kwargs,
    ):
        super().__init__(**kwargs)
        if not 0 <= random_fraction <= 1:
            raise ValueError("random_fraction must be in [0, 1]")
        if imputation not in ("cl_min", "cl_max", "cl_mean"):
            raise ValueError("imputation must be one of cl_min/cl_max/cl_mean")
        self.num_warmup_trials = int(num_warmup_trials)
        self.random_fraction = float(random_fraction)
        self.imputation = imputation

    def initialize(self) -> None:
        warmup = min(self.num_warmup_trials, self.num_trials)
        self._warmup_buffer = [
            self.searchspace.sample(self._py_rng) for _ in range(warmup)
        ]
        self.models: Dict[Optional[float], object] = {}

    # ------------------------------------------------------------------ interface

    @abstractmethod
    def fit_model(self, X: np.ndarray, y: np.ndarray):
        """Fit and return a surrogate for (X, y) in the unit cube (y minimized)."""

    @abstractmethod
    def sample_from_model(self, model) -> np.ndarray:
        """Propose the next point in the unit cube from a fitted surrogate."""

    def get_suggestion(self, trial: Optional[Trial] = None) -> Union[Trial, str, None]:
        if self.pruner is not None:
            decision = self.pruner.pruning_routine()
            if decision == "IDLE":
                return IDLE
            if decision is None:
                return None
            return self._pruner_trial(decision)

        if self.num_created >= self.num_trials:
            return IDLE if self.trial_store else None

        # 1. warmup: pre-sampled random configs
        while self._warmup_buffer:
            params = self._warmup_buffer.pop(0)
            if not self.hparams_exist(params):
                return self.create_trial(params, sample_type="warmup")

        # 2. exploration fraction stays random forever (async BO robustness)
        if self.rng.random() < self.random_fraction:
            params = self._unique_random()
            if params is not None:
                return self.create_trial(params, sample_type="random")
            return IDLE if self.trial_store else None

        # 3. model-based proposal
        params = self._model_proposal()
        if params is not None:
            return self.create_trial(params, sample_type="model")
        params = self._unique_random()
        if params is not None:
            return self.create_trial(params, sample_type="random")
        return IDLE if self.trial_store else None

    # ------------------------------------------------------------------ internals

    def _pruner_trial(self, decision) -> Trial:
        def fresh():
            params = self._model_proposal(budget=decision["budget"])
            if params is not None:
                return params, "model"
            return self._unique_random(), "random"

        return self.pruner_trial(decision, fresh)

    def _unique_random(self, attempts: int = 20) -> Optional[dict]:
        for _ in range(attempts):
            params = self.searchspace.sample(self._py_rng)
            if not self.hparams_exist(params):
                return params
        return None

    def _training_set(self, budget: Optional[float] = None):
        """(X, y) at one budget rung (None = budget-less experiment) with
        busy-location imputation: in-flight configs get a constant-liar value so
        the acquisition avoids re-proposing them (reference bayes/base.py:400-457).
        X and y come from the same `_observed` filter, so they always align."""
        X_parts, y_parts = [], []
        X_done = self.get_hparams_array(budget)
        y_done = self.get_metrics_array(budget)
        if X_done.size:
            X_parts.append(X_done)
            y_parts.append(y_done)
        if y_done.size and self.trial_store:
            liar = {
                "cl_min": float(y_done.min()),
                "cl_max": float(y_done.max()),
                "cl_mean": float(y_done.mean()),
            }[self.imputation]
            busy = self.searchspace.transform_many(
                [
                    self._strip_budget(t.params)
                    for t in self.trial_store.values()
                    if budget is None or t.params.get("budget") == budget
                ]
            )
            if busy.size:
                X_parts.append(busy)
                y_parts.append(np.full(busy.shape[0], liar))
        if not X_parts:
            return None, None
        return np.concatenate(X_parts), np.concatenate(y_parts)

    def _model_budget(self, target_budget: Optional[float]) -> Optional[float]:
        """Train the surrogate at the largest budget rung with enough
        observations (per-budget models, reference bayes/base.py:136-139);
        fall back to the target rung itself."""
        if target_budget is None:
            return None
        need = max(3, len(self.searchspace) + 1)
        budgets = sorted(
            {
                t.params["budget"]
                for t in self.final_store
                if "budget" in t.params and t.final_metric is not None
            },
            reverse=True,
        )
        for b in budgets:
            if len(self._observed(b)) >= need:
                return b
        return target_budget

    def _model_proposal(
        self, dedup_attempts: int = 3, budget: Optional[float] = None
    ) -> Optional[dict]:
        model_budget = self._model_budget(budget)
        X, y = self._training_set(model_budget)
        if X is None or len(X) < max(3, len(self.searchspace) + 1):
            return None
        try:
            model = self.fit_model(X, y)
        except Exception:  # singular kernels etc. — fall back to random
            return None
        self.models[model_budget] = model
        for _ in range(dedup_attempts):
            vec = np.clip(self.sample_from_model(model), 0.0, 1.0)
            params = self.searchspace.inverse_transform(vec)
            if not self.hparams_exist(params):
                return params
        return None
