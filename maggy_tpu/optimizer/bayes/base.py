"""Asynchronous Bayesian-optimization base.

Capability parity with the reference ``maggy/optimizer/bayes/base.py:26-681``:
a random warmup buffer, an exploration ``random_fraction``, per-budget surrogate
models, busy-trial imputation (constant liar) so parallel workers do not pile
onto the same optimum, and duplicate-config rejection with a bounded random
fallback. Surrogates live in numpy (GP) — no skopt/statsmodels (§2.9).

Async contract: ``get_suggestion`` is called by the driver's digestion thread
whenever a worker needs a config; observations are whatever sits in
``final_store`` at that moment — there is no synchronous batch.
"""

from __future__ import annotations

from abc import abstractmethod
from typing import Dict, Optional, Union

import numpy as np

from maggy_tpu.optimizer.abstractoptimizer import IDLE, AbstractOptimizer
from maggy_tpu.trial import Trial


class BaseAsyncBO(AbstractOptimizer):
    def __init__(
        self,
        num_warmup_trials: int = 15,
        random_fraction: float = 0.33,
        imputation: str = "cl_min",
        multi_fidelity: str = "per_rung",
        interim_rows: int = 0,
        **kwargs,
    ):
        """``multi_fidelity`` (only relevant with a pruner): "per_rung" trains
        one surrogate per budget rung; "augment" trains a single surrogate over
        budget-augmented final metrics z=[x, b/b_max] using ALL observations.
        ``interim_rows > 0`` additionally emits up to that many rows per trial
        from its heartbeat metric history at fractional budgets — the
        reference's interim-results augmentation (bayes/base.py:459-641).
        ``imputation="kb"`` is kriging believer (reference gp.py:329-373):
        busy trials are imputed at the surrogate's posterior mean rather than
        a constant — surrogate-specific, provided by GP via
        :meth:`_impute_busy`."""
        super().__init__(**kwargs)
        if not 0 <= random_fraction <= 1:
            raise ValueError("random_fraction must be in [0, 1]")
        if imputation not in ("cl_min", "cl_max", "cl_mean", "kb"):
            raise ValueError("imputation must be one of cl_min/cl_max/cl_mean/kb")
        if multi_fidelity not in ("per_rung", "augment"):
            raise ValueError("multi_fidelity must be per_rung or augment")
        self.num_warmup_trials = int(num_warmup_trials)
        self.random_fraction = float(random_fraction)
        self.imputation = imputation
        self.multi_fidelity = multi_fidelity
        self.interim_rows = int(interim_rows)

    def initialize(self) -> None:
        warmup = min(self.num_warmup_trials, self.num_trials)
        self._warmup_buffer = [
            self.searchspace.sample(self._py_rng) for _ in range(warmup)
        ]
        self.models: Dict[Optional[float], object] = {}

    # ------------------------------------------------------------------ interface

    @abstractmethod
    def fit_model(self, X: np.ndarray, y: np.ndarray):
        """Fit and return a surrogate for (X, y) in the unit cube (y minimized)."""

    @abstractmethod
    def sample_from_model(self, model, fixed_last: Optional[float] = None) -> np.ndarray:
        """Propose the next point in the unit cube from a fitted surrogate.

        ``fixed_last``: multi-fidelity augmentation — the model's last input
        dimension is a normalized budget pinned to this value; the returned
        vector EXCLUDES that coordinate."""

    def get_suggestion(self, trial: Optional[Trial] = None) -> Union[Trial, str, None]:
        if self.pruner is not None:
            decision = self.pruner.pruning_routine()
            if decision == "IDLE":
                return IDLE
            if decision is None:
                return None
            return self._pruner_trial(decision)

        if self.num_created >= self.num_trials:
            return IDLE if self.trial_store else None

        # 1. warmup: pre-sampled random configs
        while self._warmup_buffer:
            params = self._warmup_buffer.pop(0)
            if not self.hparams_exist(params):
                return self.create_trial(params, sample_type="warmup")

        # 2. exploration fraction stays random forever (async BO robustness)
        if self.rng.random() < self.random_fraction:
            params = self._unique_random()
            if params is not None:
                return self.create_trial(params, sample_type="random")
            return IDLE if self.trial_store else None

        # 3. model-based proposal
        params = self._model_proposal()
        if params is not None:
            return self.create_trial(params, sample_type="model")
        params = self._unique_random()
        if params is not None:
            return self.create_trial(params, sample_type="random")
        return IDLE if self.trial_store else None

    # ------------------------------------------------------------------ internals

    def _pruner_trial(self, decision) -> Trial:
        def fresh():
            params = self._model_proposal(budget=decision["budget"])
            if params is not None:
                return params, "model"
            return self._unique_random(), "random"

        return self.pruner_trial(decision, fresh)

    def _unique_random(self, attempts: int = 20) -> Optional[dict]:
        for _ in range(attempts):
            params = self.searchspace.sample(self._py_rng)
            if not self.hparams_exist(params):
                return params
        return None

    def _training_set(self, budget: Optional[float] = None):
        """(X, y) at one budget rung (None = budget-less experiment) with
        busy-location imputation: in-flight configs get a constant-liar value so
        the acquisition avoids re-proposing them (reference bayes/base.py:400-457).
        X and y come from the same `_observed` filter, so they always align."""
        X_parts, y_parts = [], []
        X_done = self.get_hparams_array(budget)
        y_done = self.get_metrics_array(budget)
        if X_done.size:
            X_parts.append(X_done)
            y_parts.append(y_done)
        if y_done.size and self.trial_store:
            busy = self.searchspace.transform_many(
                [
                    self._strip_budget(t.params)
                    for t in self.trial_store.values()
                    if budget is None or t.params.get("budget") == budget
                ]
            )
            if busy.size:
                X_parts.append(busy)
                y_parts.append(self._impute_busy(X_done, y_done, busy))
        if not X_parts:
            return None, None
        return np.concatenate(X_parts), np.concatenate(y_parts)

    def _model_budget(self, target_budget: Optional[float]) -> Optional[float]:
        """Train the surrogate at the largest budget rung with enough
        observations (per-budget models, reference bayes/base.py:136-139);
        fall back to the target rung itself."""
        if target_budget is None:
            return None
        need = max(3, len(self.searchspace) + 1)
        budgets = sorted(
            {
                t.params["budget"]
                for t in self.final_store
                if "budget" in t.params and t.final_metric is not None
            },
            reverse=True,
        )
        for b in budgets:
            if len(self._observed(b)) >= need:
                return b
        return target_budget

    def _liar(self, y_done: np.ndarray) -> float:
        """Constant-liar value for busy-trial imputation ("kb" surrogates
        override :meth:`_impute_busy`; the mean is their fallback)."""
        return {
            "cl_min": float(y_done.min()),
            "cl_max": float(y_done.max()),
            "cl_mean": float(y_done.mean()),
            "kb": float(y_done.mean()),
        }[self.imputation]

    def _impute_busy(
        self, X_done: np.ndarray, y_done: np.ndarray, X_busy: np.ndarray
    ) -> np.ndarray:
        """Imputed y for in-flight configs: constant liar by default;
        surrogates supporting kriging believer override this."""
        return np.full(X_busy.shape[0], self._liar(y_done))

    def _augmented_training_set(self, target_budget: Optional[float]):
        """[x, b/b_max] design over ALL observations + busy imputation; returns
        (X_aug, y, b_norm) with b_norm the normalized target coordinate."""
        max_b = self.get_max_budget() or target_budget or 1.0
        obs = self._observed()
        if not obs:
            return None, None, 1.0
        X = self.searchspace.transform_many([self._strip_budget(t.params) for t in obs])
        b = np.asarray(
            [t.params.get("budget", max_b) / max_b for t in obs], dtype=np.float64
        )
        # y derived from the SAME `obs` list so X/y rows always align
        y = np.asarray(
            [
                -t.final_metric if self.direction == "max" else t.final_metric
                for t in obs
            ],
            dtype=np.float64,
        )
        X_aug = np.concatenate([X, b[:, None]], axis=1)
        # busy-trial imputation learns from FINAL metrics only, before interim
        # rows dilute y with early-training values
        X_final, y_final = (X_aug, y) if self.trial_store and y.size else (None, None)
        if self.interim_rows > 0:
            # interim observations: the metric after the j-th of n heartbeats of
            # a budget-b trial sits at fractional budget (j+1)/n * b/b_max —
            # scaled by position in the trial's OWN history, since heartbeat
            # step numbering is user-defined and not in budget units
            extra_X, extra_y = [], []
            for t, x_row, b_frac in zip(obs, X, b):
                n_hist = len(t.metric_history)
                if n_hist == 0:
                    continue
                idx = (
                    np.linspace(0, n_hist - 1, self.interim_rows).astype(int)
                    if n_hist > self.interim_rows
                    else np.arange(n_hist)
                )
                for j in idx:
                    frac = (j + 1) / n_hist * b_frac
                    m = t.metric_history[j]
                    extra_X.append(np.concatenate([x_row, [frac]]))
                    extra_y.append(-m if self.direction == "max" else m)
            if extra_X:
                X_aug = np.concatenate([X_aug, np.stack(extra_X)])
                y = np.concatenate([y, np.asarray(extra_y, dtype=np.float64)])
        if self.trial_store and X_final is not None:
            busy = list(self.trial_store.values())
            Xb = self.searchspace.transform_many(
                [self._strip_budget(t.params) for t in busy]
            )
            bb = np.asarray(
                [t.params.get("budget", max_b) / max_b for t in busy],
                dtype=np.float64,
            )
            if Xb.size:
                Xb_aug = np.concatenate([Xb, bb[:, None]], axis=1)
                X_aug = np.concatenate([X_aug, Xb_aug])
                y = np.concatenate([y, self._impute_busy(X_final, y_final, Xb_aug)])
        b_norm = (target_budget / max_b) if target_budget else 1.0
        return X_aug, y, float(min(b_norm, 1.0))

    def _model_proposal(
        self, dedup_attempts: int = 3, budget: Optional[float] = None
    ) -> Optional[dict]:
        fixed_coord = None
        if self.multi_fidelity == "augment" and budget is not None:
            X, y, b_norm = self._augmented_training_set(budget)
            fixed_coord = b_norm
            model_key = "augment"
        else:
            model_budget = self._model_budget(budget)
            X, y = self._training_set(model_budget)
            model_key = model_budget
        # augment mode has one extra (budget) column — require one more row
        min_rows = max(3, (X.shape[1] if X is not None else 0) + 1,
                       len(self.searchspace) + 1)
        if X is None or len(X) < min_rows:
            return None
        try:
            model = self.fit_model(X, y)
        except Exception:  # singular kernels etc. — fall back to random
            return None
        self.models[model_key] = model
        for _ in range(dedup_attempts):
            vec = np.clip(
                self.sample_from_model(model, fixed_last=fixed_coord), 0.0, 1.0
            )
            params = self.searchspace.inverse_transform(vec)
            if not self.hparams_exist(params):
                return params
        return None
