"""ASHA — Asynchronous Successive Halving (arXiv:1810.05934).

Capability parity with the reference ``maggy/optimizer/asha.py:23-169``: geometric
budget rungs ``resource_min * reduction_factor**k``, promotion whenever
``len(finished_in_rung) // reduction_factor`` exceeds the number already promoted,
new random configurations at the base rung otherwise. Unlike the reference, whose
``_top_k`` always sorts descending regardless of ``direction`` (asha.py:166 — a
latent bug noted in SURVEY.md §2.6), promotion here respects the optimization
direction.

Budgets ride in ``trial.params["budget"]``; the train_fn reads it to size its
training (epochs/steps) — same contract as the reference (asha.py:130-152).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Set, Union

from maggy_tpu.optimizer.abstractoptimizer import IDLE, AbstractOptimizer
from maggy_tpu.trial import Trial


class Asha(AbstractOptimizer):
    def __init__(
        self,
        reduction_factor: int = 2,
        resource_min: float = 1,
        resource_max: float = 4,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if reduction_factor < 2:
            raise ValueError("reduction_factor must be >= 2")
        if resource_min <= 0 or resource_max < resource_min:
            raise ValueError("need 0 < resource_min <= resource_max")
        self.reduction_factor = int(reduction_factor)
        self.resource_min = resource_min
        self.resource_max = resource_max

    def initialize(self) -> None:
        eta, r, R = self.reduction_factor, self.resource_min, self.resource_max
        # epsilon before floor: log(243, 3) == 4.999... would silently drop the
        # top rung otherwise
        self.num_rungs = int(math.floor(math.log(R / r, eta) + 1e-9)) + 1
        self.budgets = [min(r * eta**k, R) for k in range(self.num_rungs)]
        self._base_sampled = 0
        self._promoted: Dict[int, Set[str]] = {k: set() for k in range(self.num_rungs)}
        # config-id (params sans budget) of every created trial, for dedup
        self._seen_configs: Set[str] = set()

    # ------------------------------------------------------------------ helpers

    def _rung_of(self, trial: Trial) -> int:
        b = trial.params.get("budget", self.budgets[0])
        for k in reversed(range(self.num_rungs)):
            if b >= self.budgets[k]:
                return k
        return 0

    def _internal_metric(self, trial: Trial) -> float:
        # Metric-less trials (errored/early-stopped) sort worst in either direction.
        if trial.final_metric is None:
            return float("inf")
        m = trial.final_metric
        return -m if self.direction == "max" else m

    def _finished_in_rung(self, k: int) -> List[Trial]:
        return [t for t in self.final_store if self._rung_of(t) == k]

    def _config_id(self, trial: Trial) -> str:
        return Trial.compute_id(self._strip_budget(trial.params))

    # ------------------------------------------------------------------ interface

    def get_suggestion(self, trial: Optional[Trial] = None) -> Union[Trial, str, None]:
        # 1. promotion, top rung first, so trials climb as fast as possible
        for k in reversed(range(self.num_rungs - 1)):
            finished = self._finished_in_rung(k)
            quota = len(finished) // self.reduction_factor - len(self._promoted[k])
            if quota <= 0:
                continue
            candidates = sorted(finished, key=self._internal_metric)
            for cand in candidates:
                cid = self._config_id(cand)
                if cid in self._promoted[k]:
                    continue
                self._promoted[k].add(cid)
                new = self.create_trial(
                    self._strip_budget(cand.params),
                    budget=self.budgets[k + 1],
                    sample_type="promoted",
                    run_budget=self.budgets[k + 1],
                )
                return new

        # 2. fresh configuration at the base rung
        if self._base_sampled < self.num_trials:
            params = self.searchspace.sample(self._py_rng)
            attempts = 0
            while Trial.compute_id(params) in self._seen_configs and attempts < 100:
                params = self.searchspace.sample(self._py_rng)
                attempts += 1
            if Trial.compute_id(params) in self._seen_configs:
                # Space exhausted: a duplicate config would collide in the
                # id-keyed trial_store. Stop sampling the base rung.
                self._base_sampled = self.num_trials
                return self.get_suggestion(trial)
            self._seen_configs.add(Trial.compute_id(params))
            self._base_sampled += 1
            return self.create_trial(
                params,
                budget=self.budgets[0],
                sample_type="random",
                run_budget=self.budgets[0],
            )

        # 3. trials still in flight may unlock promotions when they land
        if self.trial_store:
            return IDLE

        return None
