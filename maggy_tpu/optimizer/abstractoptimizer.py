"""Optimizer (trial-generation controller) base class.

Capability parity with the reference ``maggy/optimizer/abstractoptimizer.py``
(abstractoptimizer.py:28-443): the driver polls ``get_suggestion`` after every
finalized trial; the optimizer reads the shared ``trial_store`` (busy trials) and
``final_store`` (finalized trials), supports a pruner hookup, budget-carrying
trials, duplicate-configuration checks, and metric accessors with max→min
negation so concrete algorithms can always minimize internally.
"""

from __future__ import annotations

import logging
import random
import time
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Union

import numpy as np

from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial

logger = logging.getLogger(__name__)

# Sentinel returned by get_suggestion when no trial is available right now but the
# experiment is not finished (reference optimization_driver.py:542-568 IDLE path).
IDLE = "IDLE"


class AbstractOptimizer(ABC):
    def __init__(self, seed: Optional[int] = None, **kwargs):
        self.searchspace: Optional[Searchspace] = None
        self.num_trials: int = 0
        self.trial_store: Dict[str, Trial] = {}
        self.final_store: List[Trial] = []
        self.direction: str = "max"
        self.pruner = None
        self.rng = np.random.default_rng(seed)
        self._py_rng = random.Random(None if seed is None else int(seed))
        self.extra_config = kwargs

    # ------------------------------------------------------------- wiring
    # The driver injects shared state after construction
    # (reference optimization_driver.py:112-117).

    def setup(
        self,
        searchspace: Searchspace,
        num_trials: int,
        trial_store: Dict[str, Trial],
        final_store: List[Trial],
        direction: str = "max",
        pruner=None,
    ) -> None:
        self.searchspace = searchspace
        self.num_trials = num_trials
        self.trial_store = trial_store
        self.final_store = final_store
        self.direction = direction
        self.pruner = pruner
        self.initialize()

    # ------------------------------------------------------------- interface

    def initialize(self) -> None:
        """Hook run once after wiring; default no-op."""

    @abstractmethod
    def get_suggestion(self, trial: Optional[Trial] = None) -> Union[Trial, str, None]:
        """Return the next Trial, IDLE if the caller should retry later, or None
        when the experiment is exhausted. ``trial`` is the just-finalized trial,
        if any (reference abstractoptimizer.py:62)."""

    def finalize_experiment(self, trials: List[Trial]) -> None:
        """Hook run once when the experiment ends."""

    # ------------------------------------------------------------- trial creation

    def create_trial(
        self,
        params: Dict[str, Any],
        budget: Optional[float] = None,
        sample_type: str = "random",
        run_budget: Optional[float] = None,
    ) -> Trial:
        """Build a Trial, stamping budget into params and provenance into info_dict
        (reference abstractoptimizer.py:317-376)."""
        params = dict(params)
        if budget is not None:
            params["budget"] = budget
        info = {
            "sample_type": sample_type,
            "sampling_time": time.time(),
        }
        if run_budget is not None:
            info["run_budget"] = run_budget
        return Trial(params, trial_type="optimization", info_dict=info)

    # ------------------------------------------------------------- accessors

    # keys injected by the framework that are not hyperparameters
    CONTROL_KEYS = ("budget", "run", "rep")

    @classmethod
    def _strip_budget(cls, params: Dict[str, Any]) -> Dict[str, Any]:
        return {k: v for k, v in params.items() if k not in cls.CONTROL_KEYS}

    def _observed(self, budget: Optional[float] = None) -> List[Trial]:
        """Finalized trials with a usable metric, optionally at one budget rung.
        One filter for both accessors below, so X and y always align."""
        return [
            t
            for t in self.final_store
            if t.final_metric is not None
            and (budget is None or t.params.get("budget") == budget)
        ]

    def get_hparams_array(self, budget: Optional[float] = None) -> np.ndarray:
        """Design matrix of observed trials in the unit cube, optionally filtered
        to one budget rung (reference abstractoptimizer.py:186-252)."""
        return self.searchspace.transform_many(
            [self._strip_budget(t.params) for t in self._observed(budget)]
        )

    def get_metrics_array(self, budget: Optional[float] = None) -> np.ndarray:
        """Metrics of observed trials, negated under direction=max so the
        surrogate always minimizes (reference abstractoptimizer.py:186-252)."""
        vals = [
            -t.final_metric if self.direction == "max" else t.final_metric
            for t in self._observed(budget)
        ]
        return np.asarray(vals, dtype=np.float64)

    def hparams_exist(self, params: Dict[str, Any]) -> bool:
        """True if this configuration (budget ignored) has already been created
        (reference abstractoptimizer.py:254-295)."""
        target = Trial.compute_id(self._strip_budget(params))
        for t in self.trial_store.values():
            if Trial.compute_id(self._strip_budget(t.params)) == target:
                return True
        for t in self.final_store:
            if Trial.compute_id(self._strip_budget(t.params)) == target:
                return True
        return False

    def ybest(self, budget: Optional[float] = None) -> Optional[float]:
        y = self.get_metrics_array(budget)
        return float(y.min()) if y.size else None

    def yworst(self, budget: Optional[float] = None) -> Optional[float]:
        y = self.get_metrics_array(budget)
        return float(y.max()) if y.size else None

    def ymean(self, budget: Optional[float] = None) -> Optional[float]:
        y = self.get_metrics_array(budget)
        return float(y.mean()) if y.size else None

    def get_max_budget(self) -> Optional[float]:
        """Largest budget among known trials (reference abstractoptimizer.py:378-400)."""
        budgets = [
            t.params["budget"]
            for t in list(self.trial_store.values()) + self.final_store
            if "budget" in t.params
        ]
        return max(budgets) if budgets else None

    @property
    def num_created(self) -> int:
        return len(self.trial_store) + len(self.final_store)

    def name(self) -> str:
        return type(self).__name__

    # ------------------------------------------------------------- pruner protocol

    def _find_trial(self, trial_id: str) -> Trial:
        if trial_id in self.trial_store:
            return self.trial_store[trial_id]
        for t in self.final_store:
            if t.trial_id == trial_id:
                return t
        raise KeyError(f"Unknown trial id {trial_id}")

    def pruner_trial(self, decision: Dict[str, Any], fresh_sampler) -> Trial:
        """Turn a pruner decision into a Trial (shared by every pruner-capable
        optimizer). ``fresh_sampler() -> (params | None, sample_type)`` supplies
        fresh configs; on exhaustion the slot is filled by re-running a random
        config salted with a 'rep' nonce so trial ids never collide."""
        trial_id, budget = decision["trial_id"], decision["budget"]
        if trial_id is None:
            params, sample_type = fresh_sampler()
            if params is None:
                self._rep_counter = getattr(self, "_rep_counter", 0) + 1
                params = self.searchspace.sample(self._py_rng)
                params["rep"] = self._rep_counter
                sample_type = "repeat"
            new = self.create_trial(
                params, budget=budget, sample_type=sample_type, run_budget=budget
            )
        else:
            base = self._find_trial(trial_id)
            new = self.create_trial(
                self._strip_budget(base.params),
                budget=budget,
                sample_type="promoted",
                run_budget=budget,
            )
        self.pruner.report_trial(original_trial_id=trial_id, new_trial_id=new.trial_id)
        return new
