"""Autotuner CLI: find the best system config for a model on this host.

    python -m maggy_tpu.tune --config tiny --presets dp,fsdp,2d \
        --batch-sizes 8,16,32 --seq-len 128
    python -m maggy_tpu.tune --config llama3_8b --budget-gb 14 --no-measure

Prints ONE JSON line (the TuneResult) on stdout; progress goes to stderr.
The winner also lands in the tuning cache under the ambient experiment root
(``MAGGY_TPU_LOG_ROOT``/``tune_cache``, local or ``gs://``), where
``bench.py`` and ``python -m maggy_tpu.serve --mesh auto`` pick it up.
"""

from __future__ import annotations

import argparse
import json
import sys


def _csv(text: str, cast=str):
    return tuple(cast(x) for x in text.split(",") if x)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m maggy_tpu.tune", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--config", default="tiny",
                        help="DecoderConfig preset name or .json file")
    parser.add_argument("--presets", default="dp,fsdp,2d",
                        help="comma-separated mesh presets")
    parser.add_argument("--batch-sizes", default="8,16,32",
                        help="comma-separated global batch sizes")
    parser.add_argument("--microbatches", default="",
                        help="comma-separated n_microbatches options (pp meshes)")
    parser.add_argument("--remat", default="",
                        help="comma-separated remat policies to try "
                             "(nothing/dots/dots_attn/everything)")
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--budget-gb", type=float,
                        help="per-device HBM budget for the AOT prune "
                             "(default: ask the device; CPU has none)")
    parser.add_argument("--no-measure", action="store_true",
                        help="static stage only — rank by flops/bytes")
    parser.add_argument("--no-cache", action="store_true",
                        help="ignore and don't write the tuning cache")
    parser.add_argument("--steps-per-unit", type=int, default=4,
                        help="train steps per unit of ASHA budget")
    parser.add_argument("--max-candidates", type=int, default=64)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    from maggy_tpu.models import Decoder
    from maggy_tpu.serve.__main__ import build_config
    from maggy_tpu.tune import TuneConfig, tune

    model = Decoder(build_config(args.config))
    remat = _csv(args.remat) or (None,)
    micro = _csv(args.microbatches, int) or (None,)
    tune_cfg = TuneConfig(
        presets=_csv(args.presets),
        batch_sizes=_csv(args.batch_sizes, int),
        microbatches=micro,
        remat_policies=remat,
        seq_len=args.seq_len,
        hbm_budget_bytes=(
            int(args.budget_gb * 2**30) if args.budget_gb else None
        ),
        measure=not args.no_measure,
        cache=not args.no_cache,
        steps_per_unit=args.steps_per_unit,
        max_candidates=args.max_candidates,
        seed=args.seed,
    )
    print(
        f"[tune] model={args.config} presets={tune_cfg.presets} "
        f"batch_sizes={tune_cfg.batch_sizes} seq_len={tune_cfg.seq_len}",
        file=sys.stderr,
    )
    result = tune(model, tune_cfg)
    out = result.to_dict()
    out.pop("reports", None)  # one-line summary; full reports live in the cache
    best = result.best
    print(
        f"[tune] {'cache hit' if result.cache_hit else 'tuned'}: "
        f"spec={best.spec} bs={best.batch_size} "
        f"remat={best.remat_policy} source={best.source}",
        file=sys.stderr,
    )
    print(json.dumps(out), file=sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
