"""Persistent tuning cache on the env seam.

Winners live as JSON records under ``<env root>/tune_cache/<key>.json`` —
through :class:`BaseEnv`, so a local directory and a ``gs://`` bucket behave
identically (the same seam checkpoints and trial records already use). The
key binds a record to exactly the situation it was measured in:

    (model fingerprint, device topology, compute dtype, seq_len, search grid)

Model fingerprint hashes the *abstract* parameter tree (every leaf's path,
shape, dtype via ``jax.eval_shape`` — no allocation) plus the model config's
repr when it has one; two models that would compile different programs get
different keys. Changing the candidate grid also changes the key: a cached
winner is only a winner *of the grid it was chosen from*.
"""

from __future__ import annotations

import hashlib
import json
import posixpath
from typing import Any, Dict, Optional


def model_fingerprint(model: Any, sample_batch: Dict[str, Any]) -> str:
    """Stable hash of the model's abstract parameter tree + config."""
    import jax

    from maggy_tpu.train.trainer import _model_inputs

    abstract = jax.eval_shape(
        lambda rng, *ins: model.init(rng, *ins),
        jax.random.key(0),
        *_model_inputs(sample_batch),
    )
    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(abstract)[0]:
        leaf = leaf.unbox() if hasattr(leaf, "unbox") else leaf
        leaves.append(
            (jax.tree_util.keystr(path), tuple(leaf.shape), str(leaf.dtype))
        )
    payload = json.dumps(sorted(leaves)) + repr(getattr(model, "cfg", ""))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def topology_key(devices: Optional[list] = None) -> Dict[str, Any]:
    import jax

    devs = devices if devices is not None else jax.devices()
    d0 = devs[0]
    return {
        "n_devices": len(devs),
        "platform": getattr(d0, "platform", "unknown"),
        "device_kind": getattr(d0, "device_kind", "unknown"),
        # process layout is part of the topology: an 8-device single host
        # and a 2x4 multi-process slice compile different programs, and two
        # concurrent jobs with those shapes must not share cache records
        "n_processes": jax.process_count(),
    }


def cache_key(
    fingerprint: str,
    topology: Dict[str, Any],
    dtype: str,
    grid: Dict[str, Any],
) -> str:
    payload = json.dumps(
        {"model": fingerprint, "topology": topology, "dtype": dtype, "grid": grid},
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


def alias_workload(
    fingerprint: str, topology: Dict[str, Any], dtype: str
) -> str:
    """The workload fingerprint a ``latest`` alias is scoped to: the
    (model, topology, dtype) identity, hashed the same way
    :func:`maggy_tpu.autopilot.plan.workload_fingerprint` hashes its
    scopes. Stamped INTO every alias record and verified on read."""
    payload = json.dumps(
        {"model": fingerprint, "topology": topology, "dtype": dtype},
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


def alias_cache_key(fingerprint: str, topology: Dict[str, Any], dtype: str) -> str:
    """Grid-independent pointer key: the LATEST winner for this (model,
    topology, dtype) regardless of which grid found it. Consumers that never
    tuned themselves (the serve CLI's ``--mesh auto``) look this up; exact
    reproducibility consumers use the grid-bound :func:`cache_key`.

    The alias is scoped per workload, not global: the key embeds the
    workload fingerprint (so two concurrent jobs with different topologies
    write DIFFERENT aliases — ``topology_key`` includes the process layout
    for exactly this reason), and the record itself carries a ``workload``
    stamp that :meth:`TuneCache.get_alias` verifies, so even a hash-level
    collision or a stale/foreign record reads as a cache miss, never as
    another workload's winner (last-writer-wins is gone both ways)."""
    return "latest-" + alias_workload(fingerprint, topology, dtype)


class TuneCache:
    """Read/write tuning records through the ambient (or given) Env."""

    SUBDIR = "tune_cache"

    def __init__(self, env=None):
        if env is None:
            from maggy_tpu.core.env import EnvSing

            env = EnvSing.get_instance()
        self.env = env

    def path(self, key: str) -> str:
        # posixpath: correct for local paths and gs:// URLs alike
        return posixpath.join(self.env.root, self.SUBDIR, f"{key}.json")

    def get_record(self, key: str) -> Optional[Dict[str, Any]]:
        """Raw record lookup — any dict-shaped JSON under the key (the
        autopilot decision store rides this; tuning winners go through
        :meth:`get`, which additionally demands a ``best`` field)."""
        path = self.path(key)
        try:
            if not self.env.exists(path):
                return None
            record = self.env.load_json(path)
        except (OSError, ValueError):
            return None
        return record if isinstance(record, dict) else None

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        record = self.get_record(key)
        return record if record is not None and "best" in record else None

    def get_alias(self, key: str, workload: str) -> Optional[Dict[str, Any]]:
        """Alias lookup scoped to a workload fingerprint: a record whose
        ``workload`` stamp does not match the requester's is a MISS (a
        clobbered or foreign alias must never hand back another job's
        config), as is a legacy unstamped record."""
        record = self.get(key)
        if record is None or record.get("workload") != workload:
            return None
        return record

    def put(self, key: str, record: Dict[str, Any]) -> None:
        try:
            # atomic publish where the env supports it (local FS: temp +
            # rename): two concurrent tuners racing the same key must each
            # leave a COMPLETE record, never interleaved JSON
            dump = getattr(self.env, "_atomic_dump", self.env.dump)
            dump(record, self.path(key))
        except OSError:
            pass  # a cold cache next run is the only consequence
