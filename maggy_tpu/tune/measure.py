"""Stage 2 — measured trials through the existing HPO machinery.

The static-stage survivors become a one-parameter CATEGORICAL
:class:`Searchspace` (the candidate index), the trial function a thin
wrapper over ``Trainer.fit`` on synthetic batches, and the schedule the
stock ASHA optimizer: short cheap trials at the base rung, the promising
configurations promoted to longer measurements. There is **zero new
distributed machinery here** — ``experiment.lagom`` runs the same driver,
RPC plane, executors, telemetry and persistence that hyperparameter studies
use; the "hyperparameter" just happens to be the system configuration.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from maggy_tpu.tune.candidates import Candidate

METRIC_KEY = "steps_per_sec"


def make_trial_fn(
    model: Any,
    survivors: List[Candidate],
    batch_fn: Callable[[int], Dict[str, Any]],
    *,
    make_optimizer: Callable[[], Any],
    loss_fn: Optional[Callable] = None,
    steps_per_unit: int = 4,
    devices: Optional[list] = None,
) -> Callable:
    """The oblivious trial function: pick the candidate by index, build its
    trainer, ``fit`` for the ASHA budget, report measured steps/sec."""

    def tune_trial(hparams, reporter, budget):
        import itertools

        import jax

        from maggy_tpu.tune.candidates import TunedConfig
        from maggy_tpu.train.trainer import lm_loss_fn

        cand = survivors[int(hparams["cand"])]
        steps = max(2, int(round(float(budget or 1) * steps_per_unit)))
        devs = devices if devices is not None else jax.devices()
        tuned = TunedConfig.from_candidate(cand, len(devs))
        trainer = tuned.trainer(
            model, make_optimizer(), devices=devs,
            loss_fn=loss_fn or lm_loss_fn,
        )
        data = itertools.cycle([batch_fn(cand.batch_size)])
        state = trainer.make_state(jax.random.key(0), next(data))
        # warmup fit: one step absorbs the XLA compile so the measured
        # window below times steady-state steps only
        state, _ = trainer.fit(state, data, num_steps=1)
        state, metrics = trainer.fit(state, data, num_steps=steps)
        sps = metrics.get(METRIC_KEY, 0.0)
        reporter.broadcast(float(sps), step=steps)
        reporter.log(
            f"[tune] measured {cand.label}: {sps:.3f} steps/s over {steps} steps"
        )
        return {
            METRIC_KEY: float(sps),
            "step_time_ms": 1e3 / sps if sps else None,
            "candidate": cand.to_dict(),
            "steps": steps,
        }

    return tune_trial


def measured_stage(
    model: Any,
    survivors: List[Candidate],
    batch_fn: Callable[[int], Dict[str, Any]],
    tune_cfg,
    *,
    make_optimizer: Callable[[], Any],
    loss_fn: Optional[Callable] = None,
    devices: Optional[list] = None,
) -> Tuple[int, Dict[str, Any]]:
    """Race the survivors under ASHA via ``experiment.lagom``. Returns
    ``(best_candidate_index, summary)``.

    Runs one experiment on the ambient env (results/telemetry land in the
    usual experiment tree) with a single local executor — trials share the
    host's devices, so concurrent measurement would corrupt the timings.
    """
    from maggy_tpu import Searchspace, experiment
    from maggy_tpu.config import HyperparameterOptConfig
    from maggy_tpu.optimizer import Asha

    space = Searchspace(cand=("CATEGORICAL", list(range(len(survivors)))))
    num_trials = int(tune_cfg.num_measure_trials or len(survivors))
    cfg = HyperparameterOptConfig(
        num_trials=num_trials,
        optimizer=Asha(
            reduction_factor=tune_cfg.asha_reduction_factor,
            resource_min=tune_cfg.asha_resource_min,
            resource_max=tune_cfg.asha_resource_max,
            seed=tune_cfg.seed,
        ),
        searchspace=space,
        optimization_key=METRIC_KEY,
        direction="max",
        es_policy="none",
        name=f"{tune_cfg.name}-measure",
        num_executors=1,
        seed=tune_cfg.seed,
    )
    trial_fn = make_trial_fn(
        model,
        survivors,
        batch_fn,
        make_optimizer=make_optimizer,
        loss_fn=loss_fn,
        steps_per_unit=tune_cfg.steps_per_unit,
        devices=devices,
    )
    result = experiment.lagom(trial_fn, cfg)
    best = (result or {}).get("best")
    if not best or best.get("params") is None:
        raise RuntimeError(f"measured stage produced no best trial: {result!r}")
    best_idx = int(best["params"]["cand"])
    summary = {
        "optimizer": "asha",
        "num_trials": result.get("num_trials"),
        "best_trial_id": best.get("trial_id"),
        "best_steps_per_sec": best.get(METRIC_KEY),
        "best_budget": best.get("params", {}).get("budget"),
        "errors": result.get("errors"),
    }
    return best_idx, summary
