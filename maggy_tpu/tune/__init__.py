"""AOT-guided system-configuration autotuner.

Maggy's core trick is the oblivious training function — the same ``train_fn``
runs as a local run, an HPO trial, or a distributed rank. This package
applies that idea to the *system* axis: mesh shape, global batch size,
microbatch count, remat policy and flash tile sizes are searched like
hyperparameters, in two stages:

**Stage 1 — static (no execution).** Every candidate's train step is
AOT-compiled (``jit → lower → compile``) against abstract arguments and
interrogated: ``memory_analysis()`` prunes configurations whose per-device
estimate exceeds the HBM budget *before anything runs*, and
``cost_analysis()`` provides a flops/bytes ranking. Works identically on the
CPU tier-1 mesh.

**Stage 2 — measured.** The survivors race through the *existing* HPO driver
with the stock ASHA optimizer — candidate index as a CATEGORICAL
searchspace, the trial fn a thin wrapper over ``Trainer.fit`` — so the tuner
adds zero distributed machinery.

Winners persist in a tuning cache on the env seam (local or ``gs://``
identically), keyed by (model fingerprint, topology, dtype, search grid);
``bench.py`` and the serve CLI consult it before falling back to defaults.

    from maggy_tpu.tune import tune, TuneConfig
    result = tune(Decoder(cfg), TuneConfig(presets=("dp", "fsdp", "2d")))
    trainer = result.best.trainer(Decoder(cfg), optax.adamw(1e-3))
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from maggy_tpu.config.tune import TuneConfig
from maggy_tpu.tune import static as static_mod
from maggy_tpu.tune.cache import (
    TuneCache,
    alias_cache_key,
    alias_workload,
    cache_key,
    model_fingerprint,
    topology_key,
)
from maggy_tpu.tune.candidates import Candidate, TunedConfig, enumerate_candidates
from maggy_tpu.tune.static import StaticReport, static_stage

__all__ = [
    "TuneConfig",
    "TuneResult",
    "TunedConfig",
    "Candidate",
    "StaticReport",
    "tune",
]


@dataclasses.dataclass
class TuneResult:
    """Outcome of one :func:`tune` invocation."""

    best: TunedConfig
    key: str
    cache_hit: bool = False
    candidates: int = 0
    pruned_oom: int = 0
    pruned_infeasible: int = 0
    compiled: int = 0
    measured: Optional[Dict[str, Any]] = None
    reports: List[StaticReport] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "best": self.best.to_dict(),
            "key": self.key,
            "cache_hit": self.cache_hit,
            "candidates": self.candidates,
            "pruned_oom": self.pruned_oom,
            "pruned_infeasible": self.pruned_infeasible,
            "compiled": self.compiled,
            "measured": self.measured,
            "reports": [r.to_dict() for r in self.reports],
        }


def default_batch_fn(model: Any, seq_len: int) -> Callable[[int], Dict[str, Any]]:
    """Synthetic LM batches for models with a ``cfg.vocab_size`` (the
    flagship Decoder family). Other models must pass an explicit
    ``batch_fn(batch_size) -> batch`` matching their input contract."""
    import numpy as np

    vocab = getattr(getattr(model, "cfg", None), "vocab_size", None)
    if vocab is None:
        raise ValueError(
            "model has no cfg.vocab_size; pass batch_fn=... to tune() "
            "(a callable batch_size -> batch dict)"
        )
    rng = np.random.default_rng(0)

    def batch_fn(batch_size: int) -> Dict[str, Any]:
        return {
            "tokens": rng.integers(
                0, vocab, size=(batch_size, seq_len), dtype=np.int32
            )
        }

    return batch_fn


def tune(
    model: Any,
    config: Optional[TuneConfig] = None,
    *,
    optimizer: Any = None,
    loss_fn: Optional[Callable] = None,
    batch_fn: Optional[Callable[[int], Dict[str, Any]]] = None,
    env=None,
    devices: Optional[list] = None,
) -> TuneResult:
    """Find the best system configuration for training ``model``.

    Consults the persistent tuning cache first (a hit returns immediately —
    no compiles); otherwise runs the static AOT stage over the candidate
    grid, prunes on memory, races the survivors through the HPO driver with
    ASHA (unless ``config.measure`` is off, in which case the static
    flops/bytes ranking decides), persists the winner, and returns a
    :class:`TuneResult` whose ``best.trainer(model, optax_tx)`` is ready for
    ``fit``. Runs one lagom experiment internally, so it cannot be called
    from inside a running experiment's train_fn.
    """
    import jax
    import optax

    from maggy_tpu import telemetry

    cfg = config or TuneConfig()
    tel = telemetry.get()
    devs = devices if devices is not None else jax.devices()
    tx = optimizer if optimizer is not None else optax.adamw(cfg.learning_rate)
    get_batch = batch_fn or default_batch_fn(model, cfg.seq_len)

    fingerprint = model_fingerprint(model, get_batch(min(cfg.batch_sizes)))
    dtype = str(getattr(getattr(model, "cfg", None), "dtype", "na"))
    key = cache_key(fingerprint, topology_key(devs), dtype, cfg.grid_fingerprint())
    cache = TuneCache(env)

    if cfg.cache:
        record = cache.get(key)
        if record is not None:
            best = TunedConfig.from_dict(record["best"])
            if best.step_time_ms is not None:
                tel.gauge("tune.best_step_time", best.step_time_ms)
            tel.count("tune.cache_hits")
            return TuneResult(
                best=best,
                key=key,
                cache_hit=True,
                candidates=int(record.get("candidates", 0)),
                pruned_oom=int(record.get("pruned_oom", 0)),
                pruned_infeasible=int(record.get("pruned_infeasible", 0)),
                compiled=0,
                measured=record.get("measured"),
            )

    candidates = enumerate_candidates(cfg, len(devs))
    if not candidates:
        raise ValueError(
            f"TuneConfig enumerates no feasible candidates for "
            f"{len(devs)} devices (presets={cfg.presets!r}, "
            f"batch_sizes={cfg.batch_sizes!r})"
        )
    budget = (
        cfg.hbm_budget_bytes
        if cfg.hbm_budget_bytes is not None
        else static_mod.device_memory_budget()
    )

    compiled_before = static_mod.COMPILE_COUNT
    with tel.span("tune.static", candidates=len(candidates)):
        reports = static_stage(
            model,
            candidates,
            get_batch,
            optimizer=tx,
            loss_fn=loss_fn,
            budget_bytes=budget,
            devices=devs,
        )
    compiled = static_mod.COMPILE_COUNT - compiled_before
    survivors = [r.candidate for r in reports if r.ok]
    pruned_oom = sum(1 for r in reports if r.status == "oom")
    pruned_infeasible = sum(1 for r in reports if r.status == "infeasible")
    tel.gauge("tune.candidates", len(candidates))
    tel.gauge("tune.pruned_oom", pruned_oom)
    if not survivors:
        raise RuntimeError(
            f"all {len(candidates)} candidates pruned "
            f"({pruned_oom} over the {budget} B budget, "
            f"{pruned_infeasible} infeasible) — widen the grid or the budget"
        )

    measured_summary = None
    if cfg.measure and len(survivors) > 1:
        from maggy_tpu.tune.measure import measured_stage

        with tel.span("tune.measure", survivors=len(survivors)):
            best_idx, measured_summary = measured_stage(
                model,
                survivors,
                get_batch,
                cfg,
                make_optimizer=lambda: tx,
                loss_fn=loss_fn,
                devices=devs,
            )
        best_cand = survivors[best_idx]
        sps = measured_summary.get("best_steps_per_sec") or 0.0
        best = TunedConfig.from_candidate(
            best_cand,
            len(devs),
            source="measured",
            steps_per_sec=sps or None,
            step_time_ms=(1e3 / sps) if sps else None,
        )
    else:
        ok_reports = [r for r in reports if r.ok]
        ok_reports.sort(key=lambda r: r.cost_per_token(cfg.seq_len))
        best = TunedConfig.from_candidate(
            ok_reports[0].candidate, len(devs), source="static"
        )

    if best.step_time_ms is not None:
        tel.gauge("tune.best_step_time", best.step_time_ms)
    result = TuneResult(
        best=best,
        key=key,
        cache_hit=False,
        candidates=len(candidates),
        pruned_oom=pruned_oom,
        pruned_infeasible=pruned_infeasible,
        compiled=compiled,
        measured=measured_summary,
        reports=reports,
    )
    if cfg.cache:
        tel.count("tune.cache_misses")
        record = {
            "best": best.to_dict(),
            "key": key,
            "candidates": len(candidates),
            "pruned_oom": pruned_oom,
            "pruned_infeasible": pruned_infeasible,
            "measured": measured_summary,
            "reports": [r.to_dict() for r in reports],
            "created": time.time(),
        }
        cache.put(key, record)
        # grid-independent "latest winner" alias for consumers that never
        # tuned themselves (serve --mesh auto) — scoped per workload
        # fingerprint: the record is stamped so a read for a different
        # (model, topology, dtype) can never resolve to this winner
        topo = topology_key(devs)
        cache.put(
            alias_cache_key(fingerprint, topo, dtype),
            {**record, "workload": alias_workload(fingerprint, topo, dtype)},
        )
    return result


def cached_best(
    model: Any,
    config: Optional[TuneConfig] = None,
    *,
    batch_fn: Optional[Callable[[int], Dict[str, Any]]] = None,
    env=None,
    devices: Optional[list] = None,
) -> Optional[TunedConfig]:
    """Cache-only lookup: the tuned winner for this model on this topology,
    if one was ever persisted, else None. Never compiles, never executes —
    the cheap probe the serve CLI uses before falling back to defaults.
    With ``config`` the lookup is bound to that exact search grid; without
    it the grid-independent "latest winner" alias is consulted."""
    import jax

    devs = devices if devices is not None else jax.devices()
    seq_len = config.seq_len if config is not None else 16
    get_batch = batch_fn or default_batch_fn(model, seq_len)
    fingerprint = model_fingerprint(model, get_batch(1))
    dtype = str(getattr(getattr(model, "cfg", None), "dtype", "na"))
    topo = topology_key(devs)
    if config is not None:
        record = TuneCache(env).get(
            cache_key(fingerprint, topo, dtype, config.grid_fingerprint())
        )
    else:
        # workload-verified alias read: a clobbered/foreign record is a
        # miss, never another workload's winner
        record = TuneCache(env).get_alias(
            alias_cache_key(fingerprint, topo, dtype),
            alias_workload(fingerprint, topo, dtype),
        )
    return TunedConfig.from_dict(record["best"]) if record else None
