"""Candidate system configurations and the tuned-winner record.

A :class:`Candidate` is one point in the system-configuration grid —
(mesh shape, global batch, microbatches, remat policy, flash tiles). The
static stage AOT-compiles each one; the measured stage races the survivors.
The winner is frozen into a :class:`TunedConfig`, the JSON-round-trippable
record the tuning cache stores and that builds a ready-to-``fit`` Trainer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Tuple

from maggy_tpu.parallel.spec import ShardingSpec


def resolve_spec(preset: Any, num_devices: int) -> ShardingSpec:
    """A preset name or ShardingSpec resolved against the live device count."""
    if isinstance(preset, ShardingSpec):
        if preset.num_devices == num_devices:
            return preset
        return preset.scaled_to(num_devices)
    return ShardingSpec.preset(str(preset), num_devices)


def apply_remat(model: Any, remat_policy: Optional[str]) -> Any:
    """Return ``model`` with the candidate's remat policy applied, when its
    config carries ``remat``/``remat_policy`` fields (the flagship Decoder
    family does); other models pass through unchanged — the knob is then a
    no-op, not an error, so generic flax models still tune over mesh/batch."""
    if remat_policy is None:
        return model
    cfg = getattr(model, "cfg", None)
    if cfg is None or not hasattr(cfg, "remat_policy"):
        return model
    new_cfg = dataclasses.replace(cfg, remat=True, remat_policy=remat_policy)
    return model.clone(cfg=new_cfg)


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One system configuration under consideration."""

    preset: Any  # preset name (str) or ShardingSpec
    batch_size: int
    n_microbatches: Optional[int] = None
    remat_policy: Optional[str] = None
    flash_block_q: Optional[int] = None
    flash_block_k: Optional[int] = None

    @property
    def label(self) -> str:
        parts = [str(self.preset), f"bs{self.batch_size}"]
        if self.n_microbatches:
            parts.append(f"mb{self.n_microbatches}")
        if self.remat_policy:
            parts.append(f"remat:{self.remat_policy}")
        if self.flash_block_q:
            parts.append(f"fq{self.flash_block_q}/fk{self.flash_block_k}")
        return "/".join(parts)

    def spec_for(self, num_devices: int) -> ShardingSpec:
        return resolve_spec(self.preset, num_devices)

    def to_dict(self) -> Dict[str, Any]:
        preset = (
            dataclasses.asdict(self.preset)
            if isinstance(self.preset, ShardingSpec)
            else self.preset
        )
        return {
            "preset": preset,
            "batch_size": self.batch_size,
            "n_microbatches": self.n_microbatches,
            "remat_policy": self.remat_policy,
            "flash_block_q": self.flash_block_q,
            "flash_block_k": self.flash_block_k,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Candidate":
        preset = d["preset"]
        if isinstance(preset, dict):
            preset = ShardingSpec(**preset)
        return cls(
            preset=preset,
            batch_size=int(d["batch_size"]),
            n_microbatches=d.get("n_microbatches"),
            remat_policy=d.get("remat_policy"),
            flash_block_q=d.get("flash_block_q"),
            flash_block_k=d.get("flash_block_k"),
        )


def enumerate_candidates(tune_cfg, num_devices: int) -> List[Candidate]:
    """The candidate grid, with obviously-infeasible combinations dropped
    before anything is compiled: batch not divisible by the mesh's
    data×fsdp extent, microbatch counts that don't divide the batch, the
    known-invalid pp×sp composition, and microbatch settings on meshes
    without a pipeline axis (collapsed to ``None`` to avoid duplicates)."""
    seen = set()
    out: List[Candidate] = []
    for preset in tune_cfg.presets:
        try:
            spec = resolve_spec(preset, num_devices)
        except ValueError:
            continue  # preset can't cover this device count
        if spec.pp > 1 and spec.sp > 1:
            continue  # Trainer rejects this composition outright
        dpf = spec.dp * spec.fsdp
        for bs in tune_cfg.batch_sizes:
            if bs % dpf:
                continue
            micro_opts: Iterable[Optional[int]] = (
                tune_cfg.microbatches if spec.pp > 1 else (None,)
            )
            for mb in micro_opts:
                if mb is not None and (bs % mb or (bs // mb) % dpf):
                    continue
                for remat in tune_cfg.remat_policies:
                    for blocks in tune_cfg.flash_blocks:
                        fq, fk = blocks if blocks else (None, None)
                        cand = Candidate(
                            preset=preset,
                            batch_size=int(bs),
                            n_microbatches=mb,
                            remat_policy=remat,
                            flash_block_q=fq,
                            flash_block_k=fk,
                        )
                        key = repr(cand.to_dict())
                        if key in seen:
                            continue
                        seen.add(key)
                        out.append(cand)
                        if len(out) >= tune_cfg.max_candidates:
                            return out
    return out


@dataclasses.dataclass(frozen=True)
class TunedConfig:
    """A tuning winner: everything needed to reproduce the chosen system
    configuration. ``Trainer.fit`` accepts it directly via :meth:`trainer`."""

    spec: ShardingSpec
    batch_size: int
    n_microbatches: Optional[int] = None
    remat_policy: Optional[str] = None
    flash_block_q: Optional[int] = None
    flash_block_k: Optional[int] = None
    source: str = "static"  # "static" | "measured" | "cache"
    steps_per_sec: Optional[float] = None
    step_time_ms: Optional[float] = None

    def apply_env(self) -> None:
        """Export the flash tile choice through the same env knobs the bench
        playbook uses, so existing kernels pick it up without plumbing."""
        import os

        if self.flash_block_q:
            os.environ["MAGGY_TPU_FLASH_BWD_Q"] = str(self.flash_block_q)
        if self.flash_block_k:
            os.environ["MAGGY_TPU_FLASH_BWD_K"] = str(self.flash_block_k)

    def mesh(self, devices: Optional[list] = None):
        from maggy_tpu.parallel.mesh import make_mesh

        import jax

        devs = devices if devices is not None else jax.devices()
        spec = (
            self.spec
            if self.spec.num_devices == len(devs)
            else self.spec.scaled_to(len(devs))
        )
        return make_mesh(spec, devs)

    def trainer(self, model: Any, optimizer: Any, devices: Optional[list] = None, **kw):
        """Build a ready Trainer on this config's mesh, with the remat policy
        applied to the model and flash tiles exported. The returned trainer's
        ``fit``/``step`` run the tuned configuration directly."""
        from maggy_tpu.train.trainer import Trainer

        self.apply_env()
        return Trainer(
            apply_remat(model, self.remat_policy),
            optimizer,
            self.mesh(devices),
            n_microbatches=self.n_microbatches,
            **kw,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "spec": dataclasses.asdict(self.spec),
            "batch_size": self.batch_size,
            "n_microbatches": self.n_microbatches,
            "remat_policy": self.remat_policy,
            "flash_block_q": self.flash_block_q,
            "flash_block_k": self.flash_block_k,
            "source": self.source,
            "steps_per_sec": self.steps_per_sec,
            "step_time_ms": self.step_time_ms,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TunedConfig":
        return cls(
            spec=ShardingSpec(**d["spec"]),
            batch_size=int(d["batch_size"]),
            n_microbatches=d.get("n_microbatches"),
            remat_policy=d.get("remat_policy"),
            flash_block_q=d.get("flash_block_q"),
            flash_block_k=d.get("flash_block_k"),
            source=d.get("source", "cache"),
            steps_per_sec=d.get("steps_per_sec"),
            step_time_ms=d.get("step_time_ms"),
        )

    @classmethod
    def from_candidate(cls, cand: Candidate, num_devices: int, **kw) -> "TunedConfig":
        return cls(
            spec=cand.spec_for(num_devices),
            batch_size=cand.batch_size,
            n_microbatches=cand.n_microbatches,
            remat_policy=cand.remat_policy,
            flash_block_q=cand.flash_block_q,
            flash_block_k=cand.flash_block_k,
            **kw,
        )
