"""Stage 1 — static AOT analysis. Nothing here ever executes a train step.

Each candidate's training step is AOT-compiled
(``jax.jit(step).lower(abstract_args).compile()``) against *abstract*
``ShapeDtypeStruct`` arguments carrying the candidate's shardings — no
parameter allocation, no data, no execution. The compiled executable is then
interrogated:

* ``memory_analysis()`` — per-device argument/output/temp byte estimates;
  candidates whose peak estimate exceeds the device budget are pruned as
  ``"oom"`` without ever running (the whole point: an OOM discovered here
  costs a compile, not a crashed trial).
* ``cost_analysis()`` — flops / bytes-accessed, used to rank survivors when
  the measured stage is disabled.

This works identically on every backend (the CPU tier-1 mesh included), so
the full pipeline is exercised hardware-free.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from maggy_tpu.tune.candidates import Candidate, apply_remat

# Module-level AOT compile counter: honest provenance for "a cache hit
# compiles nothing" (tests read it; TuneResult.compiled reports per-run).
COMPILE_COUNT = 0


@dataclasses.dataclass
class StaticReport:
    """One candidate's static-analysis outcome."""

    candidate: Candidate
    status: str  # "ok" | "oom" | "infeasible"
    reason: Optional[str] = None
    hbm_bytes: Optional[int] = None
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    compile_ms: Optional[float] = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    def cost_per_token(self, seq_len: int) -> float:
        """Static ranking proxy: (flops + bytes touched) per trained token.
        Crude — it ignores the compute/bandwidth overlap a roofline model
        would capture — but monotone in both terms, which is all a
        *pre-measurement* ranking needs."""
        tokens = max(1, self.candidate.batch_size * seq_len)
        return ((self.flops or 0.0) + (self.bytes_accessed or 0.0)) / tokens

    def to_dict(self) -> Dict[str, Any]:
        return {
            "candidate": self.candidate.to_dict(),
            "status": self.status,
            "reason": self.reason,
            "hbm_bytes": self.hbm_bytes,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "compile_ms": self.compile_ms,
        }


def device_memory_budget() -> Optional[int]:
    """Per-device memory budget from the backend, with ~6% headroom for
    allocator fragmentation. TPU/GPU report ``bytes_limit``; CPU reports
    nothing → ``None`` (no memory pruning unless the user sets a budget)."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:
        return None
    if not stats or "bytes_limit" not in stats:
        return None
    return int(stats["bytes_limit"] * 0.94)


def _abstract_step_args(trainer, batch: Dict[str, Any]):
    """(state_structs, batch_structs): every train-step argument as a
    ShapeDtypeStruct carrying this trainer's target sharding — shapes flow
    from ``jax.eval_shape`` over init, so nothing is allocated."""
    import jax
    from jax.sharding import NamedSharding

    from maggy_tpu.train.trainer import _model_inputs

    shardings = trainer.state_shardings_for(batch)
    abstract = jax.eval_shape(
        trainer._init_fn(), jax.random.key(0), *_model_inputs(batch)
    )

    def struct(s, leaf):
        # state leaves may be flax Partitioned boxes around ShapeDtypeStructs
        leaf = leaf.unbox() if hasattr(leaf, "unbox") else leaf
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=s)

    state_structs = jax.tree.map(
        struct, shardings, abstract,
        is_leaf=lambda x: isinstance(x, NamedSharding),
    )
    batch_structs = jax.tree.map(
        lambda leaf, s: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=s),
        batch,
        trainer.batch_shardings(batch),
    )
    return state_structs, batch_structs


def _peak_bytes(mem) -> int:
    """Per-device peak estimate from CompiledMemoryStats: live arguments +
    outputs + XLA temp, minus donated (aliased) buffers counted twice."""
    return int(
        mem.argument_size_in_bytes
        + mem.output_size_in_bytes
        + mem.temp_size_in_bytes
        - mem.alias_size_in_bytes
    )


def analyze_candidate(
    model: Any,
    candidate: Candidate,
    batch: Dict[str, Any],
    *,
    optimizer: Any,
    loss_fn: Optional[Callable] = None,
    budget_bytes: Optional[int] = None,
    devices: Optional[list] = None,
) -> StaticReport:
    """AOT-compile ``candidate``'s train step and read its memory/cost
    analyses. Never executes. Build failures (indivisible batch, invalid
    axis composition, model/mesh mismatch) come back as ``"infeasible"``."""
    global COMPILE_COUNT
    import jax

    from maggy_tpu.parallel.mesh import make_mesh
    from maggy_tpu.train.trainer import Trainer, lm_loss_fn

    devs = devices if devices is not None else jax.devices()
    t0 = time.perf_counter()
    try:
        spec = candidate.spec_for(len(devs))
        mesh = make_mesh(spec, devs)
        candidate_model = apply_remat(model, candidate.remat_policy)
        trainer = Trainer(
            candidate_model,
            optimizer,
            mesh,
            loss_fn=loss_fn or lm_loss_fn,
            n_microbatches=candidate.n_microbatches,
        )
        state_structs, batch_structs = _abstract_step_args(trainer, batch)
        step = trainer._build_train_step()
        with mesh:
            COMPILE_COUNT += 1
            compiled = step.lower(state_structs, batch_structs).compile()
    except Exception as e:  # noqa: BLE001 - infeasible candidate, not a tuner bug
        return StaticReport(
            candidate=candidate,
            status="infeasible",
            reason=f"{type(e).__name__}: {e}",
            compile_ms=(time.perf_counter() - t0) * 1e3,
        )
    compile_ms = (time.perf_counter() - t0) * 1e3

    hbm = flops = bytes_accessed = None
    try:
        hbm = _peak_bytes(compiled.memory_analysis())
    except Exception:  # noqa: BLE001 - backend without memory analysis
        pass
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0)) or None
        bytes_accessed = float(cost.get("bytes accessed", 0.0)) or None
    except Exception:  # noqa: BLE001 - backend without cost analysis
        pass

    if budget_bytes is not None and hbm is not None and hbm > budget_bytes:
        return StaticReport(
            candidate=candidate,
            status="oom",
            reason=f"estimated {hbm} B/device > budget {budget_bytes} B",
            hbm_bytes=hbm,
            flops=flops,
            bytes_accessed=bytes_accessed,
            compile_ms=compile_ms,
        )
    return StaticReport(
        candidate=candidate,
        status="ok",
        hbm_bytes=hbm,
        flops=flops,
        bytes_accessed=bytes_accessed,
        compile_ms=compile_ms,
    )


def static_stage(
    model: Any,
    candidates: List[Candidate],
    batch_fn: Callable[[int], Dict[str, Any]],
    *,
    optimizer: Any,
    loss_fn: Optional[Callable] = None,
    budget_bytes: Optional[int] = None,
    devices: Optional[list] = None,
    log: Optional[Callable[[str], None]] = None,
) -> List[StaticReport]:
    """Analyze every candidate; one report each, same order."""
    reports = []
    for cand in candidates:
        report = analyze_candidate(
            model,
            cand,
            batch_fn(cand.batch_size),
            optimizer=optimizer,
            loss_fn=loss_fn,
            budget_bytes=budget_bytes,
            devices=devices,
        )
        if log is not None:
            detail = report.reason or (
                f"~{(report.hbm_bytes or 0) / 1e6:.1f} MB/device"
            )
            log(f"[tune] static {cand.label}: {report.status} ({detail})")
        reports.append(report)
    return reports
