"""Exception hierarchy.

Mirrors the reference's ``maggy/core/exceptions.py`` surface (core/exceptions.py:22-111)
and adds RPC/scheduling errors that the TPU control plane needs.
"""


class MaggyError(Exception):
    """Base class for all framework errors."""


class EarlyStopException(MaggyError):
    """Raised inside ``reporter.broadcast`` when the driver asked this trial to stop
    (reference core/exceptions.py:22, reporter.py:100-101)."""

    def __init__(self, metric=None):
        super().__init__("Early stop requested by the experiment driver.")
        self.metric = metric


class NotSupportedError(MaggyError):
    """A config value is not supported (reference core/exceptions.py:30)."""

    def __init__(self, category, value, suggestion=""):
        super().__init__(
            f"{category} {value!r} is not supported. {suggestion}".strip()
        )


class ReturnTypeError(MaggyError):
    """train_fn returned something that is neither a number nor a dict with the
    optimization key (reference core/exceptions.py:42)."""

    def __init__(self, optimization_key, return_val):
        super().__init__(
            f"The train_fn return value must be numeric or a dict containing the "
            f"optimization key {optimization_key!r}; got {type(return_val).__name__}: "
            f"{return_val!r}"
        )


class MetricTypeError(MaggyError):
    """The optimization metric inside the returned dict has a bad type
    (reference core/exceptions.py:56)."""

    def __init__(self, optimization_key, metric):
        super().__init__(
            f"The metric {optimization_key!r} must be numeric, got "
            f"{type(metric).__name__}: {metric!r}"
        )


class BroadcastMetricTypeError(MaggyError):
    """reporter.broadcast called with a non-numeric metric (reference core/exceptions.py:69)."""

    def __init__(self, metric):
        super().__init__(
            f"Broadcast metrics must be numeric, got {type(metric).__name__}: {metric!r}"
        )


class BroadcastStepTypeError(MaggyError):
    """reporter.broadcast called with a non-integer step (reference core/exceptions.py:81)."""

    def __init__(self, metric, step):
        super().__init__(
            f"Broadcast step for metric {metric!r} must be an int, got "
            f"{type(step).__name__}: {step!r}"
        )


class BroadcastStepValueError(MaggyError):
    """reporter.broadcast called with a non-monotonic step (reference core/exceptions.py:95)."""

    def __init__(self, metric, step, last_step):
        super().__init__(
            f"Broadcast step must be monotonically increasing: got step {step} after "
            f"{last_step} (metric {metric!r})."
        )


class BadArgumentsError(MaggyError):
    """A function was called with inconsistent arguments (reference core/exceptions.py:111)."""

    def __init__(self, fn_name, detail=""):
        super().__init__(f"Bad arguments for {fn_name}. {detail}".strip())


class RpcError(MaggyError):
    """Control-plane transport failure (connect/auth/framing)."""


class RpcRejectedError(RpcError):
    """The server understood the frame and refused it (ERR reply: bad
    secret, unknown verb, handler-raised validation error). Never retried —
    resending the same message gets the same answer — unlike the transport
    failures its parent covers, which reconnect-and-retry."""


class ServerBusyError(RpcError):
    """429-style admission shed: the serving router projected TTFT past the
    configured SLO (or has no healthy replica) and declined the request
    instead of queueing it. Transient by nature — back off and resubmit
    (``ServeClient.submit(retry_busy=...)`` does it with the rpc jitter)."""


class WorkerLost(MaggyError):
    """The worker hosting in-flight work died out from under it (preemption,
    host loss, chaos kill). A TRANSIENT failure by definition: the runtime
    requeues/restarts the interrupted work instead of failing the experiment
    (resilience/policy.py classify_failure). Executors let this propagate —
    it is a worker death, never a trial error."""


class ReservationTimeoutError(MaggyError):
    """Not all executors registered within the reservation window
    (reference rpc.py:282-303 analogue)."""

    def __init__(self, registered, expected, timeout):
        super().__init__(
            f"Only {registered}/{expected} executors registered within {timeout:.0f}s."
        )


class ExperimentAbortedError(MaggyError):
    """The driver aborted the experiment (worker exception or user interrupt)."""
