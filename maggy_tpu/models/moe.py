"""Mixtral-style sparse Mixture-of-Experts decoder.

Expert parallelism is absent from the reference (§2.10) and required by the
BASELINE Mixtral config. TPU-first design: GShard-style dense dispatch —
top-k routing builds one-hot dispatch/combine tensors with a static per-expert
capacity, expert FFNs are a single batched einsum over parameters laid out
[experts, ...] and sharded on the ``expert`` mesh axis, so XLA inserts the
token all-to-alls and the whole layer stays static-shaped for the MXU.
"""

from __future__ import annotations

import dataclasses
import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from maggy_tpu.models.transformer import (
    REMAT_POLICIES,
    Attention,
    DecoderConfig,
    RMSNorm,
    _dense,
    _parse_ablated,
    _partitioned,
)


@dataclasses.dataclass(frozen=True)
class MoEConfig(DecoderConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    # tokens are routed in fixed-size groups so the dispatch one-hot is
    # O(tokens * group_size), not O(tokens^2) — the GShard group axis
    group_size: int = 512

    @classmethod
    def mixtral_8x7b(cls, **overrides) -> "MoEConfig":
        """Mixtral-8x7B geometry (BASELINE config 5)."""
        return cls(
            **{
                **dict(
                    vocab_size=32_000,
                    d_model=4096,
                    n_layers=32,
                    n_heads=32,
                    n_kv_heads=8,
                    d_ff=14_336,
                    n_experts=8,
                    top_k=2,
                    max_seq_len=8192,
                    remat=True,
                ),
                **overrides,
            }
        )

    @classmethod
    def tiny_moe(cls, **overrides) -> "MoEConfig":
        return cls(
            **{
                **dict(
                    vocab_size=256,
                    d_model=64,
                    n_layers=2,
                    n_heads=4,
                    n_kv_heads=2,
                    d_ff=96,
                    n_experts=4,
                    top_k=2,
                ),
                **overrides,
            }
        )


class MoEBlock(nn.Module):
    """Top-k routed SwiGLU experts with static capacity."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, aux_gate=None):
        cfg = self.cfg
        b, s, d = x.shape
        t = b * s
        e = cfg.n_experts
        g = min(cfg.group_size, t)  # group axis keeps dispatch memory O(t * g)
        n_groups = (t + g - 1) // g
        pad = n_groups * g - t
        capacity = max(
            cfg.top_k,
            int(math.ceil(g / e * cfg.top_k * cfg.capacity_factor)),
        )

        tokens = x.reshape(t, d)
        if pad:
            tokens = jnp.pad(tokens, ((0, pad), (0, 0)))
        grouped = tokens.reshape(n_groups, g, d)

        router_logits = _dense(e, ("embed", None), cfg, "router")(grouped)
        router_probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

        gate_vals, expert_idx = jax.lax.top_k(router_probs, cfg.top_k)  # [n,g,k]
        gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

        # GShard dispatch per group: position of each (token, k) in its expert
        # queue; top-1 assignments win capacity slots over top-2
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)  # [n,g,k,e]
        flat = onehot.transpose(0, 2, 1, 3).reshape(n_groups, cfg.top_k * g, e)
        pos_flat = jnp.cumsum(flat, axis=1) - flat
        pos = pos_flat.reshape(n_groups, cfg.top_k, g, e).transpose(0, 2, 1, 3)
        pos_in_expert = (pos * onehot).sum(-1)  # [n,g,k]
        within = pos_in_expert < capacity

        disp = (
            jax.nn.one_hot(expert_idx, e, dtype=x.dtype)[..., None]
            * jax.nn.one_hot(pos_in_expert, capacity, dtype=x.dtype)[..., None, :]
            * within[..., None, None].astype(x.dtype)
        )  # [n,g,k,e,c]
        combine = (disp * gate_vals[..., None, None].astype(x.dtype)).sum(2)
        dispatch = disp.sum(2)  # [n,g,e,c]

        expert_in = jnp.einsum("ngec,ngd->necd", dispatch, grouped)
        expert_in = expert_in.reshape(n_groups, e, capacity, d)
        # fold groups into the expert batch: experts see [e, n*c, d]
        expert_in = expert_in.transpose(1, 0, 2, 3).reshape(e, n_groups * capacity, d)

        w_gate = self.param(
            "w_gate",
            _partitioned(nn.initializers.normal(0.02), ("expert", "embed", "mlp"), cfg),
            (e, d, cfg.d_ff),
            cfg.param_dtype,
        )
        w_up = self.param(
            "w_up",
            _partitioned(nn.initializers.normal(0.02), ("expert", "embed", "mlp"), cfg),
            (e, d, cfg.d_ff),
            cfg.param_dtype,
        )
        w_down = self.param(
            "w_down",
            _partitioned(nn.initializers.normal(0.02), ("expert", "mlp", "embed"), cfg),
            (e, cfg.d_ff, d),
            cfg.param_dtype,
        )
        w_gate, w_up, w_down = (
            jnp.asarray(w_gate, cfg.dtype),
            jnp.asarray(w_up, cfg.dtype),
            jnp.asarray(w_down, cfg.dtype),
        )
        hidden = nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, w_gate)) * jnp.einsum(
            "ecd,edf->ecf", expert_in, w_up
        )
        expert_out = jnp.einsum("ecf,efd->ecd", hidden, w_down)
        expert_out = expert_out.reshape(e, n_groups, capacity, d).transpose(1, 0, 2, 3)

        y = jnp.einsum("ngec,necd->ngd", combine, expert_out).reshape(-1, d)
        if pad:
            y = y[:t]
        y = y.reshape(b, s, d)

        # load-balancing auxiliary loss (Switch/Mixtral style); a LOCO gate
        # scales it too, so ablated blocks add no balancing gradients
        me = router_probs.reshape(-1, e).mean(0)  # [e] mean router prob
        ce = jax.nn.one_hot(expert_idx[..., 0], e).reshape(-1, e).mean(0)
        aux = (me * ce).sum() * e * cfg.router_aux_weight
        if aux_gate is not None:
            aux = aux * aux_gate.astype(aux.dtype)
        self.sow("intermediates", "router_aux_loss", aux)
        return y


class MoELayer(nn.Module):
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None, gates=None):
        """``gates`` — optional [2] float (attn, moe) LOCO ablation gates,
        same semantics as DecoderLayer (zero gate = identity residual,
        zero grads, unchanged param tree). The gate also scales the sown
        router aux loss — an ablated expert block must not keep pushing
        balancing gradients into its router."""
        a = Attention(self.cfg, name="attn")(
            RMSNorm(self.cfg, name="attn_norm")(x), positions, segment_ids
        )
        x = x + (a if gates is None else a * gates[0].astype(a.dtype))
        m = MoEBlock(self.cfg, name="moe")(
            RMSNorm(self.cfg, name="mlp_norm")(x),
            aux_gate=None if gates is None else gates[1],
        )
        x = x + (m if gates is None else m * gates[1].astype(m.dtype))
        return x


class _ScannedMoELayer(nn.Module):
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        return MoELayer(self.cfg, name="layer")(x, positions, segment_ids), None


class _ScannedGatedMoELayer(nn.Module):
    """Scan body when LOCO gates are active (gates ride in_axes=0)."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, positions, gates, segment_ids=None):
        return MoELayer(self.cfg, name="layer")(
            x, positions, segment_ids, gates
        ), None


class MoEDecoder(nn.Module):
    """Sparse-MoE causal LM; same interface as
    :class:`maggy_tpu.models.transformer.Decoder`."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, tokens, positions=None, segment_ids=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape
            )
        embed = self.param(
            "embedding",
            _partitioned(nn.initializers.normal(1.0), ("vocab", "embed"), cfg),
            (cfg.vocab_size, cfg.d_model),
            cfg.param_dtype,
        )
        x = jnp.asarray(embed, cfg.dtype)[tokens]

        gates = _parse_ablated(cfg.ablated, cfg.n_layers)
        layer_cls = _ScannedMoELayer if gates is None else _ScannedGatedMoELayer
        if cfg.remat and not cfg.decode:  # no gradients (hence no remat) in decode
            layer_cls = nn.remat(
                layer_cls,
                prevent_cse=not cfg.scan_layers,
                policy=REMAT_POLICIES[cfg.remat_policy],
            )
        if cfg.scan_layers:
            scanned = nn.scan(
                layer_cls,
                variable_axes={"params": 0, "intermediates": 0, "cache": 0},
                split_rngs={"params": True},
                in_axes=(
                    (nn.broadcast, nn.broadcast)
                    if gates is None
                    else (nn.broadcast, 0, nn.broadcast)
                ),
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: None},
            )(cfg, name="layers")
            if gates is None:
                x, _ = scanned(x, positions, segment_ids)
            else:
                x, _ = scanned(x, positions, jnp.asarray(gates), segment_ids)
        else:
            for i in range(cfg.n_layers):
                if gates is None:
                    x, _ = layer_cls(cfg, name=f"layers_{i}")(x, positions, segment_ids)
                else:
                    x, _ = layer_cls(cfg, name=f"layers_{i}")(
                        x, positions, jnp.asarray(gates[i]), segment_ids
                    )

        x = RMSNorm(cfg, name="final_norm")(x)
        logits = _dense(cfg.vocab_size, ("embed", "vocab"), cfg, "lm_head")(x)
        return logits.astype(jnp.float32)
