"""MLP classifier (BASELINE config 1: MNIST MLP single-run lagom parity)."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from maggy_tpu.parallel.sharding import logical_partitioning


class MLP(nn.Module):
    features: Sequence[int] = (128, 64)
    num_classes: int = 10
    dtype: Any = jnp.float32
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, width in enumerate(self.features):
            x = nn.Dense(
                width,
                dtype=self.dtype,
                kernel_init=logical_partitioning(
                    nn.initializers.he_normal(), ("embed", "mlp")
                ),
                name=f"dense_{i}",
            )(x)
            x = nn.relu(x)
            if self.dropout:
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(
            self.num_classes,
            dtype=self.dtype,
            kernel_init=logical_partitioning(
                nn.initializers.he_normal(), ("mlp", None)
            ),
            name="head",
        )(x)
