"""Flagship model family: LLaMA-style decoder-only transformer.

TPU-first design (none of this exists in the reference, which orchestrates
user-supplied torch/keras models — §2.10; this model family is what the
BASELINE Llama-3-8B config trains):

* bf16 compute / fp32 params via ``dtype``/``param_dtype`` — MXU-native.
* RMSNorm + RoPE + SwiGLU + grouped-query attention (GQA).
* ``scan_layers=True`` folds the layer stack into one ``nn.scan`` — O(1)
  compile time in depth, the standard XLA-friendly layout.
* ``remat=True`` wraps each layer in ``jax.checkpoint`` to trade FLOPs for HBM.
* Every parameter carries logical axis names (via ``nn.with_partitioning``)
  consumed by :mod:`maggy_tpu.parallel.sharding` — the same module runs
  replicated, FSDP, tensor-parallel, or any mesh combination unchanged.
* ``attention_fn`` hook: defaults to an einsum soft-max attention; the Pallas
  flash/ring kernels in :mod:`maggy_tpu.ops` slot in here for long sequences.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from maggy_tpu.ops import attention as ops_attn

Dtype = Any

# remat policies by name so configs stay JSON-friendly/hashable.
# "dots_attn" = "dots" plus the tensor tagged `checkpoint_name(.., "attn_out")`
# (the attention kernel's output): it trades ~2 bytes/token/layer of HBM for
# not re-running the flash forward in the backward. Measured a wash at S=1024
# on v5e (65.3k vs 66.9k tok/s, within noise) — it becomes the right trade
# when attention dominates (long S with remat still on).
REMAT_POLICIES = {
    "nothing": jax.checkpoint_policies.nothing_saveable,
    "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "dots_attn": jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        jax.checkpoint_policies.save_only_these_names("attn_out"),
    ),
    "everything": jax.checkpoint_policies.everything_saveable,
}


def _parse_ablated(ablated, n_layers: int):
    """Component-name grammar for factory-free LOCO ablation (VERDICT r3
    item 3): "attn" / "mlp" (that sublayer in every layer), "layers.<i>"
    (layer i entirely), "layers.<i>.attn" / "layers.<i>.mlp". Returns a
    [n_layers, 2] float gate array (attn, mlp) or None when nothing is
    ablated. Raises on unknown names so typos never silently train the full
    model."""
    if not ablated:
        return None
    import numpy as np

    gates = np.ones((n_layers, 2), np.float32)
    for comp in sorted(ablated):
        parts = str(comp).split(".")
        ok = True
        if comp == "attn":
            gates[:, 0] = 0.0
        elif comp == "mlp":
            gates[:, 1] = 0.0
        elif parts[0] == "layers" and len(parts) in (2, 3) and parts[1].isdigit():
            i = int(parts[1])
            if not 0 <= i < n_layers:
                raise ValueError(
                    f"Ablated component {comp!r}: layer index out of range "
                    f"(n_layers={n_layers})"
                )
            if len(parts) == 2:
                gates[i] = 0.0
            elif parts[2] == "attn":
                gates[i, 0] = 0.0
            elif parts[2] == "mlp":
                gates[i, 1] = 0.0
            else:
                ok = False
        else:
            ok = False
        if not ok:
            raise ValueError(
                f"Unknown ablated component {comp!r}; expected 'attn', 'mlp', "
                "'layers.<i>', 'layers.<i>.attn' or 'layers.<i>.mlp'"
            )
    return gates


@dataclasses.dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_ff: int = 1376
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = False
    # which intermediates remat keeps: "dots_attn" saves projection/MLP matmul
    # outputs (no-batch-dim dots) plus the attention kernel's output, so the
    # backward recomputes only cheap elementwise work — measured fastest on
    # v5e at every S (BENCH_NOTES round 2; "nothing" costs ~27% at S=1024).
    # "dots" drops the attention output (re-runs the flash forward in the
    # backward); "nothing" recomputes the whole layer (minimum HBM).
    remat_policy: str = "dots_attn"
    logits_softcap: float = 0.0
    tie_embeddings: bool = False
    attention_fn: Optional[Callable] = None
    # decode=True switches attention to the KV-cache incremental path
    # (build via `dataclasses.replace(cfg, decode=True)`; params are identical)
    decode: bool = False
    # paged=True (decode only) stores K/V in a flat pool of `num_pages`
    # fixed-size pages instead of [B, max_seq_len] rows; a per-row page
    # table (cache variable "pages", [B, max_seq_len/page_size] int32,
    # host-managed by the serve engine's block allocator) maps logical
    # positions to physical pages. Decouples batch width from sequence
    # reservation — the enabler for paged serving (docs/serving.md "Paged
    # KV cache"). The dense decode path is unchanged when False.
    paged: bool = False
    page_size: int = 64
    num_pages: int = 0
    # KV-cache read chunk: decode attends over ceil(written/chunk) chunks of
    # the cache instead of all max_seq_len slots — HBM traffic (the decode
    # bottleneck, ~4x off roofline per BENCH_NOTES r1) tracks the ACTUAL
    # prefix length. Rounded down to a divisor of max_seq_len at use
    decode_chunk: int = 256
    # False drops the nn.with_partitioning logical-axis annotations from every
    # param (identical values/tree). Used where params are placed manually —
    # e.g. per-stage modules inside the pipeline shard_map, where flax would
    # otherwise try to resolve logical names against the physical mesh
    partition_params: bool = True
    # components gated to zero for LOCO ablation (param tree unchanged —
    # ablated sublayers contribute nothing and receive zero gradients);
    # grammar in _parse_ablated, usually set via cfg.without(...)
    ablated: Any = frozenset()

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def __post_init__(self):
        if self.d_model % self.n_heads:
            raise ValueError("d_model must be divisible by n_heads")
        if self.n_heads % self.n_kv_heads:
            raise ValueError("n_heads must be divisible by n_kv_heads")
        if self.remat_policy not in REMAT_POLICIES:
            raise ValueError(
                f"remat_policy must be one of {sorted(REMAT_POLICIES)}"
            )
        if self.paged:
            if not self.decode:
                raise ValueError("paged=True requires decode=True")
            p = self.page_size
            if p < 1 or (p & (p - 1)):
                raise ValueError(f"page_size must be a power of two, got {p}")
            if self.max_seq_len % p:
                raise ValueError(
                    f"page_size ({p}) must divide max_seq_len "
                    f"({self.max_seq_len})"
                )
            if self.num_pages < 2:
                raise ValueError(
                    "paged=True needs num_pages >= 2 (page 0 is the "
                    f"reserved scratch page), got {self.num_pages}"
                )
        object.__setattr__(self, "ablated", frozenset(self.ablated))
        _parse_ablated(self.ablated, self.n_layers)  # validate eagerly

    def without(self, components) -> "DecoderConfig":
        """Factory-free model ablation (the flax-idiomatic counterpart of the
        reference's Keras-JSON layer surgery, loco.py:82-136): returns a
        config whose named components are gated out of the forward pass.
        ``components`` is a str or iterable of strs in the
        :func:`_parse_ablated` grammar. Param shapes are unchanged, so
        checkpoints/shardings transfer between variants."""
        if isinstance(components, str):
            components = (components,)
        return dataclasses.replace(
            self, ablated=self.ablated | frozenset(components)
        )

    @classmethod
    def llama3_8b(cls, **overrides) -> "DecoderConfig":
        """Llama-3-8B geometry (BASELINE config 3)."""
        return cls(
            **{
                **dict(
                    vocab_size=128_256,
                    d_model=4096,
                    n_layers=32,
                    n_heads=32,
                    n_kv_heads=8,
                    d_ff=14_336,
                    rope_theta=500_000.0,
                    max_seq_len=8192,
                    remat=True,
                    # 8k-context: minimum-HBM remat (dots would save
                    # ~50KB/token/layer of matmul outputs)
                    remat_policy="nothing",
                ),
                **overrides,
            }
        )

    @classmethod
    def tiny(cls, **overrides) -> "DecoderConfig":
        """Test/debug geometry: fits any host, compiles in seconds."""
        return cls(
            **{
                **dict(
                    vocab_size=256,
                    d_model=64,
                    n_layers=2,
                    n_heads=4,
                    n_kv_heads=2,
                    d_ff=128,
                    max_seq_len=128,
                ),
                **overrides,
            }
        )


def _partitioned(init, logical_axes, cfg):
    # getattr: _dense/RMSNorm are shared by model configs (Bert, MoE, ...)
    # that don't carry the pipeline-only partition_params switch.
    # logical_partitioning (not nn.with_partitioning): the names are LOGICAL
    # axes the trainer's rule tables resolve — flax must never apply them as
    # a raw sharding constraint (parallel/sharding.py LogicalPartitioned)
    if getattr(cfg, "partition_params", True):
        from maggy_tpu.parallel.sharding import logical_partitioning

        return logical_partitioning(init, logical_axes)
    return init


def _dense(features, logical_axes, cfg: DecoderConfig, name: str):
    return nn.DenseGeneral(
        features=features,
        use_bias=False,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=_partitioned(nn.initializers.normal(stddev=0.02), logical_axes, cfg),
        name=name,
    )


class RMSNorm(nn.Module):
    cfg: DecoderConfig

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale",
            _partitioned(nn.initializers.ones_init(), ("norm",), self.cfg),
            (x.shape[-1],),
            self.cfg.param_dtype,
        )
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + self.cfg.norm_eps)
        return (y * scale).astype(self.cfg.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding over the last dim of [B, S, H, D] arrays.

    fp32 internally: sin/cos of large position*inv_freq products lose too much
    precision in bf16.
    """
    half = x.shape[-1] // 2
    freq = jnp.arange(half, dtype=jnp.float32) / half
    inv_freq = theta ** (-freq)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [B, S, half]
    angles = angles[:, :, None, :]  # broadcast over heads
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def auto_attention(q, k, v, *, causal: bool = True, segment_ids=None):
    """Pick the fastest correct kernel for the backend/shape: the Pallas flash
    kernel (fwd+bwd) on TPU when the geometry tiles onto the MXU (head_dim a
    multiple of the 128 lanes, seq a multiple of the 128 block), otherwise the
    XLA dense path. With the auto-tuned MXU-sized blocks (ops/flash.py
    ``_auto_blocks``: 512-row q tiles) the kernel wins the full train step at
    every measured length — 66.9k vs 60.7k tok/s at S=1024 and 44.0k vs 22.8k
    at S=8192 against the dense path on v5e (BENCH_NOTES round 2; the old
    128x128 blocks LOST to dense everywhere, so block size is the whole
    game). On a multi-device mesh the kernel runs per-shard under shard_map
    (a pallas_call has no GSPMD partitioning rule); incompatible layouts
    (sp/pp axes, non-divisible batch/heads) fall back to the XLA path."""
    from maggy_tpu.ops.flash import (  # late: avoid import cycle
        flash_attention,
        sharded_flash_attention,
    )
    from maggy_tpu.parallel.mesh import ambient_mesh

    b, sq, h, d = q.shape
    sk = k.shape[1]
    if (
        jax.default_backend() == "tpu"
        and d % 128 == 0
        and sq % 128 == 0
        and sk % 128 == 0
    ):
        mesh = ambient_mesh()
        if mesh is None or mesh.size == 1:
            return flash_attention(q, k, v, causal=causal, segment_ids=segment_ids)
        out = sharded_flash_attention(
            q, k, v, mesh=mesh, causal=causal, segment_ids=segment_ids
        )
        if out is not None:
            return out
    return default_attention(q, k, v, causal=causal, segment_ids=segment_ids)


def default_attention(q, k, v, *, causal: bool = True, segment_ids=None):
    """Reference soft-max attention: q [B,S,H,D], k/v [B,S,Kh,D] with GQA
    head-group broadcast. fp32 logits/softmax for stability."""
    b, sq, h, d = q.shape
    kh = k.shape[2]
    group = h // kh
    q = q.reshape(b, sq, kh, group, d)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(d).astype(jnp.float32)
    if causal:
        sk = k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool))
        logits = jnp.where(mask[None, None, None], logits, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, None, None, :, None] == segment_ids[:, None, None, None, :]
        logits = jnp.where(seg_mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v)
    return out.reshape(b, sq, h, d)


class Attention(nn.Module):
    cfg: DecoderConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        hd = cfg.head_dim
        q = _dense((cfg.n_heads, hd), ("embed", "heads", None), cfg, "wq")(x)
        k = _dense((cfg.n_kv_heads, hd), ("embed", "kv", None), cfg, "wk")(x)
        v = _dense((cfg.n_kv_heads, hd), ("embed", "kv", None), cfg, "wv")(x)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        if cfg.decode:
            out = self._cached_attention(q, k, v, positions, segment_ids)
        else:
            attn = cfg.attention_fn or auto_attention
            out = attn(q, k, v, causal=True, segment_ids=segment_ids)
            # under remat="dots_attn" this tag saves the kernel output so the
            # backward reads it instead of re-running the flash forward
            # (plain "dots" ignores the tag and recomputes)
            from jax.ad_checkpoint import checkpoint_name

            out = checkpoint_name(out, "attn_out")
        out = nn.DenseGeneral(
            features=cfg.d_model,
            axis=(-2, -1),
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=_partitioned(
                nn.initializers.normal(stddev=0.02), ("heads", None, "embed"), cfg
            ),
            name="wo",
        )(out)
        return out

    def _cached_attention(self, q, k, v, positions, segment_ids=None):
        """Incremental decoding: append this chunk's K/V to a cache of
        ``max_seq_len`` and attend the chunk's queries over everything cached
        so far (the KV-cache path the recompute-based generate() lacks).

        Length-adaptive reads (VERDICT r3 item 7): the cache is consumed in
        ``decode_chunk``-sized blocks under a dynamic-trip-count loop that
        stops after the last WRITTEN chunk, so per-step HBM traffic — the
        decode bottleneck — is proportional to the actual prefix, not
        ``max_seq_len``. Online-softmax across chunks (same recurrence as
        ops.attention) keeps the math exact.

        Packed batches (VERDICT r4 item 4): with ``segment_ids`` a packed
        prompt prefills in ONE pass — the ids are cached alongside K/V and
        every read is masked to the query's segment, so segments cannot
        attend across their boundaries. Later single-token steps may omit
        ``segment_ids``; once the ``seg`` track exists the new token extends
        the row's most recent segment. Unpacked flows never create the track
        and keep the exact original compute.

        The write index is PER ROW (``[B]`` int32, not a scalar): each batch
        row carries its own cache length, so rows may sit at different
        sequence positions — the enabler for slot-based continuous batching
        (maggy_tpu/serve), where one compiled step decodes requests admitted
        at different times. Lockstep callers (generate_cached, prefill) keep
        identical values in every row and reproduce the old scalar
        semantics exactly.

        Paged mode (``cfg.paged``; docs/serving.md "Paged KV cache")
        replaces the ``[B, max_seq_len]`` row reservation with a flat page
        pool plus per-row page-table indirection — same math, same masks,
        storage decoupled from batch width. The packed ``segment_ids``
        track is a dense-path feature (the serve engine never packs)."""
        cfg = self.cfg
        if cfg.paged:
            if segment_ids is not None or self.has_variable("cache", "seg"):
                raise NotImplementedError(
                    "paged decode does not support packed segment_ids"
                )
            return self._paged_cached_attention(q, k, v, positions)
        b, t, kh, hd = k.shape
        k_cache = self.variable(
            "cache", "k",
            lambda: jnp.zeros((b, cfg.max_seq_len, kh, hd), cfg.dtype),
        )
        v_cache = self.variable(
            "cache", "v",
            lambda: jnp.zeros((b, cfg.max_seq_len, kh, hd), cfg.dtype),
        )
        index = self.variable(
            "cache", "index", lambda: jnp.zeros((b,), jnp.int32)
        )
        idx = index.value  # [B] per-row write offsets

        def _row_write(cache_row, update_row, start):
            return jax.lax.dynamic_update_slice(
                cache_row, update_row, (start, 0, 0)
            )

        k_all = jax.vmap(_row_write)(k_cache.value, k.astype(cfg.dtype), idx)
        v_all = jax.vmap(_row_write)(v_cache.value, v.astype(cfg.dtype), idx)
        k_cache.value = k_all
        v_cache.value = v_all
        index.value = idx + t

        # packed-segment track: static trace-time decision (flax variable
        # presence), so unpacked decode pays nothing
        seg_all = seg_q = None
        if segment_ids is not None or self.has_variable("cache", "seg"):
            seg_cache = self.variable(
                "cache", "seg",
                lambda: jnp.zeros((b, cfg.max_seq_len), jnp.int32),
            )
            if segment_ids is None:
                # continuation: the new token(s) extend the most recent
                # segment written for the row
                last = jax.vmap(
                    lambda row, i: jax.lax.dynamic_slice_in_dim(
                        row, jnp.maximum(i - 1, 0), 1
                    )
                )(seg_cache.value, idx)
                seg_q = jnp.broadcast_to(last, (b, t))
            else:
                seg_q = segment_ids.astype(jnp.int32)
            seg_all = jax.vmap(
                lambda row, upd, i: jax.lax.dynamic_update_slice(row, upd, (i,))
            )(seg_cache.value, seg_q, idx)
            seg_cache.value = seg_all

        S = cfg.max_seq_len
        chunk = min(cfg.decode_chunk, S)
        while S % chunk:  # dynamic_slice must never clamp past the end
            chunk //= 2
        if chunk < 16:
            chunk = S  # pathological lengths: one full-cache chunk
        h = q.shape[2]
        scale = 1.0 / (hd**0.5)
        written = idx + t  # [B] per-row cache lengths after this write
        # chunks covering the LONGEST row's prefix (the loop bound must be a
        # scalar; shorter rows mask out the excess), clamped so the final
        # dynamic_slice can never be position-shifted by end-clamping
        # (over-long prompt buffers)
        n_valid = jnp.minimum(
            (jnp.max(written) + chunk - 1) // chunk, S // chunk
        )

        # a query's own write location in the cache; for packed rows this is
        # the causal clock (``positions`` restart per segment there, so they
        # cannot order keys across the whole cache)
        qslot = idx[:, None] + jnp.arange(t)[None, :]  # [B, t]

        def body(ci, carry):
            k_c = jax.lax.dynamic_slice_in_dim(k_all, ci * chunk, chunk, axis=1)
            v_c = jax.lax.dynamic_slice_in_dim(v_all, ci * chunk, chunk, axis=1)
            kpos = ci * chunk + jnp.arange(chunk)
            w_row = written[:, None, None, None]  # per-row valid-key bound
            if seg_all is None:
                # causal over the cache: a query at position p sees keys at
                # <= p that have actually been written (positions == cache
                # slots on this path)
                mask = (
                    kpos[None, None, None, :] <= positions[:, None, :, None]
                ) & (kpos[None, None, None, :] < w_row)
            else:
                # packed: causal in CACHE ORDER (packing preserves a row's
                # temporal order) and restricted to the query's own segment
                seg_c = jax.lax.dynamic_slice_in_dim(
                    seg_all, ci * chunk, chunk, axis=1
                )
                mask = (
                    (kpos[None, None, None, :] <= qslot[:, None, :, None])
                    & (kpos[None, None, None, :] < w_row)
                    & (seg_c[:, None, None, :] == seg_q[:, None, :, None])
                )
            return ops_attn.online_block_update(
                carry,
                q,
                ops_attn.repeat_kv(k_c, h),
                ops_attn.repeat_kv(v_c, h),
                mask,
                scale,
            )

        carry = ops_attn.init_carry(b, h, t, hd)
        acc, _, l = jax.lax.fori_loop(0, n_valid, body, carry)
        return ops_attn.finalize(acc, l, q.dtype)

    def _paged_cached_attention(self, q, k, v, positions):
        """Paged KV cache: K/V live in a flat pool of ``num_pages`` pages of
        ``page_size`` slots (``[N, P, Kh, Dh]``) and each batch row maps its
        logical positions to physical pages through a ``[B, max_seq_len/P]``
        int32 page-table row — the vLLM/Pallas paged-attention layout
        expressed at the XLA level. The table is a cache variable this
        module only READS; the serve engine's host-side block allocator
        owns it (allocation, prefix aliasing, release all happen by editing
        table rows, never by moving K/V bytes).

        Writes scatter each new token to ``(table[b, pos // P], pos % P)``.
        A released/inactive row's table is zeroed and its index clamped, so
        masked lockstep writes land on the reserved scratch page 0 —
        garbage by design, never read as valid.

        Reads run the SAME chunked online-softmax loop as the dense path,
        except each chunk is materialized by gathering ``chunk/P`` pages
        into a contiguous block (one gather per chunk — the XLA analogue of
        the paged-attention kernel's per-page DMA batch) instead of a
        contiguous ``dynamic_slice``. Chunk token count, masks and update
        order are identical to the dense path whenever ``page_size``
        divides the effective chunk, so paged decode output is
        BIT-identical to dense decode — the byte-parity contract
        tests/test_paged_kv.py enforces."""
        cfg = self.cfg
        b, t, kh, hd = k.shape
        P = cfg.page_size
        S = cfg.max_seq_len
        max_pages = S // P
        k_pool = self.variable(
            "cache", "k",
            lambda: jnp.zeros((cfg.num_pages, P, kh, hd), cfg.dtype),
        )
        v_pool = self.variable(
            "cache", "v",
            lambda: jnp.zeros((cfg.num_pages, P, kh, hd), cfg.dtype),
        )
        pages = self.variable(
            "cache", "pages", lambda: jnp.zeros((b, max_pages), jnp.int32)
        )
        index = self.variable(
            "cache", "index", lambda: jnp.zeros((b,), jnp.int32)
        )
        idx = index.value  # [B] per-row write offsets (logical positions)
        pt = pages.value  # [B, max_pages] logical page -> physical page

        # scatter this chunk's K/V through the page table: token j of row b
        # lands at (pt[b, (idx+j)//P], (idx+j)%P). Distinct live rows own
        # distinct pages, so scatter indices never collide except on the
        # scratch page (masked rows), whose content is garbage by contract.
        pos_w = idx[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
        page_slot = jnp.clip(pos_w // P, 0, max_pages - 1)
        phys = jnp.take_along_axis(pt, page_slot, axis=1)  # [B, t]
        off = pos_w % P
        k_all = k_pool.value.at[phys, off].set(k.astype(cfg.dtype))
        v_all = v_pool.value.at[phys, off].set(v.astype(cfg.dtype))
        k_pool.value = k_all
        v_pool.value = v_all
        index.value = idx + t

        # identical chunk geometry to the dense path (bit parity): token
        # chunks of the dense size, materialized as cpp-page gathers
        chunk = min(cfg.decode_chunk, S)
        while S % chunk:
            chunk //= 2
        if chunk < 16:
            chunk = S
        cpp = max(1, chunk // P)  # pages per chunk
        tok_chunk = cpp * P
        n_chunks = max_pages // cpp
        h = q.shape[2]
        scale = 1.0 / (hd**0.5)
        written = idx + t  # [B] per-row logical lengths after this write
        n_valid = jnp.minimum(
            (jnp.max(written) + tok_chunk - 1) // tok_chunk, n_chunks
        )

        def body(ci, carry):
            pt_c = jax.lax.dynamic_slice(
                pt, (jnp.int32(0), ci * cpp), (b, cpp)
            )  # [B, cpp] physical page ids for this chunk
            k_c = k_all[pt_c].reshape(b, tok_chunk, kh, hd)
            v_c = v_all[pt_c].reshape(b, tok_chunk, kh, hd)
            kpos = ci * tok_chunk + jnp.arange(tok_chunk)
            w_row = written[:, None, None, None]  # per-row valid-key bound
            # causal over logical positions + written bound: exactly the
            # dense unpacked mask (unallocated table entries point at the
            # scratch page; their kpos >= written, so they are masked)
            mask = (
                kpos[None, None, None, :] <= positions[:, None, :, None]
            ) & (kpos[None, None, None, :] < w_row)
            return ops_attn.online_block_update(
                carry,
                q,
                ops_attn.repeat_kv(k_c, h),
                ops_attn.repeat_kv(v_c, h),
                mask,
                scale,
            )

        carry = ops_attn.init_carry(b, h, t, hd)
        acc, _, l = jax.lax.fori_loop(0, n_valid, body, carry)
        return ops_attn.finalize(acc, l, q.dtype)


class MLPBlock(nn.Module):
    cfg: DecoderConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gate = _dense(cfg.d_ff, ("embed", "mlp"), cfg, "w_gate")(x)
        up = _dense(cfg.d_ff, ("embed", "mlp"), cfg, "w_up")(x)
        return _dense(cfg.d_model, ("mlp", "embed"), cfg, "w_down")(
            nn.silu(gate) * up
        )


def _constrain_residual(x):
    """Pin the residual stream's layout: batch over (data, fsdp), seq over sp,
    embed replicated. Settled behavior: every DecoderLayer exit re-asserts
    this one canonical placement, because on deep tp/fsdp/sp meshes GSPMD
    propagation from the tensor-sharded projections can otherwise drift the
    residual into an embed-sharded (or gathered) layout mid-stack and pay an
    all-gather per layer. The embed dim stays deliberately REPLICATED — a
    per-layer reduce-scatter/all-gather pair costs more than it saves at the
    d_models this family targets — and inside manual (shard_map) regions the
    constraint is a no-op by construction (constrain_activation degrades
    there), so the pipeline stage adapter composes with it unchanged."""
    from maggy_tpu.parallel.sharding import constrain_activation

    return constrain_activation(x, ("batch", "activation_seq", None))


class DecoderLayer(nn.Module):
    cfg: DecoderConfig

    @nn.compact
    def __call__(self, x, positions, gates=None, segment_ids=None):
        """``gates`` — optional [2] float (attn, mlp) LOCO ablation gates: a
        zero gate removes that sublayer's contribution (residual becomes
        identity) and cuts its gradients, with an unchanged param tree.
        ``segment_ids`` — optional [B, S] packed-sequence ids."""
        a = Attention(self.cfg, name="attn")(
            RMSNorm(self.cfg, name="attn_norm")(x), positions, segment_ids
        )
        x = x + (a if gates is None else a * gates[0].astype(a.dtype))
        m = MLPBlock(self.cfg, name="mlp")(RMSNorm(self.cfg, name="mlp_norm")(x))
        x = x + (m if gates is None else m * gates[1].astype(m.dtype))
        return _constrain_residual(x)


class _ScannedLayer(nn.Module):
    cfg: DecoderConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        return DecoderLayer(self.cfg, name="layer")(
            x, positions, None, segment_ids
        ), None


class _ScannedGatedLayer(nn.Module):
    """Scan body when LOCO gates are active: gates ride the scan's in_axes=0
    so each layer sees its own (attn, mlp) pair."""

    cfg: DecoderConfig

    @nn.compact
    def __call__(self, x, positions, gates, segment_ids=None):
        return DecoderLayer(self.cfg, name="layer")(
            x, positions, gates, segment_ids
        ), None


class Decoder(nn.Module):
    """LLaMA-style causal LM. ``__call__(tokens [B,S]) -> logits [B,S,V]``."""

    cfg: DecoderConfig

    @nn.compact
    def __call__(self, tokens, positions=None, segment_ids=None):
        """``positions`` default to per-row arange; packed batches pass both
        ``positions`` (restarting per segment) and ``segment_ids`` [B, S]
        (attention masks across segment boundaries, SURVEY §5.7)."""
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape
            )
        embed = self.param(
            "embedding",
            _partitioned(
                nn.initializers.normal(stddev=1.0), ("vocab", "embed"), cfg
            ),
            (cfg.vocab_size, cfg.d_model),
            cfg.param_dtype,
        )
        x = _constrain_residual(jnp.asarray(embed, cfg.dtype)[tokens])

        gates = _parse_ablated(cfg.ablated, cfg.n_layers)
        layer_cls = _ScannedLayer if gates is None else _ScannedGatedLayer
        if cfg.remat and not cfg.decode:  # no gradients (hence no remat) in decode
            layer_cls = nn.remat(
                layer_cls,
                prevent_cse=not cfg.scan_layers,
                policy=REMAT_POLICIES[cfg.remat_policy],
            )
        if cfg.scan_layers:
            scanned = nn.scan(
                layer_cls,
                variable_axes={"params": 0, "cache": 0},
                split_rngs={"params": True},
                # positions/segment_ids are the same for every layer; LOCO
                # gates are per-layer
                in_axes=(
                    (nn.broadcast, nn.broadcast)
                    if gates is None
                    else (nn.broadcast, 0, nn.broadcast)
                ),
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: None},
            )(cfg, name="layers")
            if gates is None:
                x, _ = scanned(x, positions, segment_ids)
            else:
                x, _ = scanned(x, positions, jnp.asarray(gates), segment_ids)
        else:
            for i in range(cfg.n_layers):
                if gates is None:
                    x, _ = layer_cls(cfg, name=f"layers_{i}")(
                        x, positions, segment_ids
                    )
                else:
                    x, _ = layer_cls(cfg, name=f"layers_{i}")(
                        x, positions, jnp.asarray(gates[i]), segment_ids
                    )

        x = RMSNorm(cfg, name="final_norm")(x)
        if cfg.tie_embeddings:
            logits = jnp.einsum("bsd,vd->bsv", x, jnp.asarray(embed, cfg.dtype))
        else:
            logits = _dense(cfg.vocab_size, ("embed", "vocab"), cfg, "lm_head")(x)
        if cfg.logits_softcap:
            logits = jnp.tanh(logits / cfg.logits_softcap) * cfg.logits_softcap
        return logits.astype(jnp.float32)
