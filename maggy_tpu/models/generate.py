"""Autoregressive generation for the decoder families.

Static-shape, jit-friendly sampling: the token buffer is padded to
``max_len`` and a ``lax.fori_loop`` fills one position per step, so XLA
compiles a single program regardless of prompt/output lengths. Each step
recomputes the full prefix (no KV cache yet — O(L·S²) compute, fine for
evaluation-sized models; a cache-backed decode path is the planned
optimization). Greedy (``temperature=0``) or temperature sampling with
optional top-k.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


@functools.partial(
    jax.jit,
    static_argnames=("model", "temperature", "top_k", "eos_id"),
)
def generate(
    model,
    variables,
    prompt: jax.Array,
    prompt_len: jax.Array,
    *,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    eos_id: int = -1,
) -> jax.Array:
    """Fill the buffer after each row's prompt with sampled continuations.

    :param prompt: int32 [B, max_len] buffer — prompt tokens left-aligned,
        tail arbitrary (overwritten).
    :param prompt_len: int32 [B] true prompt lengths (>= 1).
    :param rng: PRNG key for temperature sampling. Defaults to a FIXED
        ``jax.random.key(0)`` — repeated calls return identical samples; pass
        a fresh key per call for diverse samples.
    :returns: int32 [B, max_len]; after a row hits ``eos_id`` it repeats it.
    """
    max_len = prompt.shape[1]
    if rng is None:
        rng = jax.random.key(0)

    def step(p, carry):
        tokens, rng, done = carry
        logits = model.apply(variables, tokens)  # [B, max_len, V]
        last = jax.lax.dynamic_index_in_dim(logits, p, axis=1, keepdims=False)
        if temperature <= 0.0:
            nxt = jnp.argmax(last, axis=-1)
        else:
            scaled = last / temperature
            if top_k > 0:
                kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
                scaled = jnp.where(scaled < kth, -1e30, scaled)
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, scaled, axis=-1)
        nxt = nxt.astype(tokens.dtype)
        # position p+1 gets a generated token only once the prompt is consumed
        generating = (p + 1) >= prompt_len  # [B]
        if eos_id >= 0:
            nxt = jnp.where(done, jnp.asarray(eos_id, tokens.dtype), nxt)
            # discarded mid-prompt predictions must not latch the done flag
            done = done | (generating & (nxt == eos_id))
        current = jax.lax.dynamic_index_in_dim(tokens, p + 1, axis=1, keepdims=False)
        new_col = jnp.where(generating, nxt, current)
        tokens = jax.lax.dynamic_update_index_in_dim(tokens, new_col, p + 1, axis=1)
        return tokens, rng, done

    done0 = jnp.zeros((prompt.shape[0],), dtype=bool)
    tokens, _, _ = jax.lax.fori_loop(0, max_len - 1, step, (prompt, rng, done0))
    return tokens
