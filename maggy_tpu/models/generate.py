"""Autoregressive generation for the decoder families.

Static-shape, jit-friendly sampling: the token buffer is padded to
``max_len`` and a ``lax.fori_loop`` fills one position per step, so XLA
compiles a single program regardless of prompt/output lengths. Two paths:

* :func:`generate` — recomputes the full prefix each step (O(L·S²) compute,
  zero model requirements); fine for evaluation-sized models.
* :func:`generate_cached` — KV-cache incremental decode (O(L·S·d) per token)
  against a ``DecoderConfig(decode=True)`` model; same trained params.

Greedy (``temperature=0``) or temperature sampling with optional top-k.
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional

import jax
import jax.numpy as jnp


def _default_rng(temperature: float, where: str) -> jax.Array:
    """The documented-but-silent footgun: sampling (``temperature > 0``) with
    the default ``jax.random.key(0)`` returns IDENTICAL tokens on every call.
    Warn when it actually bites (the check runs at trace time, so it fires
    once per compiled variant, not per step); greedy decode stays silent —
    the fixed key is never consumed there. The serving engine
    (maggy_tpu/serve) threads a fresh per-request key instead."""
    if temperature > 0.0:
        warnings.warn(
            f"{where}: temperature sampling with the fixed default PRNG key "
            "(jax.random.key(0)) — repeated calls return identical samples; "
            "pass rng=jax.random.key(<fresh seed>) per call",
            UserWarning,
            stacklevel=3,
        )
    return jax.random.key(0)


@functools.partial(
    jax.jit,
    static_argnames=("model", "temperature", "top_k", "eos_id"),
)
def generate(
    model,
    variables,
    prompt: jax.Array,
    prompt_len: jax.Array,
    *,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    eos_id: int = -1,
) -> jax.Array:
    """Fill the buffer after each row's prompt with sampled continuations.

    :param prompt: int32 [B, max_len] buffer — prompt tokens left-aligned,
        tail arbitrary (overwritten).
    :param prompt_len: int32 [B] true prompt lengths (>= 1).
    :param rng: PRNG key for temperature sampling. Defaults to a FIXED
        ``jax.random.key(0)`` — repeated calls return identical samples; pass
        a fresh key per call for diverse samples.
    :returns: int32 [B, max_len]; after a row hits ``eos_id`` it repeats it.
    """
    max_len = prompt.shape[1]
    if rng is None:
        rng = _default_rng(temperature, "generate")

    def step(p, carry):
        tokens, rng, done = carry
        logits = model.apply(variables, tokens)  # [B, max_len, V]
        last = jax.lax.dynamic_index_in_dim(logits, p, axis=1, keepdims=False)
        nxt, rng = _sample(last, rng, temperature, top_k)
        nxt = nxt.astype(tokens.dtype)
        # position p+1 gets a generated token only once the prompt is consumed
        generating = (p + 1) >= prompt_len  # [B]
        if eos_id >= 0:
            nxt = jnp.where(done, jnp.asarray(eos_id, tokens.dtype), nxt)
            # discarded mid-prompt predictions must not latch the done flag
            done = done | (generating & (nxt == eos_id))
        current = jax.lax.dynamic_index_in_dim(tokens, p + 1, axis=1, keepdims=False)
        new_col = jnp.where(generating, nxt, current)
        tokens = jax.lax.dynamic_update_index_in_dim(tokens, new_col, p + 1, axis=1)
        return tokens, rng, done

    done0 = jnp.zeros((prompt.shape[0],), dtype=bool)
    tokens, _, _ = jax.lax.fori_loop(0, max_len - 1, step, (prompt, rng, done0))
    return tokens


def _sample(last, rng, temperature: float, top_k: int):
    if temperature <= 0.0:
        return jnp.argmax(last, axis=-1), rng
    scaled = last / temperature
    if top_k > 0:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled < kth, -1e30, scaled)
    rng, sub = jax.random.split(rng)
    return jax.random.categorical(sub, scaled, axis=-1), rng


def cache_shardings(mesh, abstract_cache, rules=None, paged: bool = False):
    """NamedShardings for a decode KV cache: batch over (data, fsdp), KV heads
    over tensor when divisible — so tensor-parallel decode holds 1/tp of each
    cache instead of a full replica (round-1 verdict weak #7). Cache leaves
    are ``[..., B, S, Kh, Dh]`` (a leading layer axis when scanned); anything
    smaller (the write index) replicates.

    ``paged=True`` (``DecoderConfig.paged`` caches): K/V leaves are page
    pools ``[..., N, P, Kh, Dh]`` with NO batch axis — any row may gather
    any page, so the page axis must stay whole per shard; only the KV-head
    axis shards (tensor). The page table ``[..., B, max_pages]`` is tiny
    and read by every shard — replicated like the index.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from maggy_tpu.parallel import sharding as shd
    from maggy_tpu.parallel.spec import AXIS_TENSOR

    rules = rules or shd.DEFAULT_RULES
    batch_axes = shd.logical_to_mesh_axes(("batch",), rules)[0]
    tp = mesh.shape[AXIS_TENSOR]

    def leaf(path, s):
        # the per-row write index [(L,) B] is tiny and read by every shard —
        # replicate (it would otherwise pattern-match the seg-track branch)
        ks = jax.tree_util.keystr(path)
        if "index" in ks or "pages" in ks:
            return NamedSharding(mesh, PartitionSpec())
        if s.ndim >= 4:
            kv = AXIS_TENSOR if (tp > 1 and s.shape[-2] % tp == 0) else None
            lead = (None,) * (s.ndim - 4)
            first = None if paged else batch_axes
            return NamedSharding(
                mesh, PartitionSpec(*lead, first, None, kv, None)
            )
        if s.ndim >= 2:
            # the packed segment-id track [(L,) B, S]: batch-sharded like K/V
            lead = (None,) * (s.ndim - 2)
            return NamedSharding(mesh, PartitionSpec(*lead, batch_axes, None))
        return NamedSharding(mesh, PartitionSpec())

    return jax.tree_util.tree_map_with_path(leaf, abstract_cache)


def init_cache(decode_model, prompt: jax.Array, mesh=None, rules=None,
               packed: bool = False):
    """Create the zeroed KV cache for a ``DecoderConfig(decode=True)`` model.

    ``eval_shape`` gives the cache structure without running the model — an
    actual ``init`` would execute the decode forward pass, writing throwaway
    K/V into slot 0 and advancing the index, corrupting every later write.

    With ``mesh``, every cache leaf is born sharded per
    :func:`cache_shardings` (never materialized replicated on one device).
    ``packed=True`` includes the segment-id track packed prefill caches
    alongside K/V (models/transformer.py ``_cached_attention``).
    """
    dummy_pos = jnp.zeros((prompt.shape[0], 1), jnp.int32)
    args = (prompt[:, :1], dummy_pos)
    if packed:
        args += (jnp.zeros((prompt.shape[0], 1), jnp.int32),)
    abstract = jax.eval_shape(
        decode_model.init, jax.random.key(0), *args
    )["cache"]
    if mesh is None:
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), abstract)
    shardings = cache_shardings(
        mesh, abstract, rules, paged=getattr(decode_model.cfg, "paged", False)
    )
    zeros = jax.jit(
        lambda: jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), abstract),
        out_shardings=shardings,
    )
    with mesh:
        return zeros()


def prefill(decode_model, params, tokens, positions, segment_ids=None,
            cache=None, mesh=None):
    """ONE-pass cache fill: run the whole prompt — packed or plain — through
    the ``decode=True`` model at once (t = prompt length), writing every
    K/V (+ segment id) cache slot in a single forward instead of one apply
    per token. Returns ``(logits [B, T, V], cache)``; feed the cache to
    further single-token applies or :func:`generate_cached_packed`.
    (VERDICT r4 item 4 — the reference has no decode path at all.)"""
    if cache is None:
        cache = init_cache(
            decode_model, tokens, mesh=mesh, packed=segment_ids is not None
        )
    args = (tokens, positions) + (
        (segment_ids,) if segment_ids is not None else ()
    )
    logits, mutated = decode_model.apply(
        {"params": params, "cache": cache}, *args, mutable=["cache"]
    )
    return logits, mutated["cache"]


@functools.partial(
    jax.jit,
    static_argnames=("decode_model", "max_new", "temperature", "top_k", "eos_id"),
)
def generate_cached_packed(
    decode_model,
    params,
    prompt: jax.Array,
    positions: jax.Array,
    segment_ids: jax.Array,
    *,
    max_new: int,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    eos_id: int = -1,
):
    """Packed serving: one :func:`prefill` pass over a FULLY-packed prompt
    buffer ``[B, T]`` (every slot belongs to a segment; ``positions``
    restart per segment), then ``max_new`` cached single-token steps
    continuing each row's LAST segment — earlier segments are context-
    isolated by the cache's segment mask exactly as they were during
    training-time packing.

    :returns: ``(prefill_logits [B, T, V], new_tokens [B, max_new])``.
    """
    b, T = prompt.shape
    max_seq = decode_model.cfg.max_seq_len
    if T + max_new > max_seq:
        raise ValueError(
            f"prompt ({T}) + max_new ({max_new}) exceeds the cache's "
            f"max_seq_len ({max_seq})"
        )
    if rng is None:
        rng = _default_rng(temperature, "generate_cached_packed")
    logits, cache = prefill(decode_model, params, prompt, positions, segment_ids)
    last_pos = positions[:, -1]
    last_seg = segment_ids[:, -1]

    def step(i, carry):
        tokens, cache, rng, done, cur_logits = carry
        nxt, rng = _sample(cur_logits, rng, temperature, top_k)
        nxt = nxt.astype(prompt.dtype)
        if eos_id >= 0:
            nxt = jnp.where(done, jnp.asarray(eos_id, prompt.dtype), nxt)
            done = done | (nxt == eos_id)
        tokens = jax.lax.dynamic_update_index_in_dim(tokens, nxt, i, axis=1)
        pos = (last_pos + 1 + i)[:, None]
        lg, mutated = decode_model.apply(
            {"params": params, "cache": cache},
            nxt[:, None], pos, last_seg[:, None], mutable=["cache"],
        )
        return tokens, mutated["cache"], rng, done, lg[:, 0]

    tokens0 = jnp.zeros((b, max_new), prompt.dtype)
    done0 = jnp.zeros((b,), dtype=bool)
    tokens, _, _, _, _ = jax.lax.fori_loop(
        0, max_new, step, (tokens0, cache, rng, done0, logits[:, -1])
    )
    return logits, tokens


@functools.partial(
    jax.jit,
    static_argnames=("decode_model", "temperature", "top_k", "eos_id"),
)
def generate_cached(
    decode_model,
    params,
    prompt: jax.Array,
    prompt_len: jax.Array,
    *,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    eos_id: int = -1,
) -> jax.Array:
    """KV-cache incremental generation: one token of compute per step
    (O(L·S·d) instead of :func:`generate`'s O(L·S²·d) prefix recompute).

    ``decode_model`` must be built with ``dataclasses.replace(cfg,
    decode=True)``; ``params`` are the trained (non-decode) params — the tree
    is identical. Same sampling semantics as :func:`generate`.
    """
    b, max_len = prompt.shape
    if rng is None:
        rng = _default_rng(temperature, "generate_cached")
    cache = init_cache(decode_model, prompt)

    def step(p, carry):
        tokens, cache, rng, done = carry
        x_t = jax.lax.dynamic_slice_in_dim(tokens, p, 1, axis=1)  # [B, 1]
        pos = jnp.full((b, 1), p, jnp.int32)
        logits, mutated = decode_model.apply(
            {"params": params, "cache": cache}, x_t, pos, mutable=["cache"]
        )
        cache = mutated["cache"]
        nxt, rng = _sample(logits[:, 0], rng, temperature, top_k)
        nxt = nxt.astype(tokens.dtype)
        generating = (p + 1) >= prompt_len
        if eos_id >= 0:
            nxt = jnp.where(done, jnp.asarray(eos_id, tokens.dtype), nxt)
            done = done | (generating & (nxt == eos_id))
        current = jax.lax.dynamic_index_in_dim(tokens, p + 1, axis=1, keepdims=False)
        new_col = jnp.where(generating, nxt, current)
        tokens = jax.lax.dynamic_update_index_in_dim(tokens, new_col, p + 1, axis=1)
        return tokens, cache, rng, done

    done0 = jnp.zeros((b,), dtype=bool)
    tokens, _, _, _ = jax.lax.fori_loop(
        0, max_len - 1, step, (prompt, cache, rng, done0)
    )
    return tokens
