from maggy_tpu.models.mlp import MLP
from maggy_tpu.models.transformer import Decoder, DecoderConfig

__all__ = ["MLP", "Decoder", "DecoderConfig"]


def __getattr__(name):
    import importlib

    lazy = {
        "ResNet": "maggy_tpu.models.cnn",
        "ResNetConfig": "maggy_tpu.models.cnn",
        "MoEDecoder": "maggy_tpu.models.moe",
        "MoEConfig": "maggy_tpu.models.moe",
        "Bert": "maggy_tpu.models.bert",
        "BertConfig": "maggy_tpu.models.bert",
    }
    if name in lazy:
        try:
            return getattr(importlib.import_module(lazy[name]), name)
        except ImportError as e:
            raise AttributeError(
                f"'{name}' is not available: {e}"
            ) from e
    raise AttributeError(f"module 'maggy_tpu.models' has no attribute {name!r}")
