"""BERT-style bidirectional encoder (BASELINE config 4: BERT-base ablation).

Shares the TPU-first conventions of the decoder family (bf16 compute, logical
partitioning, scan/remat) with learned positions, bidirectional blockwise
attention, and a pooled classification head. Components are named so an
AblationStudy factory can drop them (``study.model.set_factory``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, FrozenSet

import flax.linen as nn
import jax
import jax.numpy as jnp

from maggy_tpu.parallel.sharding import logical_partitioning

from maggy_tpu.models.transformer import _dense
from maggy_tpu.ops.attention import blockwise_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30_522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    num_classes: int = 2
    dropout: float = 0.1
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    ablated: FrozenSet[str] = frozenset()  # component names dropped by LOCO

    @classmethod
    def base(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        return cls(
            **{
                **dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
                       max_seq_len=64, dropout=0.0),
                **kw,
            }
        )

    def head_dim(self) -> int:
        return self.d_model // self.n_heads


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask, train: bool = False):
        cfg = self.cfg
        hd = cfg.head_dim()
        norm = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.norm_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=name
        )
        h = norm("attn_norm")(x)
        q = _dense((cfg.n_heads, hd), ("embed", "heads", None), cfg, "wq")(h)
        k = _dense((cfg.n_heads, hd), ("embed", "kv", None), cfg, "wk")(h)
        v = _dense((cfg.n_heads, hd), ("embed", "kv", None), cfg, "wv")(h)
        attn = blockwise_attention(q, k, v, causal=False, segment_ids=mask)
        attn = nn.DenseGeneral(
            cfg.d_model,
            axis=(-2, -1),
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=logical_partitioning(
                nn.initializers.normal(0.02), ("heads", None, "embed")
            ),
            name="wo",
        )(attn)
        x = x + attn
        h = norm("mlp_norm")(x)
        h = _dense(cfg.d_ff, ("embed", "mlp"), cfg, "w_in")(h)
        h = nn.gelu(h)
        h = _dense(cfg.d_model, ("mlp", "embed"), cfg, "w_out")(h)
        if cfg.dropout and train:
            h = nn.Dropout(cfg.dropout, deterministic=False)(h)
        return x + h


class Bert(nn.Module):
    """``__call__(tokens [B,S], attention_mask [B,S]?) -> (pooled_logits,
    sequence_output)``. Ablatable components: "position_embeddings", "pooler",
    and any "layer_{i}"."""

    cfg: BertConfig = BertConfig.tiny()

    @nn.compact
    def __call__(self, tokens, attention_mask=None, train: bool = False):
        cfg = self.cfg
        if attention_mask is None:
            attention_mask = jnp.ones_like(tokens)
        embed = self.param(
            "embedding",
            logical_partitioning(nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.d_model),
            cfg.param_dtype,
        )
        x = jnp.asarray(embed, cfg.dtype)[tokens]
        if "position_embeddings" not in cfg.ablated:
            pos = self.param(
                "position_embedding",
                logical_partitioning(nn.initializers.normal(0.02), (None, "embed")),
                (cfg.max_seq_len, cfg.d_model),
                cfg.param_dtype,
            )
            x = x + jnp.asarray(pos[: tokens.shape[1]], cfg.dtype)[None]

        # segment ids: padding tokens get -1 so they never attend/are attended
        seg = jnp.where(attention_mask > 0, 0, -1).astype(jnp.int32)
        for i in range(cfg.n_layers):
            if f"layer_{i}" in cfg.ablated:
                continue
            x = BertLayer(cfg, name=f"layer_{i}")(x, seg, train)
        x = nn.LayerNorm(
            epsilon=cfg.norm_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="final_norm",
        )(x)

        cls = x[:, 0]
        if "pooler" not in cfg.ablated:
            cls = jnp.tanh(_dense(cfg.d_model, ("embed", "embed"), cfg, "pooler")(cls))
        logits = nn.Dense(
            cfg.num_classes,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            name="classifier",
        )(cls)
        return logits, x
