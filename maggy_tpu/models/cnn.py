"""ResNet for image classification (BASELINE config 2: ResNet-50/CIFAR-10 ASHA).

TPU-first: NHWC layout (XLA's native conv layout on TPU), bf16 compute option,
and logical partitioning on conv kernels so FSDP shards the output-channel
axis. Standard v1.5 bottleneck blocks.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from maggy_tpu.parallel.sharding import logical_partitioning


def _norm(cfg, channels: int, name: str):
    return nn.GroupNorm(
        num_groups=math.gcd(32, channels),
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        name=name,
    )


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 10
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    small_inputs: bool = True  # CIFAR stem (3x3, no maxpool) vs ImageNet stem

    @classmethod
    def resnet18(cls, **kw) -> "ResNetConfig":
        return cls(**{**dict(stage_sizes=(2, 2, 2, 2)), **kw})

    @classmethod
    def resnet50(cls, **kw) -> "ResNetConfig":
        return cls(**kw)


def _conv(features, kernel, strides, cfg, name):
    return nn.Conv(
        features,
        kernel,
        strides=strides,
        use_bias=False,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=logical_partitioning(
            nn.initializers.he_normal(),
            ("conv_spatial", "conv_spatial", "conv_in", "conv_out"),
        ),
        name=name,
    )


class BottleneckBlock(nn.Module):
    cfg: ResNetConfig
    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.cfg
        # GroupNorm: batch-stat-free (TPU friendly); groups adapt to narrow nets
        residual = x
        y = nn.relu(_norm(cfg, self.features, "n1")(
            _conv(self.features, (1, 1), 1, cfg, "conv1")(x)))
        y = nn.relu(_norm(cfg, self.features, "n2")(
            _conv(self.features, (3, 3), self.strides, cfg, "conv2")(y)))
        y = _norm(cfg, self.features * 4, "n3")(
            _conv(self.features * 4, (1, 1), 1, cfg, "conv3")(y))
        if residual.shape != y.shape:
            residual = _norm(cfg, self.features * 4, "np")(
                _conv(self.features * 4, (1, 1), self.strides, cfg, "proj")(x)
            )
        return nn.relu(y + residual)


class ResNet(nn.Module):
    cfg: ResNetConfig = ResNetConfig()

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        if cfg.small_inputs:
            x = _conv(cfg.width, (3, 3), 1, cfg, "stem")(x)
        else:
            x = _conv(cfg.width, (7, 7), 2, cfg, "stem")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.relu(_norm(cfg, cfg.width, "stem_norm")(x))
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    cfg,
                    features=cfg.width * 2**stage,
                    strides=strides,
                    name=f"stage{stage}_block{block}",
                )(x, train)
        x = x.mean(axis=(1, 2))
        return nn.Dense(
            cfg.num_classes,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=logical_partitioning(
                nn.initializers.zeros_init(), ("embed", None)
            ),
            name="head",
        )(x)
