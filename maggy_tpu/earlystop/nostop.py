"""No-op early-stopping policy (reference earlystop/nostop.py:20-25)."""

from __future__ import annotations

from typing import Dict, List

from maggy_tpu.earlystop.abstractearlystop import AbstractEarlyStop
from maggy_tpu.trial import Trial


class NoStoppingRule(AbstractEarlyStop):
    @staticmethod
    def earlystop_check(
        to_check: Dict[str, Trial], final_store: List[Trial], direction: str
    ) -> List[str]:
        return []
