"""Median stopping rule (reference earlystop/medianrule.py:27-60): stop a running
trial whose best observed metric is worse than the median of the finalized trials'
running averages evaluated at the same step."""

from __future__ import annotations

import statistics
from typing import Dict, List

from maggy_tpu.earlystop.abstractearlystop import AbstractEarlyStop
from maggy_tpu.trial import Trial


class MedianStoppingRule(AbstractEarlyStop):
    @staticmethod
    def earlystop_check(
        to_check: Dict[str, Trial], final_store: List[Trial], direction: str
    ) -> List[str]:
        stop_ids: List[str] = []
        if not final_store:
            return stop_ids
        for trial_id, trial in to_check.items():
            if not trial.step_history:
                continue
            step = trial.step_history[-1]
            avgs = [
                avg
                for avg in (t.running_avg(up_to_step=step) for t in final_store)
                if avg is not None
            ]
            if not avgs:
                continue
            median = statistics.median(avgs)
            metrics = trial.metrics
            if direction == "max":
                if max(metrics) < median:
                    stop_ids.append(trial_id)
            else:
                if min(metrics) > median:
                    stop_ids.append(trial_id)
        return stop_ids
