from maggy_tpu.earlystop.abstractearlystop import AbstractEarlyStop
from maggy_tpu.earlystop.medianrule import MedianStoppingRule
from maggy_tpu.earlystop.nostop import NoStoppingRule

__all__ = ["AbstractEarlyStop", "MedianStoppingRule", "NoStoppingRule"]
