"""Early-stopping policy interface (reference earlystop/abstractearlystop.py:25)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List

from maggy_tpu.trial import Trial


class AbstractEarlyStop(ABC):
    @staticmethod
    @abstractmethod
    def earlystop_check(
        to_check: Dict[str, Trial], final_store: List[Trial], direction: str
    ) -> List[str]:
        """Return trial ids among ``to_check`` (running trials) that should stop,
        judged against the finalized trials in ``final_store``."""
