"""Synchronous Python client for the serving front-end.

Thin wrapper over the control-plane :class:`maggy_tpu.core.rpc.Client`
(framed JSON, secret-authenticated, auto-reconnect) speaking the serving
verbs. One socket per client; safe to use from multiple threads (the
underlying client serializes the main socket).

    client = ServeClient((host, port), secret)
    rid = client.submit([1, 2, 3], max_new=8)
    result = client.result(rid, timeout=30)   # poll until terminal
    print(result["tokens"])
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from maggy_tpu.core import rpc
from maggy_tpu.exceptions import RpcError


class ServeClient:
    def __init__(self, server_addr: Tuple[str, int], secret: str):
        self._client = rpc.Client(tuple(server_addr), partition_id=-1, secret=secret)

    def submit(
        self,
        prompt: List[int],
        *,
        temperature: float = 0.0,
        top_k: int = 0,
        max_new: int = 16,
        eos_id: int = -1,
        seed: int = 0,
        deadline_s: Optional[float] = None,
    ) -> str:
        reply = self._client._request(
            {
                "type": "SUBMIT",
                "prompt": [int(t) for t in prompt],
                "temperature": temperature,
                "top_k": top_k,
                "max_new": max_new,
                "eos_id": eos_id,
                "seed": seed,
                "deadline_s": deadline_s,
            }
        )
        return reply["id"]

    def poll(self, request_id: str) -> Dict[str, Any]:
        return self._client._request({"type": "POLL", "id": request_id})

    def result(
        self, request_id: str, timeout: float = 60.0, poll_interval: float = 0.01
    ) -> Dict[str, Any]:
        """Poll until the request reaches a terminal state."""
        deadline = time.time() + timeout
        while True:
            snap = self.poll(request_id)
            if snap.get("done"):
                return snap
            if time.time() > deadline:
                raise RpcError(
                    f"request {request_id} not done within {timeout}s "
                    f"(state={snap.get('state')})"
                )
            time.sleep(poll_interval)

    def generate(self, prompt: List[int], timeout: float = 60.0, **params) -> List[int]:
        """submit + result convenience; returns the generated tokens."""
        rid = self.submit(prompt, **params)
        snap = self.result(rid, timeout=timeout)
        if snap.get("state") != "done":
            raise RpcError(
                f"request {rid} ended {snap.get('state')}: {snap.get('error')}"
            )
        return list(snap["tokens"])

    def cancel(self, request_id: str) -> bool:
        return bool(
            self._client._request({"type": "CANCEL", "id": request_id}).get(
                "cancelled"
            )
        )

    def stats(self) -> Dict[str, Any]:
        return self._client._request({"type": "SSTATS"})

    def close(self) -> None:
        self._client.stop()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
