"""Synchronous Python client for the serving front-end.

Thin wrapper over the control-plane :class:`maggy_tpu.core.rpc.Client`
(framed JSON, secret-authenticated, auto-reconnect) speaking the serving
verbs against a single engine OR a fleet router — the verb set is
identical. One socket per client; safe to use from multiple threads (the
underlying client serializes the main socket).

    client = ServeClient((host, port), secret)
    rid = client.submit([1, 2, 3], max_new=8)
    result = client.result(rid, timeout=30)   # poll until terminal
    print(result["tokens"])

**Failover (default):** a transport-level failure (connection loss, server
restart) is retried with the control plane's jittered backoff instead of
raised on first error — the transparent-failover contract the fleet needs:
a replica dying mid-request surfaces to a polling client only as a
``state="requeued"`` snapshot, never an exception, and a briefly
unreachable router heals under the same backoff. Note SUBMIT retries are
at-least-once: a submit whose reply was lost may have landed, so a retried
submit can duplicate work (never corrupt it — requests are independent).
Rejections (validation errors) and 429-style ``BUSY`` sheds are typed
(:class:`~maggy_tpu.exceptions.RpcRejectedError` /
:class:`~maggy_tpu.exceptions.ServerBusyError`) and never retried unless
``submit(retry_busy=N)`` asks for BUSY re-tries.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from maggy_tpu import constants
from maggy_tpu.core import rpc
from maggy_tpu.exceptions import RpcError, RpcRejectedError, ServerBusyError
from maggy_tpu.telemetry import tracing


class ServeClient:
    def __init__(
        self,
        server_addr: Tuple[str, int],
        secret: str,
        failover: bool = True,
        max_retries: Optional[int] = None,
    ):
        self._client = rpc.Client(tuple(server_addr), partition_id=-1, secret=secret)
        self.failover = failover
        self.max_retries = (
            constants.RPC_MAX_RETRIES if max_retries is None else int(max_retries)
        )

    def _call(self, msg: Dict[str, Any], retry_busy: int = 0) -> Dict[str, Any]:
        """One verb round-trip with the failover ladder: transport errors
        (the rpc client already reconnect-retried underneath) get the same
        jittered backoff again up to ``max_retries``; BUSY replies retry
        only within the caller's ``retry_busy`` budget; rejections raise
        immediately."""
        attempts = max(1, self.max_retries if self.failover else 1)
        busy_left = int(retry_busy)
        last_err: Optional[Exception] = None
        attempt = 0
        while attempt < attempts:
            try:
                reply = self._client.request(msg)
            except RpcRejectedError:
                raise
            except (RpcError, OSError) as e:
                last_err = e
                attempt += 1
                if attempt >= attempts:
                    break
                time.sleep(rpc._retry_delay(attempt - 1))
                continue
            if reply.get("type") == "BUSY":
                if busy_left <= 0:
                    raise ServerBusyError(
                        f"server busy: {reply.get('error')} "
                        f"(projected_ttft_ms={reply.get('projected_ttft_ms')})"
                    )
                busy_left -= 1
                # prefer the server's projected-drain hint (retry_after_ms,
                # staggered per shed so clients don't re-arrive in lockstep)
                hint_ms = reply.get("retry_after_ms")
                if hint_ms is not None:
                    delay = float(hint_ms) / 1e3
                else:
                    delay = float(reply.get("retry_after_s") or 0.25)
                time.sleep(delay)
                continue  # BUSY retries don't consume transport attempts
            return reply
        raise RpcError(
            f"{msg.get('type')} failed after {attempts} attempt(s): {last_err}"
        )

    def submit(
        self,
        prompt: List[int],
        *,
        temperature: float = 0.0,
        top_k: int = 0,
        max_new: int = 16,
        eos_id: int = -1,
        seed: int = 0,
        deadline_s: Optional[float] = None,
        retry_busy: int = 0,
        trace: Optional[str] = None,
        tenant: Optional[str] = None,
        qos: Optional[str] = None,
    ) -> str:
        """Submit one request. A request-scoped ``trace`` id is minted here
        (or adopted from the caller / ambient scope) and rides the SUBMIT
        frame — the server stamps every lifecycle event with it, so the
        request's whole cross-worker journey correlates in the exported
        trace (docs/observability.md). Retried submits reuse the same id.
        ``tenant``/``qos`` select the QoS class (docs/fleet.md "QoS
        classes"); omitted means best_effort under the anonymous tenant."""
        msg = {
            "type": "SUBMIT",
            "prompt": [int(t) for t in prompt],
            "temperature": temperature,
            "top_k": top_k,
            "max_new": max_new,
            "eos_id": eos_id,
            "seed": seed,
            "deadline_s": deadline_s,
            "trace": trace or tracing.ensure(),
        }
        if tenant is not None:
            msg["tenant"] = str(tenant)
        if qos is not None:
            msg["qos"] = str(qos)
        reply = self._call(msg, retry_busy=retry_busy)
        return reply["id"]

    def poll(self, request_id: str) -> Dict[str, Any]:
        return self._call({"type": "POLL", "id": request_id})

    def result(
        self, request_id: str, timeout: float = 60.0, poll_interval: float = 0.01
    ) -> Dict[str, Any]:
        """Poll until the request reaches a terminal state. A fleet request
        whose replica died reports ``state="requeued"`` in between — keep
        polling; the router re-runs it on a survivor under the same id."""
        deadline = time.time() + timeout
        while True:
            snap = self.poll(request_id)
            if snap.get("done"):
                return snap
            if time.time() > deadline:
                raise RpcError(
                    f"request {request_id} not done within {timeout}s "
                    f"(state={snap.get('state')})"
                )
            time.sleep(poll_interval)

    def generate(self, prompt: List[int], timeout: float = 60.0, **params) -> List[int]:
        """submit + result convenience; returns the generated tokens."""
        rid = self.submit(prompt, **params)
        snap = self.result(rid, timeout=timeout)
        if snap.get("state") != "done":
            raise RpcError(
                f"request {rid} ended {snap.get('state')}: {snap.get('error')}"
            )
        return list(snap["tokens"])

    def cancel(self, request_id: str) -> bool:
        return bool(self._call({"type": "CANCEL", "id": request_id}).get("cancelled"))

    def stats(self) -> Dict[str, Any]:
        return self._call({"type": "SSTATS"})

    def close(self) -> None:
        self._client.stop()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
