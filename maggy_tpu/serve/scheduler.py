"""FCFS request scheduler and the engine loop.

One daemon thread owns the engine: it admits queued requests whenever slots
free up (prefill interleaved with decode), decodes one token per active slot
per iteration, and retires requests on EOS / ``max_new`` / cancellation /
deadline. RPC handlers only touch the queue and request index under the
scheduler lock — they never block on device work, which keeps the asyncio
socket loop responsive while XLA crunches.

Telemetry (continuously, into the ambient or provided recorder):
``serve.queue_depth``, ``serve.active_slots``, ``serve.tokens_per_sec``
(EMA over loop iterations), ``serve.ttft_ms`` per admission,
``serve.drain_ms`` (host-blocked time per async token drain —
docs/performance.md), and the engine's retrace gauges. Counters:
``serve.requests_{submitted,done,cancelled,expired,failed,rejected}`` and
``serve.tokens_out``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from maggy_tpu import telemetry
from maggy_tpu.exceptions import BadArgumentsError
from maggy_tpu.serve import request as rq
from maggy_tpu.serve.engine import Engine
from maggy_tpu.serve.request import Request, SamplingParams

# terminal requests stay pollable this long after finishing
RETENTION_S = 300.0
# idle wait when nothing is queued or active
IDLE_WAIT_S = 0.02


class Scheduler:
    def __init__(
        self,
        engine: Engine,
        max_queue: int = 1024,
        telemetry_recorder=None,
        retention_s: float = RETENTION_S,
    ):
        self.engine = engine
        self.max_queue = max_queue
        self.retention_s = retention_s
        self.telemetry = telemetry_recorder or engine.telemetry or telemetry.get()
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._queue: deque = deque()  # FCFS: append right, pop left
        self._requests: Dict[str, Request] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._ttft_ms: deque = deque(maxlen=512)
        self._started_ts = time.time()
        self._tok_rate_ema = 0.0
        self.counters: Dict[str, int] = {
            "submitted": 0,
            "done": 0,
            "cancelled": 0,
            "expired": 0,
            "failed": 0,
            "rejected": 0,
        }

    # ------------------------------------------------------------- public API
    # (called from RPC handler threads; must not block on device work)

    def submit(
        self,
        prompt: List[int],
        params: Optional[SamplingParams] = None,
        deadline_s: Optional[float] = None,
    ) -> Request:
        params = params or SamplingParams()
        params.validate()
        if not prompt:
            raise BadArgumentsError("empty prompt")
        if len(prompt) + params.max_new > self.engine.max_seq_len:
            raise BadArgumentsError(
                f"prompt ({len(prompt)}) + max_new ({params.max_new}) "
                f"exceeds max_seq_len ({self.engine.max_seq_len})"
            )
        req = Request(prompt=[int(t) for t in prompt], params=params)
        if deadline_s is not None:
            req.deadline_ts = time.time() + float(deadline_s)
        with self._wake:
            if len(self._queue) >= self.max_queue:
                self.counters["rejected"] += 1
                raise BadArgumentsError(
                    f"queue full ({self.max_queue} requests waiting)"
                )
            self._queue.append(req)
            self._requests[req.id] = req
            self.counters["submitted"] += 1
            self._wake.notify_all()
        return req

    def poll(self, request_id: str) -> Dict[str, Any]:
        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                raise BadArgumentsError(f"unknown request {request_id!r}")
            return req.snapshot()

    def cancel(self, request_id: str) -> bool:
        """Flag a request for cancellation; the loop enacts it at the next
        boundary (queued requests die before admission, running ones are
        evicted after the in-flight step). Returns False for terminal or
        unknown requests."""
        with self._wake:
            req = self._requests.get(request_id)
            if req is None or req.state in rq.TERMINAL:
                return False
            req.cancel_requested = True
            self._wake.notify_all()
            return True

    def stats(self) -> Dict[str, Any]:
        """One consistent snapshot, built entirely under the scheduler lock.

        The router polls SSTATS concurrently with the engine loop; every
        mutable structure read here (queue, counters, TTFT deque) is copied
        while the lock is held so a mid-iteration mutation can never tear the
        snapshot (dict-changed-size during iteration) or mix counters from
        two different instants. Engine counters are plain ints the scheduler
        thread owns — single reads are atomic under the GIL."""
        with self._lock:
            ttft = sorted(self._ttft_ms)
            counters = dict(self.counters)
            queue_depth = len(self._queue)
            engine = self.engine
            snap = {
                "queue_depth": queue_depth,
                "active_slots": engine.slots.active_count,
                "num_slots": engine.slots.num_slots,
                "tokens_out": engine.tokens_out,
                "tokens_per_sec": round(self._tok_rate_ema, 2),
                "steps": engine.steps,
                "uptime_s": round(time.time() - self._started_ts, 3),
                "compile_counts": engine.compile_counts,
                **engine.prefix_stats,
            }
        pct = lambda q: ttft[min(len(ttft) - 1, int(q * len(ttft)))] if ttft else None  # noqa: E731
        snap["ttft_ms_p50"] = pct(0.50)
        snap["ttft_ms_p95"] = pct(0.95)
        snap.update({f"requests_{k}": v for k, v in counters.items()})
        return snap

    # -------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="maggy-serve-scheduler", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until queue and slots are empty (tests/CLI shutdown)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if not self._queue and self.engine.slots.active_count == 0:
                    return True
            time.sleep(0.005)
        return False

    # ------------------------------------------------------------ engine loop

    def _finish(self, req: Request, state: str, error: Optional[str] = None) -> None:
        req.finish(state, error)
        key = {
            rq.DONE: "done",
            rq.CANCELLED: "cancelled",
            rq.EXPIRED: "expired",
            rq.FAILED: "failed",
        }[state]
        self.counters[key] += 1
        self.telemetry.count(f"serve.requests_{key}")

    def _emit(self, req: Request, token: int, now: float) -> bool:
        """Append a generated token; True when the request just finished."""
        req.tokens.append(int(token))
        if req.first_token_ts is None:
            req.first_token_ts = now
            if req.ttft_ms is not None:
                self._ttft_ms.append(req.ttft_ms)
                self.telemetry.gauge("serve.ttft_ms", req.ttft_ms)
        p = req.params
        if (p.eos_id >= 0 and int(token) == p.eos_id) or len(req.tokens) >= p.max_new:
            self._finish(req, rq.DONE)
            return True
        return False

    def _admit_ready(self, now: float) -> None:
        """Admit queued requests into free slots, FCFS; drop dead ones."""
        while self.engine.slots.free_slots():
            with self._lock:
                if not self._queue:
                    return
                req = self._queue.popleft()
            if req.cancel_requested:
                with self._lock:
                    self._finish(req, rq.CANCELLED)
                continue
            if req.deadline_ts is not None and now > req.deadline_ts:
                with self._lock:
                    self._finish(req, rq.EXPIRED, "deadline exceeded in queue")
                continue
            try:
                slot, first = self.engine.admit(req)
            except Exception as e:  # noqa: BLE001 - a poison request must not kill the loop
                with self._lock:
                    self._finish(req, rq.FAILED, f"{type(e).__name__}: {e}")
                continue
            with self._lock:
                req.state = rq.RUNNING
                req.admitted_ts = now
                if self._emit(req, first, time.time()):
                    self.engine.release(slot)

    def _sweep_active(self, now: float) -> None:
        """Evict running requests whose cancel flag or deadline fired."""
        for slot in list(self.engine.slots.active_slots()):
            req = self.engine.slots.get(slot).request
            if req.cancel_requested:
                with self._lock:
                    self._finish(req, rq.CANCELLED)
                self.engine.release(slot)
            elif req.deadline_ts is not None and now > req.deadline_ts:
                with self._lock:
                    self._finish(req, rq.EXPIRED, "deadline exceeded while decoding")
                self.engine.release(slot)

    def _retire_old(self, now: float) -> None:
        with self._lock:
            dead = [
                rid
                for rid, r in self._requests.items()
                if r.done_ts is not None and now - r.done_ts > self.retention_s
            ]
            for rid in dead:
                del self._requests[rid]

    def _loop(self) -> None:
        tel = self.telemetry
        last_flush = time.time()
        while not self._stop.is_set():
            now = time.time()
            self._sweep_active(now)
            self._admit_ready(now)

            active = self.engine.slots.active_slots()
            if active:
                t0 = time.perf_counter()
                out = self.engine.step()
                dt = time.perf_counter() - t0
                now = time.time()
                for slot, token in out.tokens.items():
                    req = self.engine.slots.get(slot).request
                    with self._lock:
                        finished = self._emit(req, token, now)
                    if finished:
                        self.engine.release(slot)
                rate = len(out.tokens) / dt if dt > 0 else 0.0
                self._tok_rate_ema = (
                    rate if self._tok_rate_ema == 0.0
                    else 0.9 * self._tok_rate_ema + 0.1 * rate
                )
                tel.gauge("serve.tokens_per_sec", self._tok_rate_ema)
            else:
                # async decode leaves the last dispatch in flight when the
                # active set empties (its rows all belong to finished
                # requests); retire it so no device refs linger across idle
                self.engine.flush()
                with self._wake:
                    if not self._queue and not self._stop.is_set():
                        self._wake.wait(timeout=IDLE_WAIT_S)

            with self._lock:
                tel.gauge("serve.queue_depth", len(self._queue))
            tel.gauge("serve.active_slots", self.engine.slots.active_count)
            if time.time() - last_flush > 1.0:
                self._retire_old(time.time())
                tel.flush()
                last_flush = time.time()
        tel.flush()
