"""QoS-aware request scheduler and the engine loop.

One daemon thread owns the engine: it admits queued requests whenever slots
free up (prefill interleaved with decode) — highest QoS class first, FIFO
within a class, bounded by the weighted token quotas in
:mod:`maggy_tpu.serve.qos` — decodes one token per active slot per
iteration, and retires requests on EOS / ``max_new`` / cancellation /
deadline. RPC handlers only touch the queue and request index under the
scheduler lock — they never block on device work, which keeps the asyncio
socket loop responsive while XLA crunches.

Telemetry (continuously, into the ambient or provided recorder):
``serve.queue_depth``, ``serve.active_slots``, ``serve.tokens_per_sec``
(EMA over loop iterations), ``serve.ttft_ms`` per admission,
``serve.drain_ms`` (host-blocked time per async token drain —
docs/performance.md), and the engine's retrace gauges. Counters:
``serve.requests_{submitted,done,cancelled,expired,failed,rejected}`` and
``serve.tokens_out``.

Request-scoped observability (docs/observability.md): every request carries
a trace id (from the SUBMIT frame, else minted here) and emits lifecycle
events under it — ``req.queued`` → ``req.admitted``/``req.prefix_admitted``
→ ``req.first_token`` → ``req.finished`` — while fixed-log-bucket
histograms aggregate TTFT, TPOT, queue-wait, and e2e latency
(scheduler-owned, for SSTATS percentiles and the router's fleet-level
merge; mirrored into the recorder for JSONL/monitor snapshots). The engine
loop arms a ``serve.loop`` stall-watchdog mark, so a wedged step loop dumps
the flight recorder instead of dying silently.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from maggy_tpu import telemetry
from maggy_tpu.core import lockdebug
from maggy_tpu.exceptions import BadArgumentsError
from maggy_tpu.resilience import chaos as chaos_mod
from maggy_tpu.serve import request as rq
from maggy_tpu.serve.engine import Engine
from maggy_tpu.serve.paging import OutOfPagesError
from maggy_tpu.serve.qos import (
    DEFAULT_TENANT,
    QOS_CLASSES,
    QOS_PRIORITY,
    QosQueue,
    QuotaLedger,
    validate_qos,
)
from maggy_tpu.serve.request import Request, SamplingParams
from maggy_tpu.telemetry import flightrec, timeseries, tracing
from maggy_tpu.telemetry.alerts import AlertEvaluator, RecompileSentinel
from maggy_tpu.telemetry.profcap import ProfileCapture
from maggy_tpu.telemetry.histogram import LatencyHistogram

# the latency signals the scheduler aggregates (histogram per signal);
# SSTATS exposes raw buckets under "latency" plus derived percentiles
LATENCY_SIGNALS = ("ttft_ms", "tpot_ms", "queue_wait_ms", "e2e_ms")

# terminal requests stay pollable this long after finishing
RETENTION_S = 300.0
# idle wait when nothing is queued or active
IDLE_WAIT_S = 0.02


class Scheduler:
    def __init__(
        self,
        engine: Engine,
        max_queue: int = 1024,
        telemetry_recorder=None,
        retention_s: float = RETENTION_S,
        slo_ttft_ms: Optional[float] = None,
        autopilot=None,
        qos_weights: Optional[Dict[str, float]] = None,
        qos_window_s: float = 5.0,
    ):
        self.engine = engine
        self.max_queue = max_queue
        self.retention_s = retention_s
        self.telemetry = telemetry_recorder or engine.telemetry or telemetry.get()
        # autopilot (docs/autotune.md "Continuous tuning"): an
        # AutopilotConfig/True attaches an online controller the loop ticks;
        # slot-geometry moves land through request_reconfigure below
        self._pending_slots: Optional[int] = None
        self.autopilot = None
        if autopilot is not None and autopilot is not False:
            from maggy_tpu.autopilot import (
                AutopilotConfig,
                Controller,
                SchedulerTarget,
            )

            cfg = autopilot if isinstance(autopilot, AutopilotConfig) else None
            self.autopilot = (
                autopilot
                if isinstance(autopilot, Controller)
                else Controller(
                    SchedulerTarget(self),
                    config=cfg,
                    telemetry_recorder=self.telemetry,
                )
            )
        self._lock = lockdebug.rlock("scheduler._lock")
        self._wake = threading.Condition(self._lock)
        # class-ordered admission queue (docs/fleet.md "QoS classes"):
        # priority then arrival within a class; preemption/backpressure
        # requeues go to the front of their own class
        self._queue = QosQueue()  # guarded-by: _lock
        # weighted decode-token quotas; the loop charges per emitted token,
        # admission defers over-share classes while others wait
        self.quota = QuotaLedger(weights=qos_weights, window_s=qos_window_s)
        # per-class lifetime counts (admitted/preempted/quota_deferred),
        # mirrored as serve.qos.* counters and in the stats() qos block
        self.qos_counters: Dict[str, Dict[str, int]] = {
            c: {"admitted": 0, "preempted": 0, "quota_deferred": 0}
            for c in QOS_CLASSES
        }  # guarded-by: _lock
        # which fleet replica this scheduler serves (set by Replica.start);
        # the replica_slow chaos seam keys on it to make one replica gray
        self.replica_index: Optional[int] = None
        self._requests: Dict[str, Request] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # scheduler-owned latency histograms (replacing the old 512-entry
        # TTFT deque): unbounded sample count, O(1) observe, mergeable at
        # the router. Written by the loop thread, serialized in stats()
        # under the lock.
        self._hist: Dict[str, LatencyHistogram] = {
            name: LatencyHistogram() for name in LATENCY_SIGNALS
        }
        # SLO attainment: exact per-request TTFT-vs-budget counters when an
        # SLO is configured (the fleet router sets its own from RouterConfig)
        self.slo_ttft_ms = None if slo_ttft_ms is None else float(slo_ttft_ms)
        self.slo_ok = 0
        self.slo_miss = 0
        self._started_ts = time.time()
        self._tok_rate_ema = 0.0
        # paged-cache preemptions enacted (docs/serving.md "Preemption") —
        # not a terminal state: the preempted request completes later
        self.preemptions = 0
        self.counters: Dict[str, int] = {
            "submitted": 0,
            "done": 0,
            "cancelled": 0,
            "expired": 0,
            "failed": 0,
            "rejected": 0,
        }
        # observability tick state (docs/observability.md "Time series"):
        # the loop samples the recorder into bounded ring-buffer series on
        # the ~1 s flush cadence, evaluates the checked-in alert rules at
        # worker scope, and the sentinel watches engine compile counts for
        # retraces outside a reconfigure window
        self.metrics = timeseries.SeriesStore()
        self.alerts = AlertEvaluator(self.metrics, self.telemetry, scope="worker")
        self.sentinel = RecompileSentinel(
            self.metrics, self.telemetry, scope="worker", steady=("decode", "admit")
        )
        # capacity observability (docs/observability.md "Capacity"): the
        # engine's memory ledger reconciles on the same tick, and a watched
        # critical alert arms a bounded profile capture beside the
        # flight-recorder dumps (telemetry/profcap.py)
        self.memory = engine.memory
        self.profcap = ProfileCapture()
        # last ticked headroom, stamped on admission events for trace
        # attribution (headroom_at_admit); loop thread writes, loop reads
        self._last_headroom_pct: Optional[float] = None

    # ------------------------------------------------------------- public API
    # (called from RPC handler threads; must not block on device work)

    def submit(
        self,
        prompt: List[int],
        params: Optional[SamplingParams] = None,
        deadline_s: Optional[float] = None,
        trace: Optional[str] = None,
        tenant: Optional[str] = None,
        qos: Optional[str] = None,
        _pack: Optional[Dict[str, Any]] = None,
    ) -> Request:
        params = params or SamplingParams()
        params.validate()
        try:
            qos = validate_qos(qos)
        except ValueError as e:
            raise BadArgumentsError(str(e)) from None
        if not prompt:
            raise BadArgumentsError("empty prompt")
        if len(prompt) + params.max_new > self.engine.max_seq_len:
            raise BadArgumentsError(
                f"prompt ({len(prompt)}) + max_new ({params.max_new}) "
                f"exceeds max_seq_len ({self.engine.max_seq_len})"
            )
        engine = self.engine
        if engine.paged:
            # a request that can NEVER fit the pool is a config error and
            # fails fast; anything that fits eventually is admitted
            # eventually (backpressure/preemption, never a refusal)
            worst = -(-(len(prompt) + params.max_new) // engine.page_size)
            cap = min(engine.max_pages_per_req, engine.allocator.pages_total)
            if worst > cap:
                raise BadArgumentsError(
                    f"request needs up to {worst} KV pages > cap {cap} "
                    f"(page_size {engine.page_size}; raise "
                    "max_pages_per_req or the pool)"
                )
        req = Request(prompt=[int(t) for t in prompt], params=params,
                      prefilled=_pack, qos=qos,
                      tenant=str(tenant) if tenant else DEFAULT_TENANT)
        # adopt the caller's trace id (SUBMIT frame / ambient RPC scope) so
        # the request's lifecycle correlates with its client-side journey;
        # direct in-process submits get a fresh one
        req.trace = trace or tracing.ensure()
        if deadline_s is not None:
            req.deadline_ts = time.time() + float(deadline_s)
        with self._wake:
            if len(self._queue) >= self.max_queue:
                self.counters["rejected"] += 1
                raise BadArgumentsError(
                    f"queue full ({self.max_queue} requests waiting)"
                )
            self._queue.append(req)
            self._requests[req.id] = req
            self.counters["submitted"] += 1
            self._wake.notify_all()
        self.telemetry.event(
            "req.queued", trace=req.trace, rid=req.id,
            plen=len(req.prompt), max_new=params.max_new,
            tenant=req.tenant, qos=req.qos,
        )
        return req

    def submit_prefilled(
        self,
        prompt: List[int],
        params: Optional[SamplingParams],
        pack: Dict[str, Any],
        deadline_s: Optional[float] = None,
        trace: Optional[str] = None,
        tenant: Optional[str] = None,
        qos: Optional[str] = None,
    ) -> Request:
        """Disaggregated handoff entry (docs/fleet.md "Disaggregated
        prefill/decode"): like :meth:`submit`, but the prompt's KV was
        already computed by a prefill replica and rides in ``pack``
        (:meth:`Engine.prefill_only`'s host-resident row). Admission writes
        the pack into the cache instead of prefilling; everything after
        the first token is the ordinary decode path."""
        return self.submit(
            prompt, params, deadline_s=deadline_s, trace=trace,
            tenant=tenant, qos=qos, _pack=dict(pack),
        )

    def poll(self, request_id: str) -> Dict[str, Any]:
        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                raise BadArgumentsError(f"unknown request {request_id!r}")
            return req.snapshot()

    def cancel(self, request_id: str) -> bool:
        """Flag a request for cancellation; the loop enacts it at the next
        boundary (queued requests die before admission, running ones are
        evicted after the in-flight step). Returns False for terminal or
        unknown requests."""
        with self._wake:
            req = self._requests.get(request_id)
            if req is None or req.state in rq.TERMINAL:
                return False
            req.cancel_requested = True
            self._wake.notify_all()
            return True

    def request_reconfigure(self, num_slots: int) -> bool:
        """Ask for a new slot geometry (the autopilot's ``serve.num_slots``
        safe-live move). Applied by the engine loop at the next wave
        boundary: admission pauses, the active set drains naturally, the
        engine rebuilds (compile warmed inside), then admission resumes —
        queued requests wait, nothing is dropped."""
        num_slots = int(num_slots)
        if num_slots < 1:
            return False
        with self._wake:
            if num_slots == self.engine.slots.num_slots:
                self._pending_slots = None
                return True
            self._pending_slots = num_slots
            self._wake.notify_all()
        return True

    def reconfigure_pending(self) -> bool:
        """True while a requested slot-geometry change awaits the drain."""
        with self._lock:
            return self._pending_slots is not None

    def _maybe_reconfigure(self) -> None:
        """Apply a pending slot change once the active set has drained
        (loop thread only)."""
        with self._lock:
            target = self._pending_slots
        if target is None or self.engine.slots.active_count:
            return
        try:
            # a reconfigure legitimately recompiles decode/admit: tell the
            # sentinel so the count bump re-baselines instead of alerting
            self.sentinel.expect()
            self.engine.reconfigure(target)
        except Exception as e:  # noqa: BLE001 - a failed re-tune must not kill serving
            self.telemetry.event(
                "autopilot.reconfigure_failed",
                num_slots=target, error=f"{type(e).__name__}: {e}",
            )
        with self._lock:
            # compare-and-clear: a newer slot request that landed while this
            # reconfigure ran must not be silently clobbered
            if self._pending_slots == target:
                self._pending_slots = None

    def _metrics_tick(self, now: float, wd=None) -> None:
        """One observability tick (loop thread, ~1 Hz with the flush):
        reconcile the capacity ledger, sample the recorder into the series
        rings, ingest the SLO counters, feed compile counts to the
        sentinel, run the alert rules, and hand the alert transitions to
        the profile-capture controller."""
        # capacity gauges go out BEFORE the sample so they land in this
        # tick's series points (heat/fragmentation ride the recorder; the
        # ledger ingests its mem.* series and burn counters directly)
        eng = self.engine
        tel = self.telemetry
        mem = self.memory.tick(store=self.metrics, telemetry=tel, now=now)
        self._last_headroom_pct = mem.get("headroom_pct") if mem else None
        if eng.paged:
            heat = eng.allocator.heat_buckets(eng.steps)
            frag = eng.allocator.fragmentation()
            tel.gauge("serve.pages_hot", heat["hot"])
            tel.gauge("serve.pages_warm", heat["warm"])
            tel.gauge("serve.pages_cold", heat["cold"])
            tel.gauge("serve.fragmentation", frag["frag_ratio"])
        res = eng.prefix_index.residency_stats(gen=eng.steps)
        tel.gauge("serve.prefix_resident_bytes", res["resident_bytes"])
        tel.gauge("serve.prefix_resident_count", res["resident_prefixes"])
        if eng.tier is not None:
            # pressure spill (docs/serving.md "Host-DRAM page tier"): when
            # reconciled HBM headroom sits under the tier's low-water mark,
            # preempt-with-spill the coldest low-class stream — at most one
            # per tick — freeing pool pages BEFORE an admission runs the
            # allocator dry and has to preempt under the gun
            if eng.tier_policy.should_spill(self._last_headroom_pct):
                self._drain_inflight()
                actives = eng.slots.active_slots()
                if actives:

                    def _rank(slot: int):
                        r = eng.slots.get(slot).request
                        return (QOS_PRIORITY.get(r.qos, len(QOS_CLASSES)),
                                r.admitted_ts or 0.0, slot)

                    self._preempt_victim(
                        max(actives, key=_rank), False, pressure=True
                    )
            ts = eng.tier.stats()
            tel.gauge("tier.host_pages_free", ts["host_pages_free"])
            tel.gauge("tier.host_pages_total", ts["host_pages_total"])
            tel.gauge("tier.host_bytes", ts["host_bytes"])
            tel.gauge("tier.resident_packs", ts["resident_packs"])
        self.metrics.sample(self.telemetry, now)
        if self.slo_ttft_ms is not None:
            with self._lock:
                slo_ok, slo_miss = self.slo_ok, self.slo_miss
            self.metrics.ingest(
                now,
                counters={
                    "serve.slo_ok": slo_ok,
                    "serve.slo_miss": slo_miss,
                },
            )
        self.sentinel.observe(self.engine.compile_counts, now, watchdog=wd)
        transitions = self.alerts.evaluate(now, watchdog=wd)
        if self.profcap.dump_dir is None and getattr(wd, "dump_dir", None):
            self.profcap.configure(dump_dir=wd.dump_dir)
        self.profcap.tick(transitions, now=now)
        self.telemetry.gauge(
            "alerts.firing", len(self.alerts.firing()) + len(self.sentinel.firing())
        )

    def stats(self) -> Dict[str, Any]:
        """One consistent snapshot, built entirely under the scheduler lock.

        The router polls SSTATS concurrently with the engine loop; every
        mutable structure read here (queue, counters, histograms) is copied
        while the lock is held so a mid-iteration mutation can never tear the
        snapshot (dict-changed-size during iteration) or mix counters from
        two different instants. Engine counters are plain ints the scheduler
        thread owns — single reads are atomic under the GIL.

        Latency surfaces: derived percentiles (``ttft_ms_p50/p90/p95/p99``,
        ``tpot_ms_p50/p95``, ``queue_wait_ms_p50``, ``e2e_ms_p50/p95``) plus
        the raw bucket encodings under ``latency`` — the router merges those
        bucket-wise into fleet-level distributions. With ``slo_ttft_ms``
        set, ``slo_ok``/``slo_miss``/``slo_attainment`` report SLO health."""
        with self._lock:
            counters = dict(self.counters)
            queue_depth = len(self._queue)
            hists = {name: h.copy() for name, h in self._hist.items()}
            slo = (self.slo_ttft_ms, self.slo_ok, self.slo_miss)
            engine = self.engine
            snap = {
                "queue_depth": queue_depth,
                "active_slots": engine.slots.active_count,
                "num_slots": engine.slots.num_slots,
                "tokens_out": engine.tokens_out,
                "tokens_per_sec": round(self._tok_rate_ema, 2),
                "steps": engine.steps,
                "uptime_s": round(time.time() - self._started_ts, 3),
                "compile_counts": engine.compile_counts,
                "paging": engine.paging_stats,
                "preemptions": self.preemptions,
                # capacity view: ledger reconciliation + profile-capture
                # controller state (docs/observability.md "Capacity")
                "memory": self.memory.snapshot(),
                "profcap": self.profcap.snapshot(),
                # host-DRAM KV tier view (docs/serving.md "Host-DRAM page
                # tier"): pool occupancy + the spill/fill ledger
                "tier": engine.tier_stats,
                # per-class QoS view (docs/fleet.md "QoS classes"): queue
                # depths, lifetime admission/preempt/defer counts, and the
                # quota ledger's windowed token shares
                "qos": {
                    "queued": self._queue.depths(),
                    "counters": {
                        c: dict(v) for c, v in self.qos_counters.items()
                    },
                    "quota": self.quota.snapshot(),
                },
                **engine.prefix_stats,
            }
        ttft = hists["ttft_ms"]
        snap["ttft_ms_p50"] = ttft.percentile(0.50)
        snap["ttft_ms_p90"] = ttft.percentile(0.90)
        snap["ttft_ms_p95"] = ttft.percentile(0.95)
        snap["ttft_ms_p99"] = ttft.percentile(0.99)
        snap["tpot_ms_p50"] = hists["tpot_ms"].percentile(0.50)
        snap["tpot_ms_p95"] = hists["tpot_ms"].percentile(0.95)
        snap["queue_wait_ms_p50"] = hists["queue_wait_ms"].percentile(0.50)
        snap["e2e_ms_p50"] = hists["e2e_ms"].percentile(0.50)
        snap["e2e_ms_p95"] = hists["e2e_ms"].percentile(0.95)
        snap["latency"] = {name: h.to_dict() for name, h in hists.items()}
        slo_ms, ok, miss = slo
        if slo_ms is not None:
            snap["slo_ttft_ms"] = slo_ms
            snap["slo_ok"] = ok
            snap["slo_miss"] = miss
            snap["slo_attainment"] = ok / (ok + miss) if (ok + miss) else None
        snap.update({f"requests_{k}": v for k, v in counters.items()})
        snap["alerts"] = self.alerts.firing() + self.sentinel.firing()
        if self.autopilot is not None:
            snap["autopilot"] = self.autopilot.status()
        return snap

    # -------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="maggy-serve-scheduler", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until queue and slots are empty (tests/CLI shutdown)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if not self._queue and self.engine.slots.active_count == 0:
                    return True
            time.sleep(0.005)
        return False

    # ------------------------------------------------------------ engine loop

    def _finish(  # guarded-by: _lock
        self, req: Request, state: str, error: Optional[str] = None
    ) -> None:
        req.finish(state, error)
        key = {
            rq.DONE: "done",
            rq.CANCELLED: "cancelled",
            rq.EXPIRED: "expired",
            rq.FAILED: "failed",
        }[state]
        self.counters[key] += 1
        tel = self.telemetry
        tel.count(f"serve.requests_{key}")
        if req.e2e_ms is not None:
            self._hist["e2e_ms"].observe(req.e2e_ms)
            tel.histogram("serve.e2e_ms", req.e2e_ms)
        if req.tpot_ms is not None:
            self._hist["tpot_ms"].observe(req.tpot_ms)
            tel.histogram("serve.tpot_ms", req.tpot_ms)
        # trace attribution v2: the request's high-water page count rides
        # the finish event (the slot is still resident here — release runs
        # after the finish on every exit path)
        peak = None
        eng = self.engine
        if eng.paged:
            for s in eng.slots.active_slots():
                if eng.slots.get(s).request is req:
                    peak = eng.pages_held_peak(s)
                    break
        tel.event(
            "req.finished", trace=req.trace, rid=req.id, state=state,
            n_tokens=len(req.tokens), e2e_ms=req.e2e_ms,
            pages_held_peak=peak,
        )

    def _emit(self, req: Request, token: int, now: float) -> bool:  # guarded-by: _lock
        """Append a generated token; True when the request just finished."""
        req.tokens.append(int(token))
        # quota accounting: one windowed decode token against the class
        self.quota.charge(req.qos, 1, now)
        if req.first_token_ts is None:
            req.first_token_ts = now
            ttft = req.ttft_ms
            if ttft is not None:
                self._hist["ttft_ms"].observe(ttft)
                tel = self.telemetry
                tel.gauge("serve.ttft_ms", ttft)
                tel.histogram("serve.ttft_ms", ttft)
                tel.event(
                    "req.first_token", trace=req.trace, rid=req.id, ttft_ms=ttft
                )
                if self.slo_ttft_ms is not None:
                    if ttft <= self.slo_ttft_ms:
                        self.slo_ok += 1
                    else:
                        self.slo_miss += 1
        p = req.params
        if (p.eos_id >= 0 and int(token) == p.eos_id) or len(req.tokens) >= p.max_new:
            self._finish(req, rq.DONE)
            return True
        return False

    def _admit_ready(self, now: float) -> None:
        """Admit queued requests into free slots — priority class first,
        FIFO within a class, quota-deferred classes skipped while another
        class waits under share; drop dead ones.

        A dry page pool (:class:`OutOfPagesError`) is BACKPRESSURE, not
        failure: the head request goes back to the front of its class and
        admission pauses until running requests finish or preemption frees
        pages — no request is ever refused for memory pressure (only a
        request that could never fit fails, at submit). A waiting class
        that strictly outranks an active row never waits for natural
        turnover, though: it preempts the lowest-class youngest row
        (slot AND pages), so premium TTFT is bounded by a prefill, not
        by a victim's remaining decode."""
        with self._lock:
            if self._pending_slots is not None:
                return  # drain-and-reconfigure in progress: let the wave empty
        while True:
            if not self.engine.slots.free_slots():
                with self._lock:
                    waiting = self._queue.classes_waiting()
                if not waiting or not self._preempt_lower_class(waiting[0]):
                    return
            with self._lock:
                req, deferred = self._queue.pop_next(self.quota, now)
                if req is None:
                    return
                for cls in deferred:
                    self.qos_counters[cls]["quota_deferred"] += 1
            for cls in deferred:
                self.telemetry.count(f"serve.qos.quota_deferred.{cls}")
            # replica_slow chaos seam (docs/resilience.md "Gray failure"):
            # a gray replica is alive but slow — inject the latency on the
            # admission path, outside the lock, so its own TTFT histograms
            # (what the router's breaker scores) absorb the slowness
            ch = chaos_mod.get()
            if ch is not None:
                slow_s = ch.replica_slow(self.replica_index)
                if slow_s > 0:
                    time.sleep(slow_s)
            if req.cancel_requested:
                with self._lock:
                    self._finish(req, rq.CANCELLED)
                continue
            if req.deadline_ts is not None and now > req.deadline_ts:
                with self._lock:
                    self._finish(req, rq.EXPIRED, "deadline exceeded in queue")
                continue
            # admission milestone BEFORE the prefill device work, so the
            # trace lane's queued→admitted gap is pure queue wait and
            # admitted→first_token is the prefill (docs/observability.md);
            # the prefix decision is re-read from the same deterministic
            # index match admit() itself will make
            req.admitted_ts = time.time()
            wait_ms = req.queue_wait_ms
            prefix_hit = (
                req.prefilled is None
                and self.engine._match_prefix(
                    list(req.prompt) + list(req.tokens)
                )
                is not None
            )
            tel = self.telemetry
            if wait_ms is not None:
                self._hist["queue_wait_ms"].observe(wait_ms)
                tel.histogram("serve.queue_wait_ms", wait_ms)
            tel.event(
                "req.prefix_admitted" if prefix_hit else "req.admitted",
                trace=req.trace, rid=req.id, queue_wait_ms=wait_ms,
                headroom_at_admit=self._last_headroom_pct,
            )
            pack, req.prefilled = req.prefilled, None
            admitted = False
            while True:
                try:
                    # the request's trace becomes ambient for the admission,
                    # so the engine's prefill/prefix-admit spans correlate
                    with tracing.scope(req.trace):
                        if pack is not None:
                            slot, first = self.engine.admit_from_kv(req, pack)
                        else:
                            slot, first = self.engine.admit(req)
                    admitted = True
                except OutOfPagesError:
                    # a dry pool must not park a higher class behind
                    # lower-class decodes: preempt strictly-lower-class
                    # rows (lowest class, youngest first) until the
                    # admission fits. Only same-or-higher-class occupancy
                    # backpressures — then the head request goes back to
                    # the front of its class (ahead of its peers; higher
                    # classes still outrank it next round), keeping its
                    # disaggregated-prefill pack for the next attempt
                    if self._preempt_lower_class(req.qos):
                        continue
                    req.prefilled = pack
                    with self._wake:
                        self._queue.requeue_front(req)
                    return
                except Exception as e:  # noqa: BLE001 - a poison request must not kill the loop
                    with self._lock:
                        self._finish(req, rq.FAILED, f"{type(e).__name__}: {e}")
                break
            if not admitted:
                continue
            with self._lock:
                req.state = rq.RUNNING
                self.qos_counters[req.qos]["admitted"] += 1
                if self._emit(req, first, time.time()):
                    self._release_slot(slot)
            tel.count(f"serve.qos.admitted.{req.qos}")

    def _release_slot(self, slot: int) -> None:
        """THE slot-vacating seam: every exit path (finish at emit, cancel,
        deadline, preemption) releases cache resources — pages, prefix
        anchor, slot row — through the engine's one release method. The
        cancel-storm regression in test_serve_engine.py asserts nothing
        leaks whichever path fires."""
        self.engine.release(slot)

    def _finish_active(
        self, slot: int, req: Request, state: str, error: Optional[str] = None
    ) -> None:
        """Finish an in-slot request and release its resources — the shared
        cancel/expire path (the emit path finishes inside ``_emit`` and
        releases through the same ``_release_slot``)."""
        with self._lock:
            self._finish(req, state, error)
        self._release_slot(slot)

    def _sweep_active(self, now: float) -> None:
        """Evict running requests whose cancel flag or deadline fired."""
        for slot in list(self.engine.slots.active_slots()):
            req = self.engine.slots.get(slot).request
            if req.cancel_requested:
                self._finish_active(slot, req, rq.CANCELLED)
            elif req.deadline_ts is not None and now > req.deadline_ts:
                self._finish_active(
                    slot, req, rq.EXPIRED, "deadline exceeded while decoding"
                )

    def _drain_inflight(self) -> None:
        """Flush the async double buffer and emit what it held (preemption
        prelude: the in-flight tokens may finish requests and free pages)."""
        out = self.engine.flush()
        now = time.time()
        for slot, token in out.tokens.items():
            req = self.engine.slots.get(slot).request
            with self._lock:
                finished = self._emit(req, token, now)
            if finished:
                self._release_slot(slot)

    def _preempt_for_pages(self) -> None:
        """Paged decode ran the allocator dry (an active row crossed a page
        boundary with no free page): preempt the LOWEST-PRIORITY active
        request, youngest within the class (PR 10's preempt-youngest is the
        degenerate single-class case) — free its pages, requeue it at the
        front of its class with prompt AND generated tokens retained — until
        every remaining row can grow. Re-admission resumes the stream
        byte-identically (docs/serving.md "Preemption"); the PRNG-chain
        resume seam is untouched by the victim-ordering change, so a
        preempted premium stream still completes bit-exact."""
        if not self.engine.paged:
            return
        while self.engine.prepare_step():
            # in-flight tokens first: a finish is cheaper than a preempt
            self._drain_inflight()
            if not self.engine.prepare_step():
                return
            actives = self.engine.slots.active_slots()
            if not actives:
                return

            def _rank(slot: int):
                r = self.engine.slots.get(slot).request
                # max() picks: largest priority number (lowest class), then
                # most recent admission (youngest) within the class
                return (QOS_PRIORITY.get(r.qos, len(QOS_CLASSES)),
                        r.admitted_ts or 0.0, slot)

            victim = max(actives, key=_rank)
            req = self.engine.slots.get(victim).request
            # a victim chosen BY class (some active row outranks it) is a
            # priority preemption, not just the youngest of equals
            vp = QOS_PRIORITY.get(req.qos, len(QOS_CLASSES))
            for_priority = any(
                QOS_PRIORITY.get(self.engine.slots.get(s).request.qos, 0) < vp
                for s in actives if s != victim
            )
            self._preempt_victim(victim, for_priority)

    def _preempt_victim(
        self, victim: int, for_priority: bool, pressure: bool = False
    ) -> None:
        """THE victim seam shared by decode-growth, admission, and
        tier-pressure preemption: spill the victim's KV to the host tier
        (when one is attached — re-admission then swaps in instead of
        re-prefilling), release the slot (pages, anchor, row) through
        ``_release_slot``, requeue the request at the front of its class
        with prompt AND generated tokens retained, and account it — the
        byte-identical resume guarantee lives entirely in this one path."""
        req = self.engine.slots.get(victim).request
        if self.engine.tier is not None:
            try:
                self.engine.spill_stream(victim, pressure=pressure)
            except Exception:  # noqa: BLE001 - spill is best-effort; preempt must proceed
                pass
        self._release_slot(victim)
        with self._wake:
            req.state = rq.QUEUED
            req.preemptions += 1
            self._queue.requeue_front(req)
            self.preemptions += 1
            self.qos_counters[req.qos]["preempted"] += 1
        tel = self.telemetry
        tel.count("serve.preemptions")
        tel.count(f"serve.qos.preempted.{req.qos}")
        tel.event(
            "req.preempted", trace=req.trace, rid=req.id,
            n_tokens=len(req.tokens), preemptions=req.preemptions,
        )
        if for_priority:
            tel.event(
                "req.preempted_for_priority", trace=req.trace, rid=req.id,
                qos=req.qos, n_tokens=len(req.tokens),
            )

    def _preempt_lower_class(self, qos: str) -> bool:
        """Free capacity (a slot and its pages) for a waiting higher-class
        admission: preempt the active row QOS strictly outranks — lowest
        class first, youngest within the class — and report whether
        admission should retry. In-flight tokens drain first (a finish is
        cheaper than a preempt, and may free the capacity by itself). A
        same-class squeeze never preempts: FIFO-within-class backpressure
        stays livelock-free."""
        if not self.engine.paged:
            return False
        had_free = self.engine.slots.free_slots()
        self._drain_inflight()
        if self.engine.slots.free_slots() > had_free:
            return True  # a finish freed slot + pages without a victim
        rp = QOS_PRIORITY.get(qos, len(QOS_CLASSES))
        victims = [
            s for s in self.engine.slots.active_slots()
            if QOS_PRIORITY.get(
                self.engine.slots.get(s).request.qos, len(QOS_CLASSES)
            ) > rp
        ]
        if not victims:
            return False

        def _rank(slot: int):
            r = self.engine.slots.get(slot).request
            return (QOS_PRIORITY.get(r.qos, len(QOS_CLASSES)),
                    r.admitted_ts or 0.0, slot)

        self._preempt_victim(max(victims, key=_rank), for_priority=True)
        return True

    def _retire_old(self, now: float) -> None:
        with self._lock:
            dead = [
                rid
                for rid, r in self._requests.items()
                if r.done_ts is not None and now - r.done_ts > self.retention_s
            ]
            for rid in dead:
                del self._requests[rid]

    def _loop(self) -> None:
        tel = self.telemetry
        last_flush = time.time()
        # stall watchdog: the loop beats every iteration (including idle
        # waits); a wedged engine step stops the beats and dumps the flight
        # recorder instead of hanging silently (docs/observability.md)
        wd = flightrec.get()
        wd.begin("serve.loop")
        try:
            self._loop_body(tel, last_flush, wd)
        finally:
            wd.end("serve.loop")

    def _loop_body(self, tel, last_flush, wd) -> None:
        while not self._stop.is_set():
            wd.beat("serve.loop")
            now = time.time()
            self._sweep_active(now)
            self._maybe_reconfigure()
            self._admit_ready(now)
            if self.autopilot is not None:
                self.autopilot.maybe_sample(now)

            self._preempt_for_pages()
            active = self.engine.slots.active_slots()
            if active:
                t0 = time.perf_counter()
                out = self.engine.step()
                dt = time.perf_counter() - t0
                now = time.time()
                for slot, token in out.tokens.items():
                    req = self.engine.slots.get(slot).request
                    with self._lock:
                        finished = self._emit(req, token, now)
                    if finished:
                        self._release_slot(slot)
                rate = len(out.tokens) / dt if dt > 0 else 0.0
                with self._lock:
                    self._tok_rate_ema = (
                        rate if self._tok_rate_ema == 0.0
                        else 0.9 * self._tok_rate_ema + 0.1 * rate
                    )
                    ema = self._tok_rate_ema
                tel.gauge("serve.tokens_per_sec", ema)
            else:
                # async decode leaves the last dispatch in flight when the
                # active set empties (its rows all belong to finished
                # requests); retire it so no device refs linger across idle
                self.engine.flush()
                with self._wake:
                    if not self._queue and not self._stop.is_set():
                        self._wake.wait(timeout=IDLE_WAIT_S)

            with self._lock:
                tel.gauge("serve.queue_depth", len(self._queue))
            tel.gauge("serve.active_slots", self.engine.slots.active_count)
            if time.time() - last_flush > 1.0:
                self._retire_old(time.time())
                self._metrics_tick(time.time(), wd)
                tel.flush()
                last_flush = time.time()
        tel.flush()
