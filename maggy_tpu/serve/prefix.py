"""Prefix index over resident prompts — the lookup half of prefix-KV reuse.

Real serving traffic shares long prompt prefixes (the system prompt is
identical across most requests), so a new request usually arrives while a
slot holding the *same opening tokens* is still resident. The engine can
then admit it by copying the already-computed KV rows instead of
re-prefilling them (docs/fleet.md "Prefix reuse"); this module answers the
host-side question "which resident slot shares the longest prefix with this
prompt, and how long is it?" in O(log max_len) hash probes instead of an
O(slots · len) scan.

Mechanics: every resident prompt is indexed under the hash of each of its
power-of-two-length prefixes (8, 16, 32, …— the same bucket ladder the
prefill compiler uses, so index granularity matches compile granularity).
``match()`` probes descending bucket lengths, verifies the hit against the
actual stored prompt (hash collisions can suggest, never lie), then extends
the verified bucket match token-by-token to the exact longest common
prefix. Newest insertion wins a bucket — recency is the better reuse bet
under churn.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, List, Optional, Tuple

# smallest indexed prefix; matches engine.MIN_PREFILL_BUCKET so a reused
# prefix always spans at least one full prefill bucket
MIN_PREFIX = 8


def _buckets(n: int, lo: int = MIN_PREFIX) -> List[int]:
    """Power-of-two prefix lengths <= n, ascending (8, 16, ... <= n)."""
    out = []
    b = lo
    while b <= n:
        out.append(b)
        b *= 2
    return out


class PrefixIndex:
    """slot -> prompt registry with hashed-prefix lookup.

    Host-side only and single-threaded by contract (the scheduler thread owns
    admission); the engine mirrors its slot lifecycle into it — ``insert`` on
    admit, ``remove`` on release.
    """

    def __init__(self, min_len: int = MIN_PREFIX):
        self.min_len = max(1, int(min_len))
        self._prompts: Dict[int, Tuple[int, ...]] = {}
        # hash(bucket-length prefix) -> slot that most recently wrote it
        self._by_hash: Dict[Tuple[int, int], int] = {}
        # per-slot residency metadata for the capacity view: when the prompt
        # became resident, when it last served a reuse hit, and how often —
        # same single-threaded contract as the index itself
        self._meta: Dict[int, Dict[str, int]] = {}
        # KV bytes one resident token pins (the engine sets this from its
        # cache geometry; 4 = raw int32 token ids when nothing better is known)
        self.bytes_per_token = 4

    def insert(self, slot: int, prompt: List[int], gen: int = 0) -> None:
        tokens = tuple(int(t) for t in prompt)
        self._prompts[slot] = tokens
        self._meta[slot] = {"inserted_gen": int(gen), "last_hit_gen": int(gen), "hits": 0}
        for b in _buckets(len(tokens), self.min_len):
            self._by_hash[(b, hash(tokens[:b]))] = slot

    def remove(self, slot: int) -> None:
        tokens = self._prompts.pop(slot, None)
        self._meta.pop(slot, None)
        if tokens is None:
            return
        for b in _buckets(len(tokens), self.min_len):
            key = (b, hash(tokens[:b]))
            if self._by_hash.get(key) == slot:
                del self._by_hash[key]
        # a dropped bucket may still be owned by an older resident sharing
        # the prefix (system prompts collide by design) — re-point it so a
        # short-lived request's release can't orphan the long-lived anchor
        for other, resident in self._prompts.items():
            for b in _buckets(len(resident), self.min_len):
                self._by_hash.setdefault((b, hash(resident[:b])), other)

    def match(
        self, prompt: List[int], gen: Optional[int] = None
    ) -> Optional[Tuple[int, int]]:
        """``(slot, lcp_len)`` of the resident prompt sharing the longest
        common prefix with ``prompt`` (>= ``min_len``), or None.

        The probe walks bucket lengths longest-first; the first verified hit
        is extended by direct comparison, so the returned length is the exact
        LCP with that slot — which may exceed the bucket that found it. A hit
        bumps the slot's residency recency (``gen`` when given) — the signal
        prefix-affinity dispatch and tiering eviction rank on.
        """
        tokens = tuple(int(t) for t in prompt)
        for b in reversed(_buckets(len(tokens), self.min_len)):
            slot = self._by_hash.get((b, hash(tokens[:b])))
            if slot is None:
                continue
            resident = self._prompts.get(slot)
            if resident is None or resident[:b] != tokens[:b]:
                continue  # hash collision or stale entry: keep probing
            lcp = b
            limit = min(len(resident), len(tokens))
            while lcp < limit and resident[lcp] == tokens[lcp]:
                lcp += 1
            meta = self._meta.get(slot)
            if meta is not None:
                meta["hits"] += 1
                if gen is not None:
                    meta["last_hit_gen"] = int(gen)
            return slot, lcp
        return None

    def resident(self) -> Dict[int, Tuple[int, ...]]:
        return dict(self._prompts)

    # -------------------------------------------------------------- residency

    @staticmethod
    def digest(tokens: Tuple[int, ...], head: int = 16) -> str:
        """Stable 8-hex digest of a prompt's opening tokens — identical for
        the same prefix on every replica/process (unlike ``hash``), so the
        fleet residency view can group residents across workers."""
        data = ",".join(str(int(t)) for t in tokens[:head]).encode()
        return f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"

    def residency_stats(
        self, gen: Optional[int] = None, top: int = 4
    ) -> Dict[str, Any]:
        """Aggregate residency view for SSTATS: how much KV the resident
        prompts pin and which prefixes are the hottest reuse anchors."""
        tokens_total = sum(len(t) for t in self._prompts.values())
        rows = []
        for slot, toks in self._prompts.items():
            meta = self._meta.get(slot) or {}
            row = {
                "digest": self.digest(toks),
                "slot": slot,
                "tokens": len(toks),
                "bytes": len(toks) * self.bytes_per_token,
                "hits": meta.get("hits", 0),
            }
            if gen is not None:
                row["age"] = int(gen) - meta.get("last_hit_gen", 0)
            rows.append(row)
        rows.sort(key=lambda r: (-r["hits"], -r["tokens"], r["digest"]))
        return {
            "resident_prefixes": len(self._prompts),
            "resident_tokens": tokens_total,
            "resident_bytes": tokens_total * self.bytes_per_token,
            "top": rows[: int(top)],
        }
