"""Slot bookkeeping for the continuous-batching engine.

A *slot* is one row of the engine's fixed-``B`` decode cache. The
:class:`SlotManager` is pure host-side accounting — which rows are free,
which request owns which row, and the per-slot decode state the jitted step
consumes (last token, next position, sampling params). Device-side cache
rows are written by :class:`maggy_tpu.serve.engine.Engine`; the invariants
here (admit only into a free slot, evict only an occupied one, one slot per
request) are what the churn tests hammer.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from maggy_tpu.exceptions import BadArgumentsError
from maggy_tpu.serve.request import Request


@dataclasses.dataclass
class SlotState:
    """Host mirror of one cache row while a request occupies it."""

    request: Request
    # next cache/sequence position the slot will write (== tokens so far)
    next_pos: int
    # the token fed to the next decode step (last sampled token)
    last_token: int
    # tokens generated so far (== index of the NEXT token to be produced)
    generated: int


class SlotOccupiedError(BadArgumentsError):
    pass


class SlotManager:
    def __init__(self, num_slots: int):
        if num_slots < 1:
            raise BadArgumentsError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = num_slots
        self._slots: List[Optional[SlotState]] = [None] * num_slots
        self._by_request: Dict[str, int] = {}

    # ------------------------------------------------------------------ admit

    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self._slots) if s is None]

    def admit(
        self,
        request: Request,
        first_token: int,
        next_pos: Optional[int] = None,
        generated: int = 1,
    ) -> int:
        """Claim a free slot for ``request`` whose prompt was just prefilled
        and whose first token was sampled from the prefill logits.

        ``next_pos``/``generated`` override the fresh-request defaults for
        PREEMPTED requests being re-admitted (docs/serving.md "Preemption"):
        their prefill covered prompt + already-generated tokens, so the
        write position and the PRNG fold-in index resume mid-stream."""
        free = self.free_slots()
        if not free:
            raise SlotOccupiedError("no free slot")
        if request.id in self._by_request:
            raise SlotOccupiedError(f"request {request.id} already in a slot")
        slot = free[0]
        self._slots[slot] = SlotState(
            request=request,
            next_pos=len(request.prompt) if next_pos is None else int(next_pos),
            last_token=int(first_token),
            generated=int(generated),
        )
        self._by_request[request.id] = slot
        return slot

    # ------------------------------------------------------------------ evict

    def evict(self, slot: int) -> Request:
        state = self._slots[slot]
        if state is None:
            raise SlotOccupiedError(f"slot {slot} is already free")
        self._slots[slot] = None
        del self._by_request[state.request.id]
        return state.request

    # ------------------------------------------------------------------ query

    def get(self, slot: int) -> Optional[SlotState]:
        return self._slots[slot]

    def slot_of(self, request_id: str) -> Optional[int]:
        return self._by_request.get(request_id)

    def active_slots(self) -> List[int]:
        return [i for i, s in enumerate(self._slots) if s is not None]

    @property
    def active_count(self) -> int:
        return len(self._by_request)

    def advance(self, slot: int, token: int) -> SlotState:
        """Record one decoded token: it becomes the next step's input and the
        slot's write position moves forward one cache row."""
        state = self._slots[slot]
        if state is None:
            raise SlotOccupiedError(f"slot {slot} is free; cannot advance")
        state.last_token = int(token)
        state.next_pos += 1
        state.generated += 1
        return state

    def check_invariants(self) -> None:
        """Cross-checks for the churn tests: the request index and the slot
        array must mirror each other exactly."""
        for rid, slot in self._by_request.items():
            state = self._slots[slot]
            assert state is not None and state.request.id == rid, (rid, slot)
        occupied = [i for i, s in enumerate(self._slots) if s is not None]
        assert len(occupied) == len(self._by_request)
