"""Continuous-batching serving engine.

The inference tier over the decode primitives in
:mod:`maggy_tpu.models.generate`: a fixed-slot KV-cache engine whose one
compiled decode step serves a churning request population (admission via
prefill into free slots, eviction on EOS/``max_new``), an FCFS scheduler
with per-request sampling params / fresh PRNG keys / deadlines /
cancellation, and an RPC front-end + client on the
:mod:`maggy_tpu.core.rpc` frame protocol.

    # server:  python -m maggy_tpu.serve --config tiny --slots 8
    # client:
    from maggy_tpu.serve import ServeClient
    client = ServeClient((host, port), secret)
    tokens = client.generate([1, 2, 3], max_new=16)

In-process use (no sockets): build ``Engine`` + ``Scheduler`` directly.
Multi-replica serving: :mod:`maggy_tpu.serve.fleet` puts an SLO-aware
router over N of these stacks behind the same verb set
(``python -m maggy_tpu.serve --replicas 2``; docs/fleet.md).
"""

from maggy_tpu.serve.client import ServeClient  # noqa: F401
from maggy_tpu.serve.engine import Engine  # noqa: F401
from maggy_tpu.serve.loadgen import (  # noqa: F401
    Arrival,
    Burst,
    TenantMix,
    TrafficReplay,
    TrafficSpec,
)
from maggy_tpu.serve.paging import (  # noqa: F401
    BlockAllocator,
    OutOfPagesError,
    PageTable,
)
from maggy_tpu.serve.prefix import PrefixIndex  # noqa: F401
from maggy_tpu.serve.qos import (  # noqa: F401
    BEST_EFFORT,
    PREMIUM,
    QOS_CLASSES,
    STANDARD,
    QosQueue,
    QuotaLedger,
)
from maggy_tpu.serve.request import Request, SamplingParams  # noqa: F401
from maggy_tpu.serve.scheduler import Scheduler  # noqa: F401
from maggy_tpu.serve.server import ServeServer  # noqa: F401
from maggy_tpu.serve.slots import SlotManager  # noqa: F401

__all__ = [
    "Arrival",
    "BEST_EFFORT",
    "BlockAllocator",
    "Burst",
    "Engine",
    "OutOfPagesError",
    "PREMIUM",
    "PageTable",
    "PrefixIndex",
    "QOS_CLASSES",
    "QosQueue",
    "QuotaLedger",
    "STANDARD",
    "Scheduler",
    "ServeServer",
    "ServeClient",
    "SlotManager",
    "Request",
    "SamplingParams",
    "TenantMix",
    "TrafficReplay",
    "TrafficSpec",
]
