"""Per-tenant QoS: priority classes, the class-ordered admission queue, and
the weighted token-budget quota ledger.

Requests carry a ``tenant`` (accounting identity) and a ``qos`` class
(scheduling identity). Three classes, in priority order::

    premium > standard > best_effort   (the wire default)

Two mechanisms share the decode capacity between them
(docs/fleet.md "QoS classes & graceful degradation"):

* **Priority admission** (:class:`QosQueue`): the scheduler admits the
  highest-priority non-empty class first, FIFO within a class. Requeues
  (preemption, page backpressure) go to the *front of their own class*, so
  a preempted premium stream still outranks queued premium arrivals but
  never jumps a class it doesn't belong to.
* **Weighted token quotas** (:class:`QuotaLedger`): each class's share of
  decode tokens over a sliding window is bounded by its weight. The ledger
  is work-conserving — an over-share class is only deferred while some
  under-share class has queued work — so quotas are a *guaranteed floor*
  for every class (premium cannot fully starve best-effort, and a
  best-effort flood cannot crowd premium out of its share), never idle
  capacity.

Preemption ordering reuses the same classes: under page pressure the
scheduler preempts the lowest class first, youngest within the class
(:meth:`Scheduler._preempt_for_pages`), and the PR-10 byte-identical resume
seam means a preempted premium stream still completes bit-exact.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from maggy_tpu.core import lockdebug

PREMIUM = "premium"
STANDARD = "standard"
BEST_EFFORT = "best_effort"

# priority order: admission walks this left to right; preemption walks it
# right to left (lowest class is the first victim)
QOS_CLASSES: Tuple[str, ...] = (PREMIUM, STANDARD, BEST_EFFORT)
QOS_PRIORITY: Dict[str, int] = {c: i for i, c in enumerate(QOS_CLASSES)}

# default decode-token weights (premium:standard:best_effort); any class's
# windowed share above weight/total defers it while others wait
DEFAULT_WEIGHTS: Dict[str, float] = {PREMIUM: 8.0, STANDARD: 3.0, BEST_EFFORT: 1.0}

DEFAULT_QOS = BEST_EFFORT
DEFAULT_TENANT = "anon"


def validate_qos(qos: Optional[str]) -> str:
    """Normalize a wire/API qos value; raises ``ValueError`` on unknowns so
    a typo'd class fails the submit instead of silently scheduling it
    best-effort."""
    if qos is None or qos == "":
        return DEFAULT_QOS
    qos = str(qos)
    if qos not in QOS_PRIORITY:
        raise ValueError(
            f"unknown qos class {qos!r} (valid: {', '.join(QOS_CLASSES)})"
        )
    return qos


class QosQueue:
    """Class-ordered admission queue: one FIFO deque per QoS class.

    Not itself locked — every method is called under the scheduler's lock
    (the same discipline the old single deque followed); it is a data
    structure, not a concurrency boundary.
    """

    def __init__(self) -> None:
        self._queues: Dict[str, deque] = {c: deque() for c in QOS_CLASSES}

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def append(self, req: Any) -> None:
        """Fresh arrival: back of its class."""
        self._queues[getattr(req, "qos", DEFAULT_QOS)].append(req)

    def requeue_front(self, req: Any) -> None:
        """Preempted / page-backpressured request: front of its class, so it
        outranks fresh arrivals of the same class but not higher classes."""
        self._queues[getattr(req, "qos", DEFAULT_QOS)].appendleft(req)

    def depths(self) -> Dict[str, int]:
        return {c: len(q) for c, q in self._queues.items()}

    def classes_waiting(self) -> List[str]:
        """Non-empty classes in priority order."""
        return [c for c in QOS_CLASSES if self._queues[c]]

    def pop_next(
        self, ledger: Optional["QuotaLedger"] = None, now: Optional[float] = None
    ) -> Tuple[Optional[Any], List[str]]:
        """The next request to admit plus the classes that were quota-deferred
        to reach it.

        Highest-priority non-empty class wins, unless the ledger says that
        class is over its windowed token share *and* some other class is
        waiting under share — then the best under-share class is served
        instead (the deferred, higher-priority classes are returned so the
        scheduler can count them). When every waiting class is over share
        the pick falls back to plain priority: quotas never idle a slot.
        """
        waiting = self.classes_waiting()
        if not waiting:
            return None, []
        choice = waiting[0]
        deferred: List[str] = []
        if ledger is not None and len(waiting) > 1:
            eligible = [c for c in waiting if not ledger.over_share(c, now)]
            if eligible and eligible[0] != choice:
                choice = eligible[0]
                deferred = waiting[: waiting.index(choice)]
        return self._queues[choice].popleft(), deferred


class QuotaLedger:
    """Sliding-window decode-token accounting per QoS class.

    The scheduler loop charges one token per emitted decode token
    (:meth:`charge`); admission asks :meth:`over_share` whether a class has
    exceeded its weighted share of the window. Tokens are accumulated into
    coarse time buckets so charging stays O(1) on the decode hot path and
    pruning is O(window / bucket).

    Charged from the scheduler loop thread and read by admission on the
    same thread, but also snapshotted by RPC-side ``stats()`` — hence the
    lock (pinned in ``tools/check_concurrency.py`` REQUIRED_MODELS).
    """

    def __init__(
        self,
        weights: Optional[Dict[str, float]] = None,
        window_s: float = 5.0,
        min_tokens: int = 32,
        bucket_s: float = 0.25,
    ):
        weights = dict(weights or DEFAULT_WEIGHTS)
        for c in QOS_CLASSES:
            weights.setdefault(c, 0.0)
        total = sum(w for w in weights.values() if w > 0) or 1.0
        self.weights = weights
        self.fractions = {c: max(0.0, w) / total for c, w in weights.items()}
        self.window_s = float(window_s)
        # below this many tokens in the window the ledger abstains: early
        # traffic must not be deferred on statistically-meaningless shares
        self.min_tokens = int(min_tokens)
        self.bucket_s = float(bucket_s)
        self._lock = lockdebug.lock("qos.ledger")
        # (bucket_start_ts, {class: tokens}) oldest-first  # guarded-by: _lock
        self._buckets: deque = deque()

    # ------------------------------------------------------------------ write

    def charge(self, qos: str, tokens: int = 1, now: Optional[float] = None) -> None:  # thread-entry — charged from the scheduler's decode loop per emitted token
        now = time.time() if now is None else now
        start = now - (now % self.bucket_s)
        with self._lock:
            if not self._buckets or self._buckets[-1][0] != start:
                self._buckets.append((start, {}))
                self._prune(now)
            counts = self._buckets[-1][1]
            counts[qos] = counts.get(qos, 0) + int(tokens)

    def _prune(self, now: float) -> None:  # guarded-by: _lock
        cutoff = now - self.window_s - self.bucket_s
        while self._buckets and self._buckets[0][0] < cutoff:
            self._buckets.popleft()

    # ------------------------------------------------------------------- read

    def totals(self, now: Optional[float] = None) -> Dict[str, int]:
        """Tokens per class inside the window."""
        now = time.time() if now is None else now
        cutoff = now - self.window_s
        out: Dict[str, int] = {c: 0 for c in QOS_CLASSES}
        with self._lock:
            for start, counts in self._buckets:
                if start + self.bucket_s < cutoff:
                    continue
                for c, n in counts.items():
                    out[c] = out.get(c, 0) + n
        return out

    def shares(self, now: Optional[float] = None) -> Dict[str, float]:
        totals = self.totals(now)
        grand = sum(totals.values())
        if grand <= 0:
            return {c: 0.0 for c in totals}
        return {c: n / grand for c, n in totals.items()}

    def over_share(self, qos: str, now: Optional[float] = None) -> bool:
        """True when ``qos`` has consumed more than its weighted share of
        the window's decode tokens (and the window is statistically
        meaningful)."""
        totals = self.totals(now)
        grand = sum(totals.values())
        if grand < self.min_tokens:
            return False
        return totals.get(qos, 0) / grand > self.fractions.get(qos, 0.0)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        totals = self.totals(now)
        return {
            "window_s": self.window_s,
            "weights": dict(self.weights),
            "tokens": totals,
            "shares": {
                c: round(s, 4) for c, s in self.shares(now).items()
            },
        }
