"""Host-side block allocator and per-slot page table for the paged KV cache.

The allocator owns the physical page id space ``[0, num_pages)``. Page
``SCRATCH_PAGE`` (0) is reserved: unallocated page-table entries point at it
and masked/inactive device writes are routed there, so its content is
garbage by design and nothing ever reads it as valid. All other pages move
between exactly three states:

* **free** — on the free list, refcount 0;
* **owned** — refcount 1, exactly one request's page list holds it;
* **shared** — refcount >= 2, a prefix-aliased page held by several page
  lists. Shared pages are read-only by contract: the engine only writes a
  page while it is owned (admission writes fresh pages; decode writes the
  tail page past ``plen``, which aliasing can never cover — see
  ``docs/serving.md`` "Paged KV cache"). ``release`` decrements and frees
  at zero, so the last sharer's eviction reclaims the page.

Invariants (the property tests in ``tests/test_paged_kv.py`` hammer these):
``alloc`` is atomic (all-or-nothing under :class:`OutOfPagesError`), a page
is never double-freed, never on the free list while referenced, and
``pages_free + pages_referenced == num_pages - 1`` always.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

# physical page 0: garbage sink for unallocated table entries and masked
# writes; never allocated, never read as valid
SCRATCH_PAGE = 0


class OutOfPagesError(RuntimeError):
    """The pool cannot satisfy an allocation; the scheduler's response is
    backpressure (queued admissions wait) or preemption (decode growth
    evicts the youngest request) — never a failed request."""


class BlockAllocator:
    """Free-list allocator over ``num_pages`` fixed-size pages with
    per-page reference counts (prefix aliasing shares pages)."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page {SCRATCH_PAGE} is reserved), "
                f"got {num_pages}"
            )
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed pages are re-used first (their
        # content is about to be fully overwritten anyway, and temporal
        # locality keeps the hot working set small)
        self._free: List[int] = list(range(self.num_pages - 1, SCRATCH_PAGE, -1))
        self._refs: Dict[int, int] = {}
        # page -> last-access generation (engine decode-step clock), stamped
        # host-side by ``touch`` on the admit/prepare paths — the heat signal
        # the tiering eviction ranking reads. Entries exist only for
        # referenced pages; a page freed is a page forgotten.
        # guarded-by: the engine lock (all allocator mutation already is)
        self._last_access: Dict[int, int] = {}
        # cumulative counters (monotonic; bench/stats)
        self.allocs = 0
        self.shares = 0

    # ------------------------------------------------------------------ state

    @property
    def pages_total(self) -> int:
        """Allocatable pages (the scratch page is not part of the budget)."""
        return self.num_pages - 1

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_shared(self) -> int:
        """Pages currently referenced by more than one page list."""
        return sum(1 for n in self._refs.values() if n >= 2)

    def refcount(self, page: int) -> int:
        return self._refs.get(int(page), 0)

    # ------------------------------------------------------------------ moves

    def alloc(self, n: int) -> List[int]:
        """``n`` fresh pages (refcount 1 each), atomically — on
        :class:`OutOfPagesError` nothing was taken."""
        n = int(n)
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise OutOfPagesError(
                f"need {n} page(s), {len(self._free)} free "
                f"of {self.pages_total}"
            )
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        self.allocs += n
        return out

    def share(self, pages: Sequence[int]) -> None:
        """Alias already-allocated pages into another page list
        (refcount += 1). Sharing a free or scratch page is a bug."""
        pages = [int(p) for p in pages]
        for p in pages:
            if p == SCRATCH_PAGE or p not in self._refs:
                raise ValueError(f"share of unallocated page {p}")
        for p in pages:
            self._refs[p] += 1
        self.shares += len(pages)

    def release(self, pages: Sequence[int]) -> int:
        """Drop one reference per page; pages reaching refcount 0 return to
        the free list. Returns how many were actually freed. Double-free
        (releasing a page no list holds) raises."""
        freed = 0
        for p in (int(p) for p in pages):
            refs = self._refs.get(p)
            if refs is None:
                raise ValueError(f"double free of page {p}")
            if refs == 1:
                del self._refs[p]
                self._last_access.pop(p, None)
                self._free.append(p)
                freed += 1
            else:
                self._refs[p] = refs - 1
        return freed

    # ------------------------------------------------------------------- heat

    def touch(self, pages: Sequence[int], gen: int) -> None:
        """Stamp ``pages`` as accessed at generation ``gen`` (the engine's
        decode-step counter). Host dict stores only — zero device cost on
        the admit/prepare hot paths. Touching a free page is ignored (a
        release can race a stale caller list by design)."""
        gen = int(gen)
        refs = self._refs
        la = self._last_access
        for p in pages:
            p = int(p)
            if p in refs:
                la[p] = gen

    def heat_buckets(
        self, gen: int, hot_age: int = 8, warm_age: int = 64
    ) -> Dict[str, int]:
        """Classify every referenced page by last-access age in generations:
        ``age <= hot_age`` hot, ``<= warm_age`` warm, else cold. Pages
        allocated but never touched count as cold (no stamp == no access)."""
        gen = int(gen)
        hot = warm = cold = 0
        la = self._last_access
        for p in self._refs:
            last = la.get(p)
            age = gen - last if last is not None else warm_age + 1
            if age <= hot_age:
                hot += 1
            elif age <= warm_age:
                warm += 1
            else:
                cold += 1
        return {"hot": hot, "warm": warm, "cold": cold}

    def coldest(
        self, n: Optional[int] = None, include_shared: bool = False
    ) -> List[int]:
        """Referenced pages ranked coldest-first (oldest last-access
        generation; never-touched pages first of all) — the eviction-candidate
        ordering the host-DRAM tiering consumes. Ties break on page id for
        determinism.

        Shared pages (refcount >= 2) are EXCLUDED by default: a
        prefix-aliased page is live working set for every request holding
        it, however stale its heat stamp looks — spilling one out from
        under an active sharer would corrupt a stream that never chose to
        be evicted. ``include_shared=True`` restores the raw ranking for
        observability callers that want the full heat picture."""
        la = self._last_access
        refs = self._refs
        pages = (
            refs
            if include_shared
            else (p for p, c in refs.items() if c < 2)
        )
        ranked = sorted(pages, key=lambda p: (la.get(p, -1), p))
        return ranked if n is None else ranked[: int(n)]

    # ---------------------------------------------------------- fragmentation

    def fragmentation(self) -> Dict[str, float]:
        """Free-run-length distribution: how contiguous the free pool is.
        ``frag_ratio`` is 0.0 when all free pages form one run (or none are
        free) and approaches 1.0 as the free space shatters into single-page
        runs — a threshold alert rule watches this via ``serve.fragmentation``.

        The alias-aware pair sizes what tiering could actually reclaim:
        ``pages_pinned_shared`` (refcount >= 2 — never spill-eligible while
        any sharer is active) and ``pages_reclaimable`` (refcount 1 — one
        release or spill away from free). They always sum with the free
        count to the whole pool."""
        free = sorted(self._free)
        shared = sum(1 for c in self._refs.values() if c >= 2)
        extra = {
            "pages_pinned_shared": shared,
            "pages_reclaimable": len(self._refs) - shared,
        }
        if not free:
            return {"free_runs": 0, "largest_run": 0, "frag_ratio": 0.0, **extra}
        runs = 1
        largest = cur = 1
        for prev, nxt in zip(free, free[1:]):
            if nxt == prev + 1:
                cur += 1
            else:
                runs += 1
                cur = 1
            if cur > largest:
                largest = cur
        return {
            "free_runs": runs,
            "largest_run": largest,
            "frag_ratio": round(1.0 - largest / len(free), 4),
            **extra,
        }

    def check_invariants(self) -> None:
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate pages on the free list"
        assert SCRATCH_PAGE not in free and SCRATCH_PAGE not in self._refs
        assert not (free & set(self._refs)), "page both free and referenced"
        assert len(free) + len(self._refs) == self.pages_total
        assert all(n >= 1 for n in self._refs.values())
        assert set(self._last_access) <= set(self._refs), (
            "heat stamp on a non-referenced page"
        )
        frag = self.fragmentation()
        assert (frag["largest_run"] == 0) == (not self._free)
        assert frag["largest_run"] <= len(self._free)
        assert 0.0 <= frag["frag_ratio"] <= 1.0
        # alias consistency: spill candidates never include a shared page,
        # and the reclaimable/pinned split tiles the referenced set
        shared = {p for p, c in self._refs.items() if c >= 2}
        assert not (set(self.coldest()) & shared), (
            "shared page ranked spill-eligible"
        )
        assert (
            frag["pages_pinned_shared"] + frag["pages_reclaimable"]
            == len(self._refs)
        )

    def stats(self) -> Dict[str, int]:
        return {
            "pages_total": self.pages_total,
            "pages_free": self.pages_free,
            "pages_shared": self.pages_shared,
            "page_size": self.page_size,
            "page_allocs": self.allocs,
            "page_shares": self.shares,
        }


class PageTable:
    """Host mirror of the device page-table cache variable: one ordered
    page list per slot, flattened into the ``[num_slots, max_pages]`` int32
    array the compiled decode step gathers through. Unused entries hold
    ``SCRATCH_PAGE``. The engine pushes ``table`` to the device whenever
    ``dirty`` (admission, release, growth) — the mirror is the single
    source of truth."""

    def __init__(self, num_slots: int, max_pages: int):
        self.num_slots = int(num_slots)
        self.max_pages = int(max_pages)
        self.table = np.full(
            (self.num_slots, self.max_pages), SCRATCH_PAGE, np.int32
        )
        self._lists: Dict[int, List[int]] = {}
        self.dirty = True  # first push seeds the device copy

    def pages(self, slot: int) -> List[int]:
        return list(self._lists.get(slot, ()))

    def count(self, slot: int) -> int:
        return len(self._lists.get(slot, ()))

    def assign(self, slot: int, pages: Sequence[int]) -> None:
        pages = [int(p) for p in pages]
        if len(pages) > self.max_pages:
            raise ValueError(
                f"slot {slot}: {len(pages)} pages > max_pages {self.max_pages}"
            )
        self._lists[slot] = pages
        self.table[slot, :] = SCRATCH_PAGE
        self.table[slot, : len(pages)] = pages
        self.dirty = True

    def grow(self, slot: int, page: int) -> None:
        """Append one page to a slot's list (decode crossed a boundary)."""
        lst = self._lists.setdefault(slot, [])
        if len(lst) >= self.max_pages:
            raise ValueError(f"slot {slot} already holds max_pages")
        self.table[slot, len(lst)] = int(page)
        lst.append(int(page))
        self.dirty = True

    def clear(self, slot: int) -> List[int]:
        """Drop a slot's list (release/preempt); returns the pages so the
        caller can hand them back to the allocator. The table row is zeroed
        so a released row's masked device writes land on the scratch page,
        never on a re-allocated one."""
        pages = self._lists.pop(slot, [])
        self.table[slot, :] = SCRATCH_PAGE
        self.dirty = True
        return pages

    def row(self, slot: int) -> np.ndarray:
        return self.table[slot].copy()

    def check_invariants(self, allocator: BlockAllocator) -> None:
        seen: Dict[int, int] = {}
        for slot, pages in self._lists.items():
            assert len(set(pages)) == len(pages), f"slot {slot} repeats a page"
            row = self.table[slot]
            assert list(row[: len(pages)]) == pages
            assert all(p == SCRATCH_PAGE for p in row[len(pages):])
            for p in pages:
                seen[p] = seen.get(p, 0) + 1
        for p, n in seen.items():
            assert allocator.refcount(p) == n, (
                f"page {p}: {n} list reference(s) vs refcount "
                f"{allocator.refcount(p)}"
            )
