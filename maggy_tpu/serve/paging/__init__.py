"""Paged KV cache: fixed-size pages + a host-side block allocator.

The slot cache (docs/serving.md "Slots and the compiled programs") reserves
``max_seq_len`` rows per slot, so HBM — not compute — caps concurrency. This
package decouples them: the engine's K/V storage becomes a flat *pool* of
``num_pages`` fixed-size pages and each slot holds an ordered *page list*;
a per-slot page-table row (``[max_pages]`` int32, a cache variable the
compiled decode step gathers through) maps logical positions to physical
pages. Consequences, in order of importance:

* **Concurrency tracks actual lengths.** A request occupies
  ``ceil(tokens/page_size)`` pages, not ``max_seq_len`` rows, so the same
  HBM admits several times more typical-length requests (``bench.py
  extra.paging`` gates ≥2x at a fixed simulated budget).
* **Prefix sharing is aliasing, not copying.** Admitting a request whose
  prompt shares a resident prefix points its page-table entries at the
  source's pages (ref-counted; ``serve.pages_shared``) instead of copying
  KV rows. Pages are copy-on-write by construction: writes only ever land
  past ``plen`` in privately-owned tail pages, so a shared page is never
  written in place.
* **Preemption is cheap.** Evicting a request frees its pages and retains
  only host state (prompt + generated tokens); re-admission re-prefills
  and continues byte-identically (docs/serving.md "Preemption").

Everything here is pure host-side bookkeeping (stdlib + numpy); the device
half lives in ``models/transformer.py`` (``_paged_cached_attention``) and
the engine's paged admit programs.
"""

from maggy_tpu.serve.paging.allocator import (  # noqa: F401
    SCRATCH_PAGE,
    BlockAllocator,
    OutOfPagesError,
    PageTable,
)

__all__ = [
    "BlockAllocator",
    "OutOfPagesError",
    "PageTable",
    "SCRATCH_PAGE",
]
