"""Continuous-batching decode engine: one compiled step, slot-based KV cache.

The engine owns a fixed-``B`` decode cache (``init_cache`` rows are *slots*)
and exactly three compiled programs:

* **prefill** — runs one request's prompt (padded to a power-of-two bucket,
  so compile count is O(log max_seq_len), not O(distinct lengths)) through a
  fresh single-row cache and samples the first token from the last valid
  logit. This is the request's TTFT token.
* **admit** — copies that prefilled row into a free slot of the batch cache
  and sets the slot's per-row write index to the TRUE prompt length (the
  pad's garbage K/V sit above the index and are masked by the per-row
  ``written`` bound until decode overwrites them, one slot per step).
* **decode step** — decodes ONE token for every slot under an active mask.
  Every input that varies as requests churn (tokens, positions, mask,
  sampling params, PRNG key rows) is a same-shape array, so the step
  compiles exactly once for the life of the engine — the XLA-friendly
  analogue of vLLM-style continuous batching. Retrace counters recorded as
  ``serve.decode_retraces`` / ``serve.prefill_retraces`` gauges prove it.

Per-request sampling keys: each request carries a base key derived from its
seed; the key for generated-token ``i`` is ``fold_in(base, i)``, so a
request's output depends only on (params, prompt, seed) — never on which
slot it landed in or what else shared the batch.

The per-row cache index (models/transformer.py ``_cached_attention``) is
what makes this work: slots sit at different sequence positions inside one
compiled program.

**Prefix-KV reuse (default; docs/fleet.md):** real traffic shares long
prompt prefixes (system prompts are identical across most requests). When a
new prompt shares a prefix of at least ``prefix_min`` tokens with a
*resident* slot's prompt (:class:`maggy_tpu.serve.prefix.PrefixIndex`), the
engine admits it with one compiled admit-from-prefix program: the source
row's already-computed KV rows ``[0, L)`` are copied device-side into a
fresh row (exact — for a shared prefix every layer input, and therefore
every cached K/V projection, is identical), only the suffix is prefilled
(positions ``L..plen``), and the row is written into the free slot with the
usual per-row index pin. Outputs are byte-identical to a full prefill;
``prefix_hits`` / ``prefix_tokens_saved`` counters prove the saved work.

**Paged KV cache (default; docs/serving.md "Paged KV cache"):** with
``paged=True`` the batch cache is a flat pool of fixed-size pages plus a
per-slot page-table row inside the ONE compiled decode program
(``models/transformer.py::_paged_cached_attention``), and a host-side
:class:`~maggy_tpu.serve.paging.BlockAllocator` owns the physical pages. A
request holds ``ceil(tokens/page_size)`` pages instead of a full
``max_seq_len`` row, so slot count decouples from HBM; prefix reuse becomes
*aliasing* ref-counted pages (zero KV copies for the shared full pages —
only the partial boundary page is copied, through the same one-program
admit) and eviction/preemption is a host-side page-list edit. Pages are
copy-on-write by construction: decode only ever writes past ``plen`` into
privately-owned tail pages, so a shared page is never written in place.
``paged=False`` (or ``MAGGY_TPU_SERVE_PAGED=0``) keeps the dense
row-per-slot path — outputs are byte-identical either way.

**Async decode (default; docs/performance.md):** ``step()`` dispatches
decode step ``i+1`` BEFORE host-reading step ``i``'s sampled tokens.
Continuing slots take their input token straight from the in-flight device
output (``jnp.where(use_prev, prev_sampled, host_tokens)`` inside the jit),
so the device→host→device round-trip per token disappears; the host drains
step ``i`` (``serve.drain_ms`` gauge) while step ``i+1`` computes. Token
streams are byte-identical to the synchronous path — the tokens fed forward
are the same sampled values, positions/keys advance identically, and the
one extra post-finish step a slot decodes before the host learns it
finished is discarded at drain (slot/request identity is checked). Pass
``async_decode=False`` (or ``MAGGY_TPU_SERVE_ASYNC=0``) for the strict
synchronous path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from maggy_tpu import telemetry
from maggy_tpu.exceptions import BadArgumentsError
from maggy_tpu.models.generate import init_cache, prefill
from maggy_tpu.telemetry import memtrack
from maggy_tpu.serve.paging import BlockAllocator, OutOfPagesError, PageTable
from maggy_tpu.serve.prefix import PrefixIndex
from maggy_tpu.serve.request import Request
from maggy_tpu.serve.slots import SlotManager, SlotOccupiedError
from maggy_tpu.serve.tier import HostPagePool, TieringPolicy

# fixed-size top-k filter: per-request top_k rides in as an array, the kth
# threshold is read from a static top-TOPK_CAP sort, keeping the decode step
# shape-stable for any requested k in [1, TOPK_CAP]
TOPK_CAP = 64

# smallest prefill bucket; prompts shorter than this share one compile
MIN_PREFILL_BUCKET = 8

# default KV page size (tokens) for the paged cache; must divide max_seq_len
DEFAULT_PAGE_SIZE = 16


def _sample_one(logits, temp, top_k, key):
    """Sample one token from one row's logits with dynamic temperature and
    (capped) top-k. ``temp <= 0`` is exact greedy — argmax, no RNG consumed —
    so greedy engine output can be compared token-for-token against
    :func:`maggy_tpu.models.generate.generate_cached`."""
    greedy = jnp.argmax(logits).astype(jnp.int32)
    cap = min(TOPK_CAP, logits.shape[-1])
    top_vals = jax.lax.top_k(logits, cap)[0]  # sorted desc
    kth = top_vals[jnp.clip(top_k - 1, 0, cap - 1)]
    filtered = jnp.where((top_k > 0) & (logits < kth), -jnp.inf, logits)
    scaled = filtered / jnp.maximum(temp, 1e-6)
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temp > 0.0, sampled, greedy)


def _base_key_data(seed: int) -> np.ndarray:
    """uint32 key data for a request's base PRNG key (host-side; raw key
    data rather than typed keys so rows stack/update like any array)."""
    return np.asarray(jax.random.key_data(jax.random.key(seed)), np.uint32)


@dataclasses.dataclass
class StepOutput:
    """One decode step's per-slot results (host-side)."""

    tokens: Dict[int, int]  # slot -> sampled token (active slots only)


class Engine:
    """Slot-based continuous-batching engine over a ``DecoderConfig`` model.

    Synchronous and single-threaded by design: the scheduler serializes all
    calls. ``params`` are the trained (non-decode) params, exactly what
    ``generate_cached`` takes.
    """

    def __init__(
        self,
        cfg,
        params: Any,
        num_slots: int = 4,
        mesh=None,
        telemetry_recorder=None,
        async_decode: Optional[bool] = None,
        prefix_reuse: Optional[bool] = None,
        prefix_min: Optional[int] = None,
        paged: Optional[bool] = None,
        page_size: Optional[int] = None,
        num_pages: Optional[int] = None,
        max_pages_per_req: Optional[int] = None,
        tier: Optional[bool] = None,
        tier_host_pages: Optional[int] = None,
        tier_low_water_pct: Optional[float] = None,
    ):
        from maggy_tpu.models import Decoder

        if cfg.decode:
            raise BadArgumentsError(
                "pass the TRAINING config; the engine builds the decode "
                "variant itself"
            )
        self.cfg = cfg
        self.decode_model = Decoder(dataclasses.replace(cfg, decode=True))
        self.params = params
        self.mesh = mesh
        self.slots = SlotManager(num_slots)
        self.max_seq_len = int(cfg.max_seq_len)
        self.telemetry = telemetry_recorder or telemetry.get()

        if async_decode is None:
            async_decode = os.environ.get(
                "MAGGY_TPU_SERVE_ASYNC", "1"
            ).lower() not in ("0", "false", "off")
        self.async_decode = async_decode

        if prefix_reuse is None:
            prefix_reuse = os.environ.get(
                "MAGGY_TPU_SERVE_PREFIX", "1"
            ).lower() not in ("0", "false", "off")
        self.prefix_reuse = prefix_reuse
        if prefix_min is None:
            prefix_min = int(
                os.environ.get("MAGGY_TPU_SERVE_PREFIX_MIN", MIN_PREFILL_BUCKET)
            )
        self.prefix_min = max(1, int(prefix_min))
        self.prefix_index = PrefixIndex(min_len=self.prefix_min)
        # prefix-reuse accounting (scheduler stats + SSTATS + telemetry)
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self.prefill_calls = 0  # full (from-scratch) prefills
        # prompt tokens ACTUALLY computed by prefill (suffix-only on any
        # reuse path) — the figure the fleet-KV bench compares across
        # affinity settings (bench.py extra.fleetkv)
        self.prefill_tokens = 0

        # ---- paged KV cache (docs/serving.md "Paged KV cache")
        if paged is None:
            paged = os.environ.get(
                "MAGGY_TPU_SERVE_PAGED", "1"
            ).lower() not in ("0", "false", "off")
        self.paged = bool(paged)
        if page_size is None:
            page_size = int(
                os.environ.get("MAGGY_TPU_SERVE_PAGE_SIZE", DEFAULT_PAGE_SIZE)
            )
        self.page_size = max(1, int(page_size))
        while self.max_seq_len % self.page_size:
            # any max_seq_len is served: fall back to the largest divisor
            self.page_size //= 2
        self.pages_per_row = self.max_seq_len // self.page_size
        # pool defaults to the dense capacity (num_slots full rows) plus the
        # reserved scratch page; pass num_pages to run UNDER the dense
        # budget — that is the whole point (bench.py extra.paging)
        self._num_pages_explicit = num_pages is not None
        self.num_pages = (
            int(num_pages)
            if num_pages is not None
            else num_slots * self.pages_per_row + 1
        )
        self.max_pages_per_req = min(
            self.pages_per_row,
            int(max_pages_per_req)
            if max_pages_per_req is not None
            else self.pages_per_row,
        )
        self.pages_aliased = 0  # cumulative pages shared instead of copied
        self._last_page_gauges = None
        # per-slot high-water page count while resident — the
        # ``pages_held_peak`` figure trace attribution (v2) records per
        # request; cleared with the slot in release()
        self._peak_pages: Dict[int, int] = {}
        if self.paged:
            self.paged_model = Decoder(
                dataclasses.replace(
                    cfg,
                    decode=True,
                    paged=True,
                    page_size=self.page_size,
                    num_pages=self.num_pages,
                )
            )
            self.allocator = BlockAllocator(self.num_pages, self.page_size)
            self.page_table = PageTable(num_slots, self.pages_per_row)
        else:
            self.paged_model = None
            self.allocator = None
            self.page_table = None
        # the model behind the batch decode step (prefill always runs the
        # dense single-row variant; paged admission re-pages its output)
        self._batch_model = self.paged_model or self.decode_model

        # ---- host-DRAM KV tier (docs/serving.md "Host-DRAM page tier")
        if tier is None:
            tier = os.environ.get(
                "MAGGY_TPU_SERVE_TIER", "1"
            ).lower() not in ("0", "false", "off")
        self._tier_pages_explicit = tier_host_pages is not None
        if self.paged and tier:
            if tier_host_pages is None:
                tier_host_pages = int(
                    os.environ.get(
                        "MAGGY_TPU_SERVE_TIER_PAGES", 2 * self.num_pages
                    )
                )
            self.tier = HostPagePool(
                int(tier_host_pages), telemetry_recorder=self.telemetry
            )
            self.tier_policy = (
                TieringPolicy(low_water_pct=float(tier_low_water_pct))
                if tier_low_water_pct is not None
                else TieringPolicy()
            )
        else:
            # dense mode has no page-granular KV to spill; the tier is a
            # paged-cache feature, quietly off otherwise
            self.tier = None
            self.tier_policy = None

        B = num_slots
        dummy = jnp.zeros((B, 1), jnp.int32)
        self.cache = init_cache(self._batch_model, dummy, mesh=mesh)
        # decode applies run under the mesh so activation constraints and the
        # sharded cache resolve; mesh-free (single chip / CPU) costs nothing
        self._ctx = (lambda: mesh) if mesh is not None else contextlib.nullcontext
        self.key_data = jnp.zeros((B, 2), jnp.uint32)
        # async double buffer: the dispatched-but-undrained decode step —
        # its device token refs plus (slot -> request id) at dispatch time,
        # so a drain can discard rows whose slot churned in the meantime
        self._pending: Optional[Dict[str, Any]] = None
        self._zero_tokens = jnp.zeros((B,), jnp.int32)
        # last async-drain host cost, sampled by the autopilot's serve
        # diagnoser (the gauge of the same name feeds dashboards)
        self.last_drain_ms = 0.0

        # trace-time side effects: these counters tick ONLY when jax retraces
        # the function, so they count compiles, not calls — the acceptance
        # telemetry that proves the decode step never recompiles under churn
        self._decode_traces = 0
        self._prefill_traces = 0
        self._admit_traces = 0
        self._prefix_traces = 0
        self._last_compile_gauges = None

        self._decode_jit = jax.jit(self._decode_impl)
        self._admit_jit = jax.jit(self._admit_impl)
        self._prefill_jit = jax.jit(self._prefill_impl)
        self._prefix_admit_jit = jax.jit(self._prefix_admit_impl)
        self._paged_admit_jit = jax.jit(self._paged_admit_impl)
        self._paged_prefix_admit_jit = jax.jit(self._paged_prefix_admit_impl)
        # abstract single-row cache: the leaf-shape template the prefix-admit
        # extraction uses to find each leaf's batch axis (mirrors _admit_impl)
        self._row_abstract = jax.eval_shape(
            lambda: init_cache(self.decode_model, jnp.zeros((1, 1), jnp.int32))
        )

        self.steps = 0
        self.tokens_out = 0

        # capacity ledger: the engine's share of HBM, reconciled at 1 Hz by
        # the scheduler's metrics tick (telemetry/memtrack.py)
        self.memory = memtrack.MemoryLedger()
        self._register_memory_accounts()

    def _register_memory_accounts(self) -> None:
        """(Re)register this engine's ledger accounts from live array sizes;
        called at build and after every reconfigure so the figures track the
        actual geometry (register is idempotent — no double counting)."""
        self.memory.register("params", memtrack.array_bytes(self.params))
        cache_bytes = memtrack.array_bytes(self.cache)
        self.memory.register("kv_pages", cache_bytes)
        self.memory.register(
            "workspace",
            memtrack.array_bytes(self.key_data)
            + memtrack.array_bytes(self._zero_tokens),
        )
        # KV bytes one resident token pins, from the real cache geometry —
        # sizes the prefix residency view (serve/prefix.py)
        cap_tokens = (
            self.num_pages * self.page_size
            if self.paged
            else self.slots.num_slots * self.max_seq_len
        )
        self.prefix_index.bytes_per_token = max(1, cache_bytes // max(1, cap_tokens))

    # ------------------------------------------------------------- jit bodies

    def _prefill_impl(self, params, tokens, plen, temp, top_k, key_data, gen0):
        """tokens [1, Pp] (bucket-padded), plen scalar — returns the filled
        single-row cache and the first sampled token. ``gen0`` is the
        generated-token index the sample resumes at: 0 for a fresh request,
        the retained token count for a preempted request being re-admitted
        from prompt+generated tokens (the PRNG chain continues exactly
        where decode would have — docs/serving.md "Preemption")."""
        self._prefill_traces += 1
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape
        )
        logits, cache = prefill(self.decode_model, params, tokens, positions)
        last = jax.lax.dynamic_index_in_dim(
            logits[0], plen - 1, axis=0, keepdims=False
        )  # [V] — the logit that predicts the next generated token
        key = jax.random.fold_in(jax.random.wrap_key_data(key_data), gen0)
        tok = _sample_one(last, temp, top_k, key)
        return cache, tok

    def _admit_impl(self, cache, row_cache, key_data, slot, plen, key_pair):
        """Copy the prefilled single-row cache into batch row ``slot`` and pin
        that row's write index to the true prompt length."""
        self._admit_traces += 1

        def write(path, batch_leaf, row_leaf):
            if "index" in jax.tree_util.keystr(path):
                row = jnp.full_like(row_leaf, plen)
            else:
                row = row_leaf
            # the batch axis is the one whose extent differs (1 vs B); with
            # B == 1 the shapes tie and slot can only be 0, so axis choice
            # is irrelevant
            axis = next(
                (
                    i
                    for i, (a, b) in enumerate(zip(batch_leaf.shape, row.shape))
                    if a != b
                ),
                0,
            )
            starts = [jnp.int32(0)] * batch_leaf.ndim
            starts[axis] = slot
            return jax.lax.dynamic_update_slice(batch_leaf, row, starts)

        cache = jax.tree_util.tree_map_with_path(write, cache, row_cache)
        key_data = jax.lax.dynamic_update_slice(
            key_data, key_pair[None, :], (slot, jnp.int32(0))
        )
        return cache, key_data

    def _prefix_admit_impl(
        self,
        params,
        cache,
        key_data,
        src_slot,
        dst_slot,
        suffix_tokens,
        start,
        plen,
        gen0,
        temp,
        top_k,
        key_pair,
    ):
        """Admit-from-prefix, one compiled program per suffix bucket: extract
        batch row ``src_slot`` as a single-row cache whose write index is
        pinned to ``start`` (the shared-prefix length — rows above it are the
        source's own suffix/generated K/V, masked exactly like prefill pad
        garbage), prefill ONLY the suffix through it (positions
        ``start..start+Sb``), sample the first token from the last valid
        suffix logit, and copy the row into ``dst_slot`` via the admit body.

        ``start``/``plen`` are traced scalars, so reuse length never
        retraces; only the suffix bucket shape does (same O(log) compile
        ladder as full prefill)."""
        self._prefix_traces += 1

        def extract(path, batch_leaf, row_ab):
            if "index" in jax.tree_util.keystr(path):
                return jnp.full(row_ab.shape, start, row_ab.dtype)
            axis = next(
                (
                    i
                    for i, (a, r) in enumerate(
                        zip(batch_leaf.shape, row_ab.shape)
                    )
                    if a != r
                ),
                0,
            )
            starts = [jnp.int32(0)] * batch_leaf.ndim
            starts[axis] = src_slot
            return jax.lax.dynamic_slice(batch_leaf, starts, row_ab.shape)

        row_cache = jax.tree_util.tree_map_with_path(
            extract, cache, self._row_abstract
        )
        positions = (start + jnp.arange(suffix_tokens.shape[1], dtype=jnp.int32))[
            None, :
        ]
        logits, mutated = self.decode_model.apply(
            {"params": params, "cache": row_cache},
            suffix_tokens,
            positions,
            mutable=["cache"],
        )
        last = jax.lax.dynamic_index_in_dim(
            logits[0], plen - start - 1, axis=0, keepdims=False
        )  # [V] — the logit at overall position plen-1, same as full prefill
        key = jax.random.fold_in(jax.random.wrap_key_data(key_pair), gen0)
        tok = _sample_one(last, temp, top_k, key)
        cache, key_data = self._admit_impl(
            cache, mutated["cache"], key_data, dst_slot, plen, key_pair
        )
        return cache, key_data, tok

    # ------------------------------------------------------ paged jit bodies

    def _paged_admit_impl(
        self, cache, row_cache, key_data, write_ids, slot, plen, key_pair
    ):
        """Write a prefilled dense single-row cache into the page pool.

        ``write_ids`` is a ``[pages_per_row]`` int32 host-built map: entry
        ``j`` is the physical page that receives the row's logical page
        ``j``, or the scratch page 0 for pages this request does not own —
        prefix-ALIASED pages (their content is already correct and shared;
        writing them would violate copy-on-write) and pages past the
        prompt. Scratch writes are garbage by contract; real pages receive
        a FULL page of row content, so the write is idempotent against any
        masked garbage an in-flight async step may have scattered there."""
        self._admit_traces += 1
        row = {
            jax.tree_util.keystr(path): leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(row_cache)[0]
        }

        def write(path, leaf):
            ks = jax.tree_util.keystr(path)
            if "pages" in ks:
                return leaf  # host-owned: the engine pushes the table
            if "index" in ks:
                b = leaf.shape[-1]
                return jnp.where(jnp.arange(b) == slot, plen, leaf)
            rl = row[ks]  # [(L,) 1, S, Kh, Dh] dense row
            P = leaf.shape[-3]
            if leaf.ndim == 5:  # scanned pool [L, N, P, Kh, Dh]
                pages = rl[:, 0].reshape(
                    rl.shape[0], -1, P, *rl.shape[3:]
                )
                return leaf.at[:, write_ids].set(pages.astype(leaf.dtype))
            pages = rl[0].reshape(-1, P, *rl.shape[3:])
            return leaf.at[write_ids].set(pages.astype(leaf.dtype))

        cache = jax.tree_util.tree_map_with_path(write, cache)
        key_data = jax.lax.dynamic_update_slice(
            key_data, key_pair[None, :], (slot, jnp.int32(0))
        )
        return cache, key_data

    def _paged_prefix_admit_impl(
        self,
        params,
        cache,
        key_data,
        src_row_ids,
        write_ids,
        dst_slot,
        suffix_tokens,
        start,
        plen,
        gen0,
        temp,
        top_k,
        key_pair,
    ):
        """Paged admit-from-prefix, one compiled program per suffix bucket.

        The source request's page-table row (``src_row_ids``) gathers its
        pool pages back into a dense single-row workspace whose index is
        pinned to ``start``; ONLY the suffix runs through the model
        (positions ``start..plen``), and the mutated row is re-paged via
        ``write_ids`` — which routes the shared full pages to scratch, so
        the aliased pages are never rewritten (zero KV copies for the
        shared prefix; the partial boundary page is the one copy, carried
        through the workspace). The persistent sharing is pure host state:
        the allocator ref-counts the aliased page ids into the new
        request's page list before this program runs."""
        self._prefix_traces += 1
        pooled = {
            jax.tree_util.keystr(path): leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]
        }

        def extract(path, row_ab):
            ks = jax.tree_util.keystr(path)
            if "index" in ks:
                return jnp.full(row_ab.shape, start, row_ab.dtype)
            leaf = pooled[ks]
            if leaf.ndim == 5:  # scanned pool [L, N, P, Kh, Dh]
                return leaf[:, src_row_ids].reshape(row_ab.shape)
            return leaf[src_row_ids].reshape(row_ab.shape)

        row_cache = jax.tree_util.tree_map_with_path(
            extract, self._row_abstract
        )
        positions = (start + jnp.arange(suffix_tokens.shape[1], dtype=jnp.int32))[
            None, :
        ]
        logits, mutated = self.decode_model.apply(
            {"params": params, "cache": row_cache},
            suffix_tokens,
            positions,
            mutable=["cache"],
        )
        last = jax.lax.dynamic_index_in_dim(
            logits[0], plen - start - 1, axis=0, keepdims=False
        )
        key = jax.random.fold_in(jax.random.wrap_key_data(key_pair), gen0)
        tok = _sample_one(last, temp, top_k, key)
        cache, key_data = self._paged_admit_impl(
            cache, mutated["cache"], key_data, write_ids, dst_slot, plen,
            key_pair,
        )
        return cache, key_data, tok

    def _decode_impl(
        self,
        params,
        cache,
        key_data,
        prev_tokens,
        host_tokens,
        use_prev,
        pos,
        active,
        temp,
        top_k,
        gen_idx,
    ):
        """One token for every slot; inactive rows run masked (their cache
        index is reset to 0 afterwards so they never inflate the chunked
        cache-read bound or run past max_seq_len).

        ``prev_tokens`` is the previous dispatch's on-device sampled output;
        rows with ``use_prev`` feed it forward directly (async double
        buffer — the value never visits the host), the rest (fresh
        admissions, and every row on the synchronous path) take
        ``host_tokens``."""
        self._decode_traces += 1
        tokens = jnp.where(use_prev, prev_tokens, host_tokens)
        logits, mutated = self._batch_model.apply(
            {"params": params, "cache": cache},
            tokens[:, None],
            pos[:, None],
            mutable=["cache"],
        )
        cache = mutated["cache"]

        keys = jax.vmap(jax.random.fold_in)(
            jax.random.wrap_key_data(key_data), gen_idx
        )
        sampled = jax.vmap(_sample_one)(logits[:, 0], temp, top_k, keys)
        sampled = jnp.where(active, sampled, 0)

        def clamp_index(path, leaf):
            if "index" in jax.tree_util.keystr(path):
                return jnp.where(active, leaf, 0)
            return leaf

        cache = jax.tree_util.tree_map_with_path(clamp_index, cache)
        # advanced coordinates for the steady-state async fast path: while
        # the slot set is unchanged, the next dispatch reuses these device
        # refs verbatim — zero host arrays built or transferred per token
        next_pos = jnp.where(active, pos + 1, pos)
        next_gen = jnp.where(active, gen_idx + 1, gen_idx)
        return cache, sampled, next_pos, next_gen

    # -------------------------------------------------------------- admission

    def _bucket(self, plen: int) -> int:
        b = MIN_PREFILL_BUCKET
        while b < plen:
            b *= 2
        return min(b, self.max_seq_len)

    def admit(self, request: Request) -> Tuple[int, int]:
        """Prefill ``request``'s prompt and claim a free slot for it.

        Returns ``(slot, first_token)`` — the first token IS the TTFT token,
        produced here, not in the decode loop. Raises
        :class:`SlotOccupiedError` when no slot is free,
        :class:`OutOfPagesError` when the paged pool cannot hold the prompt
        (the scheduler's cue to wait or preempt — never a failed request),
        and :class:`BadArgumentsError` when the request cannot fit at all.

        A request carrying generated tokens is a PREEMPTED request being
        re-admitted: the effective prompt is prompt+tokens and the sampling
        chain resumes at ``gen0 = len(tokens)``, so the continued stream is
        byte-identical to one that was never preempted.
        """
        prompt = [int(t) for t in request.prompt] + [
            int(t) for t in request.tokens
        ]
        gen0 = len(request.tokens)
        plen = len(prompt)
        p = request.params
        if len(request.prompt) < 1:
            raise BadArgumentsError("empty prompt")
        if len(request.prompt) + p.max_new > self.max_seq_len:
            raise BadArgumentsError(
                f"prompt ({len(request.prompt)}) + max_new ({p.max_new}) "
                f"exceeds max_seq_len ({self.max_seq_len})"
            )
        if not self.slots.free_slots():
            raise SlotOccupiedError("no free slot")
        if self.paged:
            worst = -(-(len(request.prompt) + p.max_new) // self.page_size)
            cap = min(self.max_pages_per_req, self.allocator.pages_total)
            if worst > cap:
                raise BadArgumentsError(
                    f"request needs up to {worst} pages "
                    f"(page_size {self.page_size}) > cap {cap} "
                    "(max_pages_per_req / pool size)"
                )

        key_pair = jnp.asarray(_base_key_data(p.seed))
        slot = self.slots.free_slots()[0]
        reuse = self._match_prefix(prompt)
        tok = None
        if self.tier is not None:
            tok = self._try_tier_admit(
                prompt, p, slot, gen0, reuse, key_pair, request
            )
        if tok is None:
            if self.paged:
                tok = self._admit_paged(prompt, p, slot, gen0, reuse, key_pair)
            else:
                tok = self._admit_dense(prompt, p, slot, gen0, reuse, key_pair)
        # claim the slot only after every device op succeeded — a throwing
        # prefill/admit must not leak an occupied slot bound to a dead request
        first = int(tok)
        assert (
            self.slots.admit(request, first, next_pos=plen, generated=gen0 + 1)
            == slot
        )
        self.prefix_index.insert(slot, prompt, gen=self.steps)
        self.tokens_out += 1
        self._record_compile_gauges()
        return slot, first

    def _admit_dense(self, prompt, p, slot, gen0, reuse, key_pair):
        """Dense-mode admission: full-row copy into the batch cache."""
        plen = len(prompt)
        if reuse is not None:
            src, shared = reuse
            # the suffix bucket must still fit above the shared rows — cap it
            # so the per-row cache write can never be position-clamped
            bucket = min(self._bucket(plen - shared), self.max_seq_len - shared)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, : plen - shared] = prompt[shared:]
            with self.telemetry.span(
                "serve.prefix_admit", bucket=bucket, shared=shared
            ), self._ctx():
                self.cache, self.key_data, tok = self._prefix_admit_jit(
                    self.params,
                    self.cache,
                    self.key_data,
                    jnp.int32(src),
                    jnp.int32(slot),
                    jnp.asarray(padded),
                    jnp.int32(shared),
                    jnp.int32(plen),
                    jnp.int32(gen0),
                    jnp.float32(p.temperature),
                    jnp.int32(p.top_k),
                    key_pair,
                )
            self._note_prefix_hit(shared, 0)
            self.prefill_tokens += plen - shared
        else:
            bucket = self._bucket(plen)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = prompt
            with self.telemetry.span("serve.prefill", bucket=bucket), self._ctx():
                row_cache, tok = self._prefill_jit(
                    self.params,
                    jnp.asarray(padded),
                    jnp.int32(plen),
                    jnp.float32(p.temperature),
                    jnp.int32(p.top_k),
                    key_pair,
                    jnp.int32(gen0),
                )
                self.cache, self.key_data = self._admit_jit(
                    self.cache,
                    row_cache,
                    self.key_data,
                    jnp.int32(slot),
                    jnp.int32(plen),
                    key_pair,
                )
            self.prefill_calls += 1
            self.prefill_tokens += plen
        return tok

    def _admit_paged(self, prompt, p, slot, gen0, reuse, key_pair):
        """Paged admission: allocate the prompt's pages (aliasing the shared
        full pages on a prefix hit), prefill (suffix-only on a hit), and
        re-page the resulting dense row through ``write_ids``. Allocation is
        rolled back if any device op throws, so a poison request leaks
        nothing."""
        plen = len(prompt)
        P = self.page_size
        n_prompt_pages = -(-plen // P)
        write_ids = np.zeros((self.pages_per_row,), np.int32)
        if reuse is not None:
            src, shared = reuse
            src_pages = self.page_table.pages(src)
            # full pages covered by the shared prefix are aliased; the
            # partial boundary page (if any) is copy-on-write — a fresh
            # page written from the workspace row
            shared_full = min(shared // P, len(src_pages), n_prompt_pages)
            fresh = self.allocator.alloc(n_prompt_pages - shared_full)
            aliased = src_pages[:shared_full]
            try:
                self.allocator.share(aliased)
            except Exception:
                self.allocator.release(fresh)
                raise
            page_list = aliased + fresh
            write_ids[shared_full:n_prompt_pages] = fresh
            bucket = min(self._bucket(plen - shared), self.max_seq_len - shared)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, : plen - shared] = prompt[shared:]
            try:
                with self.telemetry.span(
                    "serve.prefix_admit", bucket=bucket, shared=shared
                ), self._ctx():
                    self.cache, self.key_data, tok = self._paged_prefix_admit_jit(
                        self.params,
                        self.cache,
                        self.key_data,
                        jnp.asarray(self.page_table.row(src)),
                        jnp.asarray(write_ids),
                        jnp.int32(slot),
                        jnp.asarray(padded),
                        jnp.int32(shared),
                        jnp.int32(plen),
                        jnp.int32(gen0),
                        jnp.float32(p.temperature),
                        jnp.int32(p.top_k),
                        key_pair,
                    )
            except Exception:
                self.allocator.release(page_list)
                raise
            self._note_prefix_hit(shared, shared_full)
            self.prefill_tokens += plen - shared
        else:
            fresh = self.allocator.alloc(n_prompt_pages)
            page_list = fresh
            write_ids[:n_prompt_pages] = fresh
            bucket = self._bucket(plen)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = prompt
            try:
                with self.telemetry.span(
                    "serve.prefill", bucket=bucket
                ), self._ctx():
                    row_cache, tok = self._prefill_jit(
                        self.params,
                        jnp.asarray(padded),
                        jnp.int32(plen),
                        jnp.float32(p.temperature),
                        jnp.int32(p.top_k),
                        key_pair,
                        jnp.int32(gen0),
                    )
                    self.cache, self.key_data = self._paged_admit_jit(
                        self.cache,
                        row_cache,
                        self.key_data,
                        jnp.asarray(write_ids),
                        jnp.int32(slot),
                        jnp.int32(plen),
                        key_pair,
                    )
            except Exception:
                self.allocator.release(fresh)
                raise
            self.prefill_calls += 1
            self.prefill_tokens += plen
        self.page_table.assign(slot, page_list)
        self.allocator.touch(page_list, self.steps)
        self._peak_pages[slot] = len(page_list)
        self._push_page_table()
        self._pages_gauges()
        return tok

    def _note_prefix_hit(self, shared: int, shared_full_pages: int) -> None:
        self.prefix_hits += 1
        self.prefix_tokens_saved += shared
        self.pages_aliased += shared_full_pages
        self.telemetry.count("serve.prefix_hits")
        self.telemetry.count("serve.prefix_tokens_saved", shared)

    def _match_prefix(self, prompt) -> Optional[Tuple[int, int]]:
        """``(src_slot, shared_len)`` when a resident slot shares a usable
        prefix with ``prompt``. The shared length is clamped to ``plen - 1``:
        at least one suffix token must run through the model to produce the
        logit that samples the request's first token."""
        if not self.prefix_reuse:
            return None
        m = self.prefix_index.match(prompt, gen=self.steps)
        if m is None:
            return None
        src, lcp = m
        shared = min(lcp, len(prompt) - 1)
        if shared < self.prefix_min:
            return None
        return src, shared

    # ------------------------------------------------- host-DRAM KV tier

    def _tier_capture_pages(self, page_ids) -> Dict[str, np.ndarray]:
        """Device→host copy of the pool pages ``page_ids``, one
        ``[n, P, Kh, Dh]`` block stack per cache leaf (scanned leaves
        carry the layer axis in front: ``[n, L, P, Kh, Dh]``). Same
        ``jax.device_get`` serialization seam as the disaggregated
        prefill pack, so bytes survive the round trip."""
        ids = [int(p) for p in page_ids]
        blocks: Dict[str, np.ndarray] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(self.cache)[0]:
            ks = jax.tree_util.keystr(path)
            if "pages" in ks or "index" in ks:
                continue  # host-owned table / per-row write index
            if leaf.ndim == 5:  # scanned pool [L, N, P, Kh, Dh]
                blocks[ks] = np.moveaxis(jax.device_get(leaf[:, ids]), 1, 0)
            else:  # [N, P, Kh, Dh]
                blocks[ks] = jax.device_get(leaf[ids])
        return blocks

    def _tier_write_pages(self, page_ids, blocks) -> None:
        """Scatter host page blocks back into the device pool at
        ``page_ids`` — the eager inverse of :meth:`_tier_capture_pages`,
        run before the compiled suffix-admit gathers through them."""
        ids = jnp.asarray([int(p) for p in page_ids], jnp.int32)

        def write(path, leaf):
            ks = jax.tree_util.keystr(path)
            if ks not in blocks:
                return leaf
            b = blocks[ks]
            if leaf.ndim == 5:
                return leaf.at[:, ids].set(
                    jnp.asarray(np.moveaxis(b, 0, 1), leaf.dtype)
                )
            return leaf.at[ids].set(jnp.asarray(b, leaf.dtype))

        self.cache = jax.tree_util.tree_map_with_path(write, self.cache)

    def spill_stream(self, slot: int, pressure: bool = False) -> bool:
        """Capture a resident stream's valid KV pages into the host tier
        as a resume pack (``rid:<id>``) — the scheduler calls this
        immediately BEFORE preempt-releasing the slot, so re-admission
        becomes a swap-in instead of a full re-prefill. Valid rows are
        ``[0, len(prompt+tokens) - 1)``: prefill wrote the prompt's rows,
        each drained decode step wrote one more, and the newest sampled
        token was never fed back — exactly the rows re-prefill would
        recompute, so the swapped-in stream is byte-identical. False (no
        side effects) when the tier is off, the slot is empty, or the
        pack does not fit the host budget."""
        if self.tier is None:
            return False
        st = self.slots.get(slot)
        if st is None:
            return False
        tokens = [int(t) for t in st.request.prompt] + [
            int(t) for t in st.request.tokens
        ]
        valid = len(tokens) - 1
        if valid < 1:
            return False
        pages = self.page_table.pages(slot)
        need = (valid - 1) // self.page_size + 1
        if len(pages) < need:
            return False
        t0 = time.perf_counter()
        blocks = self._tier_capture_pages(pages[:need])
        ok = self.tier.put(
            f"rid:{st.request.id}",
            blocks,
            {"tokens": tuple(tokens), "valid": valid, "kind": "resume"},
        )
        if ok:
            self.tier_policy.note_spill(need, pressure=pressure)
            self.telemetry.count("tier.spills")
            self.telemetry.count("tier.spilled_pages", need)
            if pressure:
                self.telemetry.count("tier.pressure_spills")
            self.telemetry.histogram(
                "tier.spill_ms", (time.perf_counter() - t0) * 1e3
            )
        return ok

    def _spill_prefix(self, slot: int) -> None:
        """On release, park the departing prompt's full KV pages in the
        host tier as a prefix pack (``px:<digest>``) so a later request
        sharing the prefix swaps it in instead of re-prefilling — prefix
        reuse that survives eviction (docs/fleet.md "Fleet-global KV").
        Gated to prompts of at least one full page; best-effort."""
        if self.tier is None:
            return
        prompt = self.prefix_index.resident().get(slot)
        if not prompt:
            return
        prompt = tuple(int(t) for t in prompt)
        plen0 = len(prompt)
        if plen0 < self.page_size:
            return  # under one page: re-prefill beats a pack round-trip
        pages = self.page_table.pages(slot)
        valid = min(plen0, len(pages) * self.page_size)
        if valid < self.page_size:
            return
        need = (valid - 1) // self.page_size + 1
        t0 = time.perf_counter()
        blocks = self._tier_capture_pages(pages[:need])
        if self.tier.put(
            f"px:{PrefixIndex.digest(prompt)}",
            blocks,
            {"tokens": prompt, "valid": valid, "kind": "prefix"},
        ):
            self.tier_policy.note_spill(need, prefix=True)
            self.telemetry.count("tier.spills")
            self.telemetry.count("tier.prefix_spills")
            self.telemetry.count("tier.spilled_pages", need)
            self.telemetry.histogram(
                "tier.spill_ms", (time.perf_counter() - t0) * 1e3
            )

    def _try_tier_admit(self, prompt, p, slot, gen0, reuse, key_pair, request):
        """Tier-first admission: a resume pack (exact token match on this
        request's id) wins outright; otherwise a prefix pack is used only
        when it covers MORE shared tokens than the device-resident prefix
        index would. Returns the first sampled token, or None to fall
        through to the normal admit paths."""
        plen = len(prompt)
        if gen0 > 0:
            key = f"rid:{request.id}"
            got = self.tier.get(key) if self.tier.has(key) else None
            if got is not None:
                blocks, meta = got
                start = int(meta.get("valid", 0))
                if (
                    meta.get("kind") == "resume"
                    and tuple(meta.get("tokens", ())) == tuple(prompt)
                    and 1 <= start <= plen - 1
                ):
                    t0 = time.perf_counter()
                    tok = self._tier_admit(
                        prompt, p, slot, gen0, key_pair, blocks, start
                    )
                    self.tier.drop(key)  # one resume per preemption
                    n = next(iter(blocks.values())).shape[0]
                    self.tier_policy.note_fill(n)
                    self.telemetry.count("tier.fills")
                    self.telemetry.count("tier.filled_pages", n)
                    self.telemetry.histogram(
                        "tier.swap_in_ms", (time.perf_counter() - t0) * 1e3
                    )
                    self.prefill_tokens += plen - start
                    return tok
                self.tier.drop(key)  # stale pack: request state moved on
        if not self.prefix_reuse or plen - 1 < self.prefix_min:
            return None
        key = f"px:{PrefixIndex.digest(prompt)}"
        got = self.tier.get(key) if self.tier.has(key) else None
        if got is None:
            return None
        blocks, meta = got
        mtok = tuple(meta.get("tokens", ()))
        shared = 0
        for a, b in zip(mtok, prompt):
            if a != b:
                break
            shared += 1
        shared = min(shared, int(meta.get("valid", 0)), plen - 1)
        dev_shared = reuse[1] if reuse is not None else 0
        if shared < self.prefix_min or shared <= dev_shared:
            return None  # digest collision, or HBM-resident reuse is better
        t0 = time.perf_counter()
        cover = (shared - 1) // self.page_size + 1
        tok = self._tier_admit(
            prompt, p, slot, gen0, key_pair,
            {ks: arr[:cover] for ks, arr in blocks.items()}, shared,
        )
        self.tier_policy.note_fill(cover, prefix=True)
        self.telemetry.count("tier.fills")
        self.telemetry.count("tier.prefix_fills")
        self.telemetry.count("tier.filled_pages", cover)
        self.telemetry.histogram(
            "tier.swap_in_ms", (time.perf_counter() - t0) * 1e3
        )
        self.prefill_tokens += plen - shared
        self._note_prefix_hit(shared, 0)
        return tok

    def _tier_admit(self, prompt, p, slot, gen0, key_pair, blocks, start):
        """Shared restore path for both pack kinds: materialize the
        pack's pages into freshly allocated pool pages, then run ONLY the
        suffix (positions ``start..plen``) through the existing compiled
        prefix-admit program — same bucket ladder, no new jit body, and
        byte-identical to a full prefill because the restored rows are
        the full prefill's own bytes."""
        plen = len(prompt)
        P = self.page_size
        n_prompt_pages = -(-plen // P)
        # pages carrying restored rows [0, start); the suffix writes from
        # the page containing row ``start`` upward (the boundary page is
        # re-written WHOLE from the workspace row, whose low rows are the
        # restored bytes — idempotent, like every paged admit)
        cover = (start - 1) // P + 1
        fresh = self.allocator.alloc(n_prompt_pages)
        try:
            self._tier_write_pages(
                fresh[:cover], {ks: arr[:cover] for ks, arr in blocks.items()}
            )
            src_row_ids = np.zeros((self.pages_per_row,), np.int32)
            src_row_ids[:cover] = fresh[:cover]
            write_ids = np.zeros((self.pages_per_row,), np.int32)
            boundary = start // P
            write_ids[boundary:n_prompt_pages] = fresh[boundary:n_prompt_pages]
            bucket = min(self._bucket(plen - start), self.max_seq_len - start)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, : plen - start] = prompt[start:]
            with self.telemetry.span(
                "serve.prefix_admit", bucket=bucket, shared=start
            ), self._ctx():
                self.cache, self.key_data, tok = self._paged_prefix_admit_jit(
                    self.params,
                    self.cache,
                    self.key_data,
                    jnp.asarray(src_row_ids),
                    jnp.asarray(write_ids),
                    jnp.int32(slot),
                    jnp.asarray(padded),
                    jnp.int32(start),
                    jnp.int32(plen),
                    jnp.int32(gen0),
                    jnp.float32(p.temperature),
                    jnp.int32(p.top_k),
                    key_pair,
                )
        except Exception:
            self.allocator.release(fresh)
            raise
        self.page_table.assign(slot, fresh)
        self.allocator.touch(fresh, self.steps)
        self._peak_pages[slot] = len(fresh)
        self._push_page_table()
        self._pages_gauges()
        return tok

    def reconfigure(self, num_slots: int) -> None:
        """Drain-and-reconfigure seam: rebuild the slot geometry with
        ``num_slots`` rows. Must be called with NO active slots (the
        scheduler drains between waves first — docs/autotune.md "Continuous
        tuning"); resident prefix anchors are dropped with the old cache.

        The existing jit wrappers are kept — jax retraces them for the new
        batch shape — and the decode step is warmed here with one
        all-inactive dispatch, so the recompile is paid inside the
        reconfigure (while the autopilot suppresses guard samples), not by
        the first live request on the new geometry."""
        num_slots = int(num_slots)
        if num_slots < 1:
            raise BadArgumentsError(f"num_slots must be >= 1, got {num_slots}")
        if self.slots.active_count:
            raise SlotOccupiedError(
                f"reconfigure with {self.slots.active_count} active slot(s); "
                "drain first"
            )
        self.flush()
        if num_slots == self.slots.num_slots:
            return
        B = num_slots
        self.slots = SlotManager(B)
        self.prefix_index = PrefixIndex(min_len=self.prefix_min)
        if self.paged:
            from maggy_tpu.models import Decoder

            # pool scales with the slot count unless the operator pinned an
            # explicit page budget (then more slots share the same HBM —
            # the paged trade the autopilot's num_slots moves exploit)
            if not self._num_pages_explicit:
                self.num_pages = B * self.pages_per_row + 1
            self.paged_model = Decoder(
                dataclasses.replace(
                    self.cfg,
                    decode=True,
                    paged=True,
                    page_size=self.page_size,
                    num_pages=self.num_pages,
                )
            )
            self._batch_model = self.paged_model
            self.allocator = BlockAllocator(self.num_pages, self.page_size)
            self.page_table = PageTable(B, self.pages_per_row)
            self._last_page_gauges = None
            # the host tier survives reconfigure — block shapes depend
            # only on page_size, and prefix packs are content-addressed —
            # but an un-pinned budget tracks the new pool size
            if self.tier is not None and not self._tier_pages_explicit:
                self.tier.set_capacity(2 * self.num_pages)
        self._peak_pages = {}
        self.cache = init_cache(
            self._batch_model, jnp.zeros((B, 1), jnp.int32), mesh=self.mesh
        )
        self._push_page_table()
        self.key_data = jnp.zeros((B, 2), jnp.uint32)
        self._zero_tokens = jnp.zeros((B,), jnp.int32)
        self._pending = None
        # warm the decode compile at the new geometry (all rows masked)
        zeros_i = jnp.zeros((B,), jnp.int32)
        with self.telemetry.span("serve.reconfigure", num_slots=B), self._ctx():
            self.cache, _, _, _ = jax.block_until_ready(
                self._decode_jit(
                    self.params, self.cache, self.key_data,
                    zeros_i, zeros_i, jnp.zeros((B,), bool), zeros_i,
                    jnp.zeros((B,), bool), jnp.zeros((B,), jnp.float32),
                    zeros_i, zeros_i,
                )
            )
        self._record_compile_gauges()
        self._register_memory_accounts()

    def release(self, slot: int) -> Request:
        """Free a slot (EOS / max_new / cancel / deadline / preempt). THE
        one cache-resource release seam: every path that vacates a slot
        funnels through here, so pages and the prefix anchor can never leak
        on one exit path but not another. Pure host-side: the decode step
        zeroes inactive rows' cache index, paged writes of a cleared row
        are routed to the scratch page, and admission overwrites whole
        pages/rows."""
        if self.paged:
            if self.tier is not None:
                try:
                    self._spill_prefix(slot)
                except Exception:
                    pass  # best-effort: a failed spill never blocks release
            pages = self.page_table.clear(slot)
            if pages:
                self.allocator.release(pages)
            self._peak_pages.pop(slot, None)
            self._pages_gauges()
        self.prefix_index.remove(slot)
        return self.slots.evict(slot)

    def pages_held_peak(self, slot: int) -> int:
        """High-water page count of the request resident in ``slot`` (0 in
        dense mode). Read BEFORE :meth:`release` — the figure dies with the
        slot; the scheduler stamps it on the request's finish event for
        trace attribution (v2)."""
        return self._peak_pages.get(slot, 0)

    # ------------------------------------------------------------ page growth

    def prepare_step(self) -> List[int]:  # hot-loop (paged decode growth)
        """Paged only: make sure every active row owns the page its next
        write lands in (a row crosses a page boundary every ``page_size``
        tokens). Returns the slots whose growth the dry allocator refused —
        the scheduler preempts the youngest request and retries; an empty
        list means :meth:`step` is safe to dispatch. Dense mode returns
        ``[]`` unconditionally."""
        if not self.paged:
            return []
        needy: List[int] = []
        prev = self._pending
        P = self.page_size
        grew = False
        for s in self.slots.active_slots():
            st = self.slots.get(s)
            lag = (
                1
                if (
                    self.async_decode
                    and prev is not None
                    and prev["slots"].get(s) == st.request.id
                )
                else 0
            )
            need = (st.next_pos + lag) // P + 1
            while self.page_table.count(s) < need:
                try:
                    page = self.allocator.alloc(1)[0]
                except OutOfPagesError:
                    needy.append(s)
                    break
                self.page_table.grow(s, page)
                grew = True
            held = self.page_table.count(s)
            if held > self._peak_pages.get(s, 0):
                self._peak_pages[s] = held
            # heat stamp: an active row touches every page it holds this
            # step (attention reads them all) — host-side dict stores only
            self.allocator.touch(self.page_table.pages(s), self.steps)
        if grew:
            self._pages_gauges()
        return needy

    def _push_page_table(self) -> None:
        """Sync the host page-table mirror into the cache variable the
        compiled decode step gathers through. Cheap no-op unless admission,
        release, or growth dirtied the mirror — the steady-state decode
        fast path transfers nothing."""
        if not self.paged or not self.page_table.dirty:
            return
        tbl = jnp.asarray(self.page_table.table)

        def repl(path, leaf):
            if "pages" in jax.tree_util.keystr(path):
                return jnp.broadcast_to(tbl, leaf.shape)
            return leaf

        self.cache = jax.tree_util.tree_map_with_path(repl, self.cache)
        self.page_table.dirty = False

    def _pages_gauges(self) -> None:
        # journaled only on change, like the compile gauges: page counts
        # move at admission/release/boundary granularity, not per token
        a = self.allocator
        vals = (a.pages_free, a.pages_shared)
        if vals != self._last_page_gauges:
            self._last_page_gauges = vals
            self.telemetry.gauge("serve.pages_free", a.pages_free)
            self.telemetry.gauge("serve.pages_shared", a.pages_shared)

    # ----------------------------------------------------------------- decode

    def step(self) -> StepOutput:  # hot-loop (tools/check_host_sync.py)
        """Decode one token for every active slot.

        Synchronous mode returns THIS dispatch's tokens. Async mode (the
        default) returns the PREVIOUS dispatch's tokens — the new dispatch is
        issued first (its inputs chain from the in-flight device output), so
        the host-side drain/bookkeeping below overlaps device compute. With
        all slots free this degenerates to :meth:`flush`.
        """
        active_ids = self.slots.active_slots()
        if not active_ids:
            return self.flush()
        if self.paged:
            # page growth for this dispatch (no-op when the scheduler's
            # prepare_step/preempt pass already ran) + table sync if dirty
            needy = self.prepare_step()
            if needy:
                raise OutOfPagesError(
                    f"slots {needy} need pages and the pool is dry; "
                    "release or preempt before stepping"
                )
            self._push_page_table()
        prev = self._pending
        entries = {s: self.slots.get(s).request.id for s in active_ids}
        if (
            self.async_decode
            and prev is not None
            and prev["slots"] == entries
        ):
            # steady state (no churn since the last dispatch): every input
            # is a carried device ref — the previous step's own outputs.
            # use_prev == active (every live row continues its stream), so
            # no host array is built or transferred for this token at all.
            c = prev["carry"]
            inputs = (
                prev["sampled"], self._zero_tokens, c["active"], c["pos"],
                c["active"], c["temp"], c["top_k"], c["gen"],
            )
            carry_static = c
        else:
            B = self.slots.num_slots
            host_tokens = np.zeros((B,), np.int32)
            use_prev = np.zeros((B,), bool)
            pos = np.zeros((B,), np.int32)
            active = np.zeros((B,), bool)
            temp = np.zeros((B,), np.float32)
            top_k = np.zeros((B,), np.int32)
            gen_idx = np.zeros((B,), np.int32)
            for s in active_ids:
                st = self.slots.get(s)
                # a slot still holding the request it held at the previous
                # dispatch has exactly ONE undrained token in flight: feed
                # it forward on-device and advance pos/gen_idx past it
                lag = 1 if (
                    self.async_decode
                    and prev is not None
                    and prev["slots"].get(s) == st.request.id
                ) else 0
                if lag:
                    use_prev[s] = True
                else:
                    host_tokens[s] = st.last_token
                pos[s] = st.next_pos + lag
                gen_idx[s] = st.generated + lag
                active[s] = True
                temp[s] = st.request.params.temperature
                top_k[s] = st.request.params.top_k
            prev_tokens = (
                prev["sampled"] if prev is not None else self._zero_tokens
            )
            active_dev = jnp.asarray(active)
            temp_dev = jnp.asarray(temp)
            top_k_dev = jnp.asarray(top_k)
            inputs = (
                prev_tokens, jnp.asarray(host_tokens), jnp.asarray(use_prev),
                jnp.asarray(pos), active_dev, temp_dev, top_k_dev,
                jnp.asarray(gen_idx),
            )
            carry_static = {
                "active": active_dev, "temp": temp_dev, "top_k": top_k_dev,
            }
        with self.telemetry.span("serve.decode_step", active=len(active_ids)), self._ctx():
            self.cache, sampled, next_pos, next_gen = self._decode_jit(
                self.params, self.cache, self.key_data, *inputs
            )
        self.steps += 1
        self._record_compile_gauges()
        dispatched = {
            "sampled": sampled,
            "slots": entries,
            "carry": {**carry_static, "pos": next_pos, "gen": next_gen},
        }
        if not self.async_decode:
            return self._drain(dispatched)
        self._pending = dispatched
        # drain the PREVIOUS step while this one crunches on the device
        return self._drain(prev)

    def flush(self) -> StepOutput:
        """Drain the in-flight async dispatch, if any. The scheduler calls
        this when the active set empties (and may call it before
        cancellation/deadline decisions that need host-current state); the
        synchronous path has nothing pending and returns an empty output."""
        prev, self._pending = self._pending, None
        return self._drain(prev)

    def _drain(self, pending: Optional[Dict[str, Any]]) -> StepOutput:
        """Host-read one dispatched step's tokens and advance the slot
        mirror. Rows whose slot was released or re-admitted since dispatch
        (the post-finish garbage step async mode inevitably runs) are
        discarded — slot/request identity gates every emit."""
        if pending is None:
            return StepOutput(tokens={})
        t0 = time.perf_counter()
        sampled = np.asarray(pending["sampled"])  # sync: ok — lagged double-buffer drain
        drain_ms = (time.perf_counter() - t0) * 1e3
        self.last_drain_ms = drain_ms
        self.telemetry.gauge("serve.drain_ms", drain_ms)
        self.telemetry.histogram("serve.drain_ms", drain_ms)
        out: Dict[int, int] = {}
        for s, rid in pending["slots"].items():
            st = self.slots.get(s)
            if st is None or st.request.id != rid:
                continue  # slot churned since dispatch; token belongs to no one
            tok = int(sampled[s])
            self.slots.advance(s, tok)
            out[s] = tok
        self.tokens_out += len(out)
        return StepOutput(tokens=out)

    # ------------------------------------------------- disaggregated prefill

    def prefill_only(self, prompt: List[int], params, gen0: int = 0) -> Dict[str, Any]:
        """The prefill half of disaggregated serving (docs/fleet.md
        "Disaggregated prefill/decode"): run one prompt through the
        single-row prefill program — slots, batch cache, and the page pool
        are untouched — and return a host-resident KV pack. The pack's
        leaves are numpy (``jax.device_get``), which IS the serialization
        boundary: a decode replica re-materializes them with a device put
        in :meth:`admit_from_kv`, exactly the checkpoint/device-put path.

        Byte-identity holds end to end because prefill output is a pure
        function of (params, prompt, seed) and the host round-trip
        preserves bits."""
        prompt = [int(t) for t in prompt]
        plen = len(prompt)
        if plen < 1:
            raise BadArgumentsError("empty prompt")
        if plen >= self.max_seq_len:
            raise BadArgumentsError(
                f"prompt ({plen}) exceeds max_seq_len ({self.max_seq_len})"
            )
        key_pair = jnp.asarray(_base_key_data(params.seed))
        bucket = self._bucket(plen)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = prompt
        with self.telemetry.span("serve.prefill", bucket=bucket), self._ctx():
            row_cache, tok = self._prefill_jit(
                self.params,
                jnp.asarray(padded),
                jnp.int32(plen),
                jnp.float32(params.temperature),
                jnp.int32(params.top_k),
                key_pair,
                jnp.int32(gen0),
            )
        self.prefill_calls += 1
        self.prefill_tokens += plen
        self._record_compile_gauges()
        return {
            "row": jax.device_get(row_cache),
            "plen": plen,
            "first": int(tok),
        }

    def admit_from_kv(self, request: Request, pack: Dict[str, Any]) -> Tuple[int, int]:
        """Admit a request whose prompt a PREFILL replica already ran: the
        pack's dense row is device-put here and written into the batch
        cache (re-paged through fresh pages in paged mode) — no model
        forward runs on this engine for the prompt. Returns
        ``(slot, first_token)``; the first token was sampled at prefill
        time and rides in the pack."""
        p = request.params
        plen = int(pack["plen"])
        if request.tokens or plen != len(request.prompt):
            raise BadArgumentsError(
                "KV pack does not match the request state (stale handoff)"
            )
        if plen + p.max_new > self.max_seq_len:
            raise BadArgumentsError(
                f"prompt ({plen}) + max_new ({p.max_new}) exceeds "
                f"max_seq_len ({self.max_seq_len})"
            )
        if not self.slots.free_slots():
            raise SlotOccupiedError("no free slot")
        key_pair = jnp.asarray(_base_key_data(p.seed))
        slot = self.slots.free_slots()[0]
        with self.telemetry.span("serve.kv_admit", plen=plen), self._ctx():
            row_cache = jax.tree.map(jnp.asarray, pack["row"])  # device put
            if self.paged:
                worst = -(-(plen + p.max_new) // self.page_size)
                cap = min(self.max_pages_per_req, self.allocator.pages_total)
                if worst > cap:
                    raise BadArgumentsError(
                        f"request needs up to {worst} pages > cap {cap}"
                    )
                n_prompt_pages = -(-plen // self.page_size)
                fresh = self.allocator.alloc(n_prompt_pages)
                write_ids = np.zeros((self.pages_per_row,), np.int32)
                write_ids[:n_prompt_pages] = fresh
                try:
                    self.cache, self.key_data = self._paged_admit_jit(
                        self.cache,
                        row_cache,
                        self.key_data,
                        jnp.asarray(write_ids),
                        jnp.int32(slot),
                        jnp.int32(plen),
                        key_pair,
                    )
                except Exception:
                    self.allocator.release(fresh)
                    raise
                self.page_table.assign(slot, fresh)
                self.allocator.touch(fresh, self.steps)
                self._peak_pages[slot] = len(fresh)
                self._push_page_table()
                self._pages_gauges()
            else:
                self.cache, self.key_data = self._admit_jit(
                    self.cache,
                    row_cache,
                    self.key_data,
                    jnp.int32(slot),
                    jnp.int32(plen),
                    key_pair,
                )
        first = int(pack["first"])
        assert self.slots.admit(request, first) == slot
        self.prefix_index.insert(
            slot, [int(t) for t in request.prompt], gen=self.steps
        )
        self.tokens_out += 1
        self._record_compile_gauges()
        return slot, first

    # -------------------------------------------------------------- telemetry

    def _record_compile_gauges(self) -> None:
        # journaled only on change: these tick on RETRACES (rare by
        # design), and a per-step re-emit of two constant gauges was a
        # measurable slice of the per-token telemetry budget
        counts = (self._decode_traces, self._prefill_traces)
        if counts != self._last_compile_gauges:
            self._last_compile_gauges = counts
            self.telemetry.gauge("serve.decode_retraces", self._decode_traces)
            self.telemetry.gauge("serve.prefill_retraces", self._prefill_traces)

    @property
    def compile_counts(self) -> Dict[str, int]:
        return {
            "decode": self._decode_traces,
            "prefill": self._prefill_traces,
            "admit": self._admit_traces,
            "prefix_admit": self._prefix_traces,
        }

    @property
    def prefix_stats(self) -> Dict[str, Any]:
        """Reuse accounting for SSTATS/telemetry: hits, tokens the reuse
        saved from prefill, full prefills actually run, and the residency
        view (which prefixes pin how much KV, and how hot they are)."""
        return {
            "prefix_hits": self.prefix_hits,
            "prefix_tokens_saved": self.prefix_tokens_saved,
            "prefill_calls": self.prefill_calls,
            "prefill_tokens": self.prefill_tokens,
            "prefix_residency": self.prefix_index.residency_stats(
                gen=self.steps
            ),
        }

    @property
    def tier_stats(self) -> Dict[str, Any]:
        """Host-DRAM tier accounting for SSTATS/monitor/bench: pool
        occupancy plus the policy's spill/fill ledger. ``{"enabled":
        False}`` when the tier is off so panels can branch safely."""
        if self.tier is None:
            return {"enabled": False}
        return {
            "enabled": True,
            **self.tier.stats(),
            **self.tier_policy.stats(),
            # host-resident prefix digests, so the fleet prefix map counts
            # a spilled-but-swappable prefix as held by this replica
            "prefix_digests": [
                k[3:] for k in self.tier.keys() if k.startswith("px:")
            ],
        }

    @property
    def paging_stats(self) -> Dict[str, Any]:
        """Paged-cache accounting for SSTATS/monitor/bench: pool occupancy,
        sharing, and the per-request page cap. ``{"paged": False}`` on the
        dense fallback so panels can branch without key errors."""
        if not self.paged:
            return {"paged": False}
        return {
            "paged": True,
            "max_pages_per_req": self.max_pages_per_req,
            "pages_aliased_total": self.pages_aliased,
            **self.allocator.stats(),
            "fragmentation": self.allocator.fragmentation(),
            "heat": self.allocator.heat_buckets(self.steps),
        }

    def set_max_pages_per_req(self, value: int) -> None:
        """Autopilot seam (``serve.max_pages_per_req``, safe-live): caps how
        many pages ONE request may hold. Applies to future admissions and
        growth denials only — resident requests keep what they own."""
        self.max_pages_per_req = max(1, min(self.pages_per_row, int(value)))

    def set_tier_host_pages(self, value: int) -> None:
        """Autopilot seam (``serve.tier_host_pages``, safe-live): resize
        the host tier's page budget. Shrink evicts LRU packs immediately;
        an explicit value pins the budget across reconfigures."""
        if self.tier is None:
            return
        self._tier_pages_explicit = True
        self.tier.set_capacity(int(value))

    def set_tier_low_water(self, value: float) -> None:
        """Autopilot seam (``serve.tier_low_water_pct``, safe-live): move
        the pressure-spill trigger's headroom threshold."""
        if self.tier_policy is None:
            return
        self.tier_policy.low_water_pct = float(value)
