"""Deterministic traffic replay for overload and QoS testing.

Overload behaviour (brownout ladders, quota starvation, priority
preemption) can't be tested with hand-rolled submit loops — the interesting
failures live in the *shape* of traffic: diurnal load swell, correlated
bursts, tenants with shared-prefix prompt populations, a mixed-class
request population. This module makes that shape a seeded value:

* :func:`generate` turns a :class:`TrafficSpec` into a flat, time-sorted
  schedule of :class:`Arrival` rows. Same spec + same seed = the same
  schedule, byte for byte, on any machine — so an overload acceptance test
  replays the *identical* storm every run, and a bench compares two builds
  under the *identical* offered load.
* :class:`TrafficReplay` paces a schedule against a live
  :class:`~maggy_tpu.serve.client.ServeClient` (engine or fleet router —
  same verb set) from a background thread, collecting per-request outcomes
  (tokens, TTFT, shed/expired/failed) for the caller to assert on.

Arrival times are a per-tenant inhomogeneous Poisson process: each tenant's
rate is ``base_rps x weight-fraction x diurnal(t) x burst(t)``, thinned
into exponential inter-arrival gaps by a tenant-private
``random.Random(seed)`` stream, so adding a tenant (or reordering the mix)
never perturbs another tenant's arrivals. The chaos seam ``tenant_burst``
(:mod:`maggy_tpu.resilience.chaos`) multiplies one tenant's offered load at
schedule-build time, so a flood scenario is spelled as chaos
(``tenant_burst:tenant=bulk,mult=5``) instead of a bespoke spec.

Prompts come from a shared-prefix population: each tenant owns
``n_prefixes`` seeded prefix stems and every prompt is ``stem + fresh
suffix`` — the distribution that makes prefix caches and paged-KV sharing
do real work (docs/serving.md).
"""

from __future__ import annotations

import dataclasses
import math
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from maggy_tpu.core import lockdebug
from maggy_tpu.exceptions import RpcError, ServerBusyError
from maggy_tpu.resilience import chaos as chaos_mod
from maggy_tpu.serve.qos import DEFAULT_QOS, validate_qos


@dataclasses.dataclass(frozen=True)
class TenantMix:
    """One tenant's slice of the offered load."""

    tenant: str
    qos: str = DEFAULT_QOS
    weight: float = 1.0  # share of base_rps, normalized over all tenants
    prompt_len: int = 12  # tokens per prompt (stem + suffix)
    prefix_len: int = 0  # leading tokens drawn from a shared stem pool
    n_prefixes: int = 4  # size of this tenant's stem pool
    max_new: int = 8


@dataclasses.dataclass(frozen=True)
class Burst:
    """A correlated load spike: multiply every tenant's rate by ``mult``
    inside [start_s, start_s + duration_s)."""

    start_s: float
    duration_s: float
    mult: float = 4.0


@dataclasses.dataclass(frozen=True)
class TrafficSpec:
    """A complete, seeded description of an offered-load scenario."""

    seed: int
    duration_s: float
    base_rps: float
    tenants: Tuple[TenantMix, ...]
    # diurnal curve: rate(t) *= 1 + amp * sin(2*pi*t / period_s); amp=0
    # is flat. period defaults to the duration (one full swell per run).
    diurnal_amp: float = 0.0
    diurnal_period_s: Optional[float] = None
    bursts: Tuple[Burst, ...] = ()
    vocab: int = 256  # token ids are drawn from [2, vocab)

    def validate(self) -> None:
        if self.duration_s <= 0:
            raise ValueError(f"duration_s must be > 0, got {self.duration_s}")
        if self.base_rps <= 0:
            raise ValueError(f"base_rps must be > 0, got {self.base_rps}")
        if not self.tenants:
            raise ValueError("spec needs at least one TenantMix")
        for t in self.tenants:
            validate_qos(t.qos)
            if t.weight <= 0:
                raise ValueError(f"tenant {t.tenant!r}: weight must be > 0")
            if t.prefix_len > t.prompt_len:
                raise ValueError(
                    f"tenant {t.tenant!r}: prefix_len {t.prefix_len} exceeds "
                    f"prompt_len {t.prompt_len}"
                )


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled request: submit ``prompt`` at ``at_s`` (relative to
    replay start) for ``tenant`` under ``qos``."""

    at_s: float
    tenant: str
    qos: str
    prompt: Tuple[int, ...]
    max_new: int
    seq: int  # global arrival index after the time-sort (stable tiebreak)


def diurnal_burst_spec(
    *,
    seed: int = 7,
    duration_s: float = 12.0,
    base_rps: float = 6.0,
    burst_mult: float = 4.0,
    diurnal_amp: float = 0.6,
    max_new: int = 6,
) -> TrafficSpec:
    """The canned capacity-planning scenario: a diurnal swell with a
    correlated burst pinned to the swell's crest.

    This is the offered load the fleet autoscaler is sized against
    (``bench.py extra.autoscale``, ``tests/test_autoscale.py``): quiet
    shoulders where scale-in should engage, a crest that demands
    scale-out, and a mid-crest burst that drives the brownout ladder to
    level >= 2. Two tenants (a standard-class majority with shared prefix
    stems and a best-effort bulk minority) keep the QoS machinery honest
    during scale events. Same arguments = the same schedule, byte for
    byte (docs/fleet.md, "Autoscaling").
    """
    return TrafficSpec(
        seed=seed,
        duration_s=duration_s,
        base_rps=base_rps,
        tenants=(
            TenantMix(
                tenant="web",
                qos="standard",
                weight=3.0,
                prompt_len=12,
                prefix_len=6,
                n_prefixes=4,
                max_new=max_new,
            ),
            TenantMix(
                tenant="bulk",
                qos="best_effort",
                weight=1.0,
                prompt_len=10,
                max_new=max_new,
            ),
        ),
        diurnal_amp=diurnal_amp,
        # one full swell per run; the burst sits on the crest (t = T/4)
        diurnal_period_s=duration_s,
        bursts=(
            Burst(
                start_s=duration_s / 4,
                duration_s=duration_s / 6,
                mult=burst_mult,
            ),
        ),
    )


def _rate_at(spec: TrafficSpec, t: float, mix: TenantMix, frac: float) -> float:
    """This tenant's instantaneous requests/sec at offset ``t``."""
    rate = spec.base_rps * frac
    if spec.diurnal_amp:
        period = spec.diurnal_period_s or spec.duration_s
        rate *= max(0.0, 1.0 + spec.diurnal_amp * math.sin(2 * math.pi * t / period))
    for b in spec.bursts:
        if b.start_s <= t < b.start_s + b.duration_s:
            rate *= b.mult
    return rate


def generate(spec: TrafficSpec) -> List[Arrival]:
    """Expand a spec into its deterministic, time-sorted arrival schedule.

    Each tenant gets a private PRNG stream keyed off ``spec.seed`` and its
    index in the mix, and the inhomogeneous Poisson process is realized by
    thinning: candidate gaps are drawn at the tenant's *peak* rate, then
    accepted with probability rate(t)/peak — exact, and deterministic for a
    fixed spec. The chaos ``tenant_burst`` seam is consulted once per
    tenant at build time (schedule construction is the seam's documented
    consumer, so replays under chaos are still fully deterministic).
    """
    spec.validate()
    total_weight = sum(t.weight for t in spec.tenants)
    ch = chaos_mod.get()
    arrivals: List[Arrival] = []
    for idx, mix in enumerate(spec.tenants):
        rng = random.Random(spec.seed * 1000003 + idx)
        frac = mix.weight / total_weight
        burst_mult = ch.tenant_burst(mix.tenant) if ch is not None else 1.0
        # peak rate bounds the thinning proposal density
        peak = max(
            _rate_at(spec, t, mix, frac)
            for t in (
                0.0,
                spec.duration_s / 4,
                spec.duration_s / 2,
                3 * spec.duration_s / 4,
            )
        )
        for b in spec.bursts:
            peak = max(peak, _rate_at(spec, b.start_s, mix, frac))
        peak *= burst_mult
        if peak <= 0:
            continue
        stems = [
            tuple(rng.randrange(2, spec.vocab) for _ in range(mix.prefix_len))
            for _ in range(max(1, mix.n_prefixes))
        ]
        t = 0.0
        while True:
            t += rng.expovariate(peak)
            if t >= spec.duration_s:
                break
            accept = _rate_at(spec, t, mix, frac) * burst_mult / peak
            if rng.random() > accept:
                continue
            stem = stems[rng.randrange(len(stems))] if mix.prefix_len else ()
            suffix = tuple(
                rng.randrange(2, spec.vocab)
                for _ in range(mix.prompt_len - mix.prefix_len)
            )
            arrivals.append(
                Arrival(
                    at_s=t,
                    tenant=mix.tenant,
                    qos=mix.qos,
                    prompt=stem + suffix,
                    max_new=mix.max_new,
                    seq=0,  # assigned after the global sort
                )
            )
    arrivals.sort(key=lambda a: (a.at_s, a.tenant))
    return [dataclasses.replace(a, seq=i) for i, a in enumerate(arrivals)]


class TrafficReplay:
    """Pace a schedule against a live serving endpoint.

    ``start()`` launches a pacing thread that submits each arrival at its
    scheduled offset (never early; late only when the endpoint itself is
    slow — which is the overload signal under test, not a harness bug) and
    a polling pass that resolves submitted requests to terminal snapshots.
    Outcomes accumulate under the lock; ``wait()`` joins and returns them.

    One outcome dict per arrival: ``{seq, tenant, qos, status, rid?,
    snapshot?, error?, submitted_at_s}`` where status is ``done`` /
    ``cancelled`` / ``expired`` / ``failed`` / ``shed`` (typed BUSY) /
    ``submit_error`` / ``timeout``.
    """

    def __init__(
        self,
        client: Any,
        schedule: Sequence[Arrival],
        *,
        retry_busy: int = 0,
        result_timeout_s: float = 60.0,
        speed: float = 1.0,
        on_submit: Optional[Callable[[Arrival, Optional[str]], None]] = None,
    ):
        self.client = client
        self.schedule = list(schedule)
        self.retry_busy = int(retry_busy)
        self.result_timeout_s = float(result_timeout_s)
        self.speed = float(speed)  # >1 compresses the timeline (tests)
        self.on_submit = on_submit
        self._lock = lockdebug.lock("serve.loadgen")
        self.outcomes: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._inflight: List[Tuple[Arrival, str, float]] = []  # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None
        self._started_ts: Optional[float] = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "TrafficReplay":
        if self._thread is not None:
            raise RuntimeError("replay already started")
        self._started_ts = time.time()
        self._thread = threading.Thread(
            target=self._pace_loop, name="traffic-replay", daemon=True
        )
        self._thread.start()
        return self

    def wait(self, timeout: Optional[float] = None) -> List[Dict[str, Any]]:
        """Join the pacing thread and return all outcomes (time-ordered by
        arrival seq)."""
        if self._thread is None:
            raise RuntimeError("replay not started")
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise RpcError("traffic replay did not finish in time")
        with self._lock:
            return sorted(self.outcomes, key=lambda o: o["seq"])

    def run(self, timeout: Optional[float] = None) -> List[Dict[str, Any]]:
        return self.start().wait(timeout)

    # --------------------------------------------------------------- pacing

    def _record(self, outcome: Dict[str, Any]) -> None:
        with self._lock:
            self.outcomes.append(outcome)

    def _pace_loop(self) -> None:  # thread-entry — paces the schedule in real time
        start = self._started_ts or time.time()
        for arrival in self.schedule:
            due = start + arrival.at_s / self.speed
            delay = due - time.time()
            if delay > 0:
                time.sleep(delay)
            self._drain_done(block=False)
            self._submit_one(arrival)
        # schedule exhausted: resolve everything still in flight
        self._drain_done(block=True)

    def _submit_one(self, arrival: Arrival) -> None:
        submitted_at = time.time() - (self._started_ts or 0.0)
        base = {
            "seq": arrival.seq,
            "tenant": arrival.tenant,
            "qos": arrival.qos,
            "submitted_at_s": round(submitted_at, 4),
        }
        try:
            rid = self.client.submit(
                list(arrival.prompt),
                max_new=arrival.max_new,
                tenant=arrival.tenant,
                qos=arrival.qos,
                retry_busy=self.retry_busy,
            )
        except ServerBusyError as e:
            self._record({**base, "status": "shed", "error": str(e)})
            if self.on_submit is not None:
                self.on_submit(arrival, None)
            return
        except (RpcError, OSError, ValueError) as e:
            self._record({**base, "status": "submit_error", "error": str(e)})
            if self.on_submit is not None:
                self.on_submit(arrival, None)
            return
        with self._lock:
            self._inflight.append((arrival, rid, time.time()))
        if self.on_submit is not None:
            self.on_submit(arrival, rid)

    def _drain_done(self, block: bool) -> None:
        """Resolve in-flight requests to terminal outcomes; when ``block``
        poll until all are terminal or individually timed out."""
        while True:
            with self._lock:
                inflight = list(self._inflight)
            if not inflight:
                return
            still: List[Tuple[Arrival, str, float]] = []
            for arrival, rid, t0 in inflight:
                base = {
                    "seq": arrival.seq,
                    "tenant": arrival.tenant,
                    "qos": arrival.qos,
                    "submitted_at_s": round(
                        t0 - (self._started_ts or 0.0), 4
                    ),
                    "rid": rid,
                }
                try:
                    snap = self.client.poll(rid)
                except (RpcError, OSError) as e:
                    self._record({**base, "status": "failed", "error": str(e)})
                    continue
                if snap.get("done"):
                    self._record(
                        {**base, "status": snap.get("state"), "snapshot": snap}
                    )
                elif time.time() - t0 > self.result_timeout_s:
                    self._record(
                        {**base, "status": "timeout", "snapshot": snap}
                    )
                else:
                    still.append((arrival, rid, t0))
            with self._lock:
                self._inflight = still
            if not block or not still:
                return
            time.sleep(0.02)


def summarize(outcomes: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-class rollup of a replay's outcomes: counts by status, TTFT
    percentiles of completed requests, shed fraction — the shape the
    overload acceptance test and ``bench.py extra.qos`` both assert on."""
    by_class: Dict[str, Dict[str, Any]] = {}
    for o in outcomes:
        cls = by_class.setdefault(
            o["qos"], {"n": 0, "status": {}, "ttft_ms": []}
        )
        cls["n"] += 1
        cls["status"][o["status"]] = cls["status"].get(o["status"], 0) + 1
        snap = o.get("snapshot") or {}
        if o["status"] == "done" and snap.get("ttft_ms") is not None:
            cls["ttft_ms"].append(float(snap["ttft_ms"]))
    out: Dict[str, Any] = {}
    for qos, cls in by_class.items():
        ttfts = sorted(cls["ttft_ms"])

        def pct(q: float) -> Optional[float]:
            if not ttfts:
                return None
            return ttfts[min(len(ttfts) - 1, int(q * len(ttfts)))]

        out[qos] = {
            "n": cls["n"],
            "status": dict(cls["status"]),
            "done": cls["status"].get("done", 0),
            "shed": cls["status"].get("shed", 0),
            "ttft_p50_ms": pct(0.50),
            "ttft_p95_ms": pct(0.95),
        }
    return out
