"""Fleet-global KV: host-DRAM page tiering + prefix-affinity routing.

HBM is the only KV tier a replica has by default, and prefix reuse is
per-replica — so at fleet scale the same system prompt prefills N times
across N replicas and a preempted stream pays a full re-prefill on
re-admission. This package adds the two missing layers (ROADMAP open item
4; docs/serving.md "Host-DRAM page tier", docs/fleet.md "Fleet-global KV"):

* :class:`HostPagePool` — a pinned host-numpy page pool behind the KV-pack
  serialization seam. The engine spills cold KV pages (preempted streams,
  released prefix anchors) into it and pages them back on demand at
  admission, so a preemption's re-prefill becomes a cheap swap-in that is
  byte-identical through the existing resume seam.
* :class:`TieringPolicy` — the spill/fill decision layer, driven off the
  memory ledger's ``mem.headroom_pct``: when headroom crosses the low-water
  mark the scheduler spills the coldest victim stream to host instead of
  discarding its KV. Tier size and water marks are autopilot knobs
  (``serve.tier_host_pages`` / ``serve.tier_low_water_pct``).
* :class:`FleetPrefixMap` — a bounded fleet map of prefix digest →
  replicas holding it resident, fed from the SSTATS ``prefix_residency``
  snapshots the router already polls. The router adds an affinity bonus to
  ``projected_ttft_ms`` so identical prefixes stop prefilling N times
  across N replicas (``fleet.affinity_weight``; brownout zeroes it under
  overload).

Telemetry rides under ``tier.*`` (registered in telemetry/metrics.py);
the concurrency contracts of all three classes are pinned in
``tools/check_concurrency.py`` REQUIRED_MODELS.
"""

from maggy_tpu.serve.tier.host_pool import HostPagePool
from maggy_tpu.serve.tier.prefixmap import FleetPrefixMap
from maggy_tpu.serve.tier.tiering import TieringPolicy

__all__ = ["HostPagePool", "TieringPolicy", "FleetPrefixMap"]
