"""Host-DRAM KV page pool — the second tier under the HBM page pool.

The engine's :class:`~maggy_tpu.serve.paging.BlockAllocator` owns the HBM
pages; this pool owns their host-side shadow. KV pages cross the boundary
as plain numpy blocks (the same ``jax.device_get`` serialization seam the
disaggregated prefill handoff uses, so bytes survive the round trip), keyed
by pack: a *resume pack* (``rid:<id>``) holds a preempted stream's pages
for cheap swap-in, a *prefix pack* (``px:<digest>``) holds a released
prompt's full pages for cross-request reuse.

Storage is preallocated per-leaf numpy buffers — one ``[H, *block]`` array
per KV cache leaf, sharing ONE page-id space — so a spill is a memcpy into
pinned rows, not a malloc per page. Capacity is a page budget
(``serve.tier_host_pages``, an autopilot knob): a put that does not fit
evicts least-recently-used packs; a put larger than the whole budget is
refused (the caller falls back to plain re-prefill). Shrinking the budget
evicts immediately but keeps the buffers — host DRAM is reclaimed lazily
by growth, never mid-serve.

Written by the scheduler thread (spill at preempt/release, fill at admit)
and read by stats/RPC threads, so the directory is lock-guarded (pinned in
``tools/check_concurrency.py`` REQUIRED_MODELS). The ``host_pool_slow``
chaos seam injects swap-in latency in :meth:`get` — outside the lock, like
every chaos sleep.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from maggy_tpu import telemetry
from maggy_tpu.core import lockdebug
from maggy_tpu.resilience import chaos as chaos_mod


class HostPagePool:
    """Bounded LRU pool of host-resident KV page packs."""

    def __init__(self, capacity_pages: int, telemetry_recorder=None):
        self.telemetry = telemetry_recorder or telemetry.get()
        self._lock = lockdebug.lock("tier.host_pool")
        self._capacity = max(0, int(capacity_pages))  # guarded-by: _lock
        # per-leaf pinned buffers, one shared page-id space; rows are grown
        # on demand up to the minted high-water mark  # guarded-by: _lock
        self._buffers: Dict[str, np.ndarray] = {}
        self._free: List[int] = []  # recycled page ids  # guarded-by: _lock
        self._next_id = 0  # mint cursor  # guarded-by: _lock
        # pack directory: key -> {"pages", "meta", "seq"}  # guarded-by: _lock
        self._packs: Dict[str, Dict[str, Any]] = {}
        self._seq = 0  # LRU clock  # guarded-by: _lock
        self.evictions = 0  # guarded-by: _lock
        self.puts = 0  # guarded-by: _lock
        self.gets = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock

    # --------------------------------------------------------------- internal

    def _used(self) -> int:  # guarded-by: _lock
        return self._next_id - len(self._free)

    def _evict_lru(self) -> bool:  # guarded-by: _lock
        """Drop the least-recently-touched pack; False when empty."""
        if not self._packs:
            return False
        key = min(self._packs.items(), key=lambda kv: kv[1]["seq"])[0]
        self._free.extend(self._packs.pop(key)["pages"])
        self.evictions += 1
        return True

    def _mint(self, n: int) -> List[int]:  # guarded-by: _lock
        """Claim ``n`` page ids (recycled first), growing buffers to fit."""
        ids = [self._free.pop() for _ in range(min(n, len(self._free)))]
        while len(ids) < n:
            ids.append(self._next_id)
            self._next_id += 1
        high = max(ids) + 1
        for ks, buf in self._buffers.items():
            if buf.shape[0] < high:
                grown = np.zeros((high,) + buf.shape[1:], buf.dtype)
                grown[: buf.shape[0]] = buf
                self._buffers[ks] = grown
        return ids

    # ------------------------------------------------------------------- API

    def put(self, key: str, blocks: Dict[str, np.ndarray], meta: Dict[str, Any]) -> bool:  # thread-entry — scheduler loop spills, stats threads read
        """Spill one pack: ``blocks`` maps cache-leaf keys to ``[n, *block]``
        page stacks (all leaves the same ``n``). Replaces any pack already
        under ``key``; evicts LRU packs to fit; False when ``n`` exceeds the
        whole budget (caller keeps the re-prefill fallback)."""
        if not blocks:
            return False
        n = next(iter(blocks.values())).shape[0]
        evicted = 0
        with self._lock:
            old = self._packs.pop(key, None)
            if old is not None:
                self._free.extend(old["pages"])
            if n > self._capacity:
                return False
            while self._used() + n > self._capacity:
                if not self._evict_lru():
                    return False
                evicted += 1
            for ks, arr in blocks.items():
                if ks not in self._buffers:
                    self._buffers[ks] = np.zeros(
                        (0,) + arr.shape[1:], arr.dtype
                    )
            ids = self._mint(n)
            for ks, arr in blocks.items():
                self._buffers[ks][ids] = arr
            self._seq += 1
            self._packs[key] = {
                "pages": ids, "meta": dict(meta), "seq": self._seq,
            }
            self.puts += 1
        if evicted:
            self.telemetry.count("tier.host_evictions", evicted)
        return True

    def get(self, key: str) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, Any]]]:
        """Fill one pack back out: ``(blocks, meta)`` copies, or None. A hit
        refreshes the pack's LRU recency; the pack stays resident (drop is
        the caller's call — a resume pack dies on successful admit, a prefix
        pack serves many requests)."""
        with self._lock:
            pack = self._packs.get(key)
            if pack is None:
                self.misses += 1
                return None
            self._seq += 1
            pack["seq"] = self._seq
            self.gets += 1
            ids = list(pack["pages"])
            blocks = {ks: buf[ids] for ks, buf in self._buffers.items()}
            meta = dict(pack["meta"])
        ch = chaos_mod.get()
        if ch is not None:
            delay = ch.host_pool_slow()
            if delay > 0:
                time.sleep(delay)  # outside the lock, like every chaos sleep
        return blocks, meta

    def has(self, key: str) -> bool:
        with self._lock:
            return key in self._packs

    def drop(self, key: str) -> None:
        with self._lock:
            pack = self._packs.pop(key, None)
            if pack is not None:
                self._free.extend(pack["pages"])

    def keys(self) -> List[str]:  # thread-entry — SSTATS threads enumerate packs
        with self._lock:
            return list(self._packs)

    def set_capacity(self, capacity_pages: int) -> None:
        """Autopilot seam (``serve.tier_host_pages``, safe-live): shrink
        evicts LRU packs immediately; growth takes effect on the next put."""
        with self._lock:
            self._capacity = max(0, int(capacity_pages))
            while self._used() > self._capacity:
                if not self._evict_lru():
                    break

    def stats(self) -> Dict[str, Any]:  # thread-entry — SSTATS/monitor threads
        with self._lock:
            used = self._used()
            return {
                "host_pages_total": self._capacity,
                "host_pages_used": used,
                "host_pages_free": max(0, self._capacity - used),
                "host_bytes": sum(b.nbytes for b in self._buffers.values()),
                "resident_packs": len(self._packs),
                "host_evictions": self.evictions,
                "puts": self.puts,
                "gets": self.gets,
                "misses": self.misses,
            }
