"""Tiering policy: WHEN to spill KV out of HBM, and the spill/fill ledger.

The mechanism lives elsewhere — the engine captures/restores pages through
:class:`~maggy_tpu.serve.tier.HostPagePool`, the scheduler picks victims —
this class is the decision layer and the accounting the ``tier.*``
telemetry reads. Two spill triggers share it:

* **Event spills** are free rides on lifecycle edges: a preemption victim's
  pages are captured before release (resume pack), a released prompt's full
  pages become a prefix pack. No policy question — the pages were leaving
  HBM anyway.
* **Pressure spills** are proactive: when the memory ledger's
  ``mem.hbm_headroom_pct`` drops under the low-water mark
  (``serve.tier_low_water_pct``, an autopilot knob), the scheduler's 1 Hz
  metrics tick asks :meth:`should_spill` and preempts-with-spill the
  coldest low-class stream — freeing pool pages *before* an admission hits
  ``OutOfPagesError`` and has to preempt under the gun. The autopilot's
  memory-bound playbook grows the host budget ahead of shrinking
  ``serve.max_pages_per_req`` (spill before preempt — docs/autotune.md).

Counters move from the scheduler thread and are read by stats/RPC threads,
so they sit behind a lock (pinned in ``tools/check_concurrency.py``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from maggy_tpu.core import lockdebug

# default low-water mark: pressure-spill when HBM headroom drops under 5%
# (below the ledger's 10% alert mark, so the alert fires first and the
# spill is the remediation the playbook narrates)
DEFAULT_LOW_WATER_PCT = 0.05


class TieringPolicy:
    """Spill/fill decision + accounting for the host-DRAM KV tier."""

    def __init__(self, low_water_pct: float = DEFAULT_LOW_WATER_PCT):
        self.low_water_pct = float(low_water_pct)
        self._lock = lockdebug.lock("tier.policy")
        # cumulative spill/fill ledger, split by pack kind; exact mirror of
        # the tier.* counters so SSTATS can report without a telemetry
        # round-trip  # guarded-by: _lock
        self.spills = 0
        self.fills = 0
        self.spilled_pages = 0
        self.filled_pages = 0
        self.prefix_spills = 0
        self.prefix_fills = 0
        self.pressure_spills = 0

    def should_spill(self, headroom_pct: Optional[float]) -> bool:  # thread-entry — scheduler's 1 Hz metrics tick
        """One pressure verdict per metrics tick: True when the ledger's
        reconciled headroom sits under the low-water mark."""
        if headroom_pct is None:
            return False
        return float(headroom_pct) < self.low_water_pct

    # ---------------------------------------------------------------- ledger

    def note_spill(self, pages: int, prefix: bool = False, pressure: bool = False) -> None:
        with self._lock:
            self.spills += 1
            self.spilled_pages += int(pages)
            if prefix:
                self.prefix_spills += 1
            if pressure:
                self.pressure_spills += 1

    def note_fill(self, pages: int, prefix: bool = False) -> None:
        with self._lock:
            self.fills += 1
            self.filled_pages += int(pages)
            if prefix:
                self.prefix_fills += 1

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "low_water_pct": self.low_water_pct,
                "spills": self.spills,
                "fills": self.fills,
                "spilled_pages": self.spilled_pages,
                "filled_pages": self.filled_pages,
                "prefix_spills": self.prefix_spills,
                "prefix_fills": self.prefix_fills,
                "pressure_spills": self.pressure_spills,
            }
