"""Fleet prefix map: which replicas hold which prefix resident, bounded.

The router already polls every replica's SSTATS, and each snapshot carries
a ``prefix_residency`` block whose top anchors are identified by the
cross-process crc32 digest (:meth:`~maggy_tpu.serve.prefix.PrefixIndex.digest`).
This map folds those snapshots into one fleet view — digest → the replica
indices holding it resident — so dispatch can add an affinity bonus to
``projected_ttft_ms`` and stop prefilling the same system prompt N times
across N replicas (docs/fleet.md "Fleet-global KV").

Hash digests can collide, so the map only *suggests*: a wrong suggestion
costs one missed reuse on the chosen replica (its own prefix index
verifies against real tokens), never a wrong output.

Bounded: at most ``max_entries`` digests, LRU-evicted, so a hostile or
high-churn prefix population cannot grow router memory without limit.
Updated by the router pump (metrics tick, replica-down sweep) and read
under the router's dispatch lock — lock-guarded, pinned in
``tools/check_concurrency.py`` REQUIRED_MODELS.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, FrozenSet, Iterable, Set

from maggy_tpu.core import lockdebug


class FleetPrefixMap:
    """Bounded digest -> resident-replica map fed from SSTATS snapshots."""

    def __init__(self, max_entries: int = 4096):
        self.max_entries = max(1, int(max_entries))
        self._lock = lockdebug.lock("tier.prefix_map")
        # digest -> set of replica indices, LRU-ordered  # guarded-by: _lock
        self._digests: "OrderedDict[str, Set[int]]" = OrderedDict()
        # replica -> digests it contributed (for O(set) replacement when a
        # fresh snapshot or a death supersedes it)  # guarded-by: _lock
        self._by_replica: Dict[int, Set[str]] = {}

    def update(self, replica: int, digests: Iterable[str]) -> None:  # thread-entry — router pump's metrics tick
        """Replace ``replica``'s contribution with this snapshot's digests
        (residency is a point-in-time fact — anchors it no longer reports
        are gone from its HBM, so they leave the map too)."""
        fresh = {str(d) for d in digests if d}
        replica = int(replica)
        with self._lock:
            for d in self._by_replica.get(replica, set()) - fresh:
                holders = self._digests.get(d)
                if holders is not None:
                    holders.discard(replica)
                    if not holders:
                        del self._digests[d]
            for d in fresh:
                holders = self._digests.get(d)
                if holders is None:
                    self._digests[d] = {replica}
                else:
                    holders.add(replica)
                self._digests.move_to_end(d)
            self._by_replica[replica] = fresh
            while len(self._digests) > self.max_entries:
                stale, holders = self._digests.popitem(last=False)
                for r in holders:
                    self._by_replica.get(r, set()).discard(stale)

    def forget_replica(self, replica: int) -> None:  # thread-entry — router pump's down-sweep
        """A dead/quarantined replica's residents are unreachable — drop
        its contribution so affinity never routes toward a corpse."""
        self.update(int(replica), ())

    def replicas_for(self, digest: str) -> FrozenSet[int]:
        with self._lock:
            return frozenset(self._digests.get(str(digest), ()))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._digests),
                "max_entries": self.max_entries,
                "replicas": {
                    str(r): len(ds)
                    for r, ds in self._by_replica.items()
                    if ds
                },
            }
