"""Request lifecycle for the serving engine.

A :class:`Request` is the unit the scheduler moves through QUEUED ->
RUNNING -> (DONE | CANCELLED | EXPIRED | FAILED). State mutation belongs to
the scheduler thread alone; RPC handlers read wire snapshots taken under the
scheduler lock, so a request object never needs its own lock.
"""

from __future__ import annotations

import dataclasses
import secrets
import time
from typing import Any, Dict, List, Optional

from maggy_tpu.serve.qos import DEFAULT_QOS, DEFAULT_TENANT

# terminal states never transition again; the scheduler drops terminal
# requests from its index after RETENTION_S so poll() has a grace window
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
CANCELLED = "cancelled"
EXPIRED = "expired"
FAILED = "failed"

TERMINAL = frozenset((DONE, CANCELLED, EXPIRED, FAILED))


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decode controls, all static-shape-safe: temperature and
    top_k ride into the compiled step as arrays (top_k via a fixed-size
    top-``TOPK_CAP`` filter), so no combination ever retraces it."""

    temperature: float = 0.0
    top_k: int = 0
    max_new: int = 16
    eos_id: int = -1
    seed: int = 0

    def validate(self) -> None:
        if self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")
        if self.temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")


@dataclasses.dataclass
class Request:
    prompt: List[int]
    params: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    id: str = dataclasses.field(default_factory=lambda: secrets.token_hex(8))
    # request-scoped trace id (docs/observability.md): arrives on the SUBMIT
    # frame (client- or router-minted), else minted at scheduler admission;
    # every lifecycle event this request produces carries it
    trace: Optional[str] = None
    state: str = QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    # wall-clock lifecycle marks (None until reached)
    submitted_ts: float = dataclasses.field(default_factory=time.time)
    admitted_ts: Optional[float] = None
    first_token_ts: Optional[float] = None
    done_ts: Optional[float] = None
    # absolute wall-clock deadline; queued or running past it -> EXPIRED
    deadline_ts: Optional[float] = None
    # set by cancel(); the scheduler enacts it at the next loop boundary
    cancel_requested: bool = False
    # disaggregated serving (docs/fleet.md): a KV pack from a prefill
    # replica — the decode engine admits from it instead of prefilling.
    # Consumed on first admission; a preempted request re-prefills locally.
    prefilled: Optional[Dict[str, Any]] = None
    # times this request was preempted for pages (docs/serving.md); its
    # generated tokens are retained and re-admission resumes byte-identically
    preemptions: int = 0
    # per-tenant QoS (docs/fleet.md "QoS classes"): tenant is the accounting
    # identity, qos the scheduling class (admission priority, quota ledger
    # bucket, preemption ordering); wire default is best_effort
    tenant: str = DEFAULT_TENANT
    qos: str = DEFAULT_QOS

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return (self.first_token_ts - self.submitted_ts) * 1e3

    @property
    def queue_wait_ms(self) -> Optional[float]:
        if self.admitted_ts is None:
            return None
        return (self.admitted_ts - self.submitted_ts) * 1e3

    @property
    def e2e_ms(self) -> Optional[float]:
        if self.done_ts is None:
            return None
        return (self.done_ts - self.submitted_ts) * 1e3

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean per-token decode time after the first token (the Gemma
        serving comparison's TPOT); needs >= 2 tokens and a terminal ts."""
        if self.done_ts is None or self.first_token_ts is None:
            return None
        if len(self.tokens) < 2:
            return None
        return (self.done_ts - self.first_token_ts) * 1e3 / (len(self.tokens) - 1)

    def finish(self, state: str, error: Optional[str] = None) -> None:
        self.state = state
        self.error = error
        self.done_ts = time.time()

    def snapshot(self) -> Dict[str, Any]:
        """Wire-format view for the POLL verb (JSON-safe, no live refs)."""
        return {
            "id": self.id,
            "trace": self.trace,
            "state": self.state,
            "tokens": list(self.tokens),
            "n_tokens": len(self.tokens),
            "prompt_len": len(self.prompt),
            "error": self.error,
            "ttft_ms": self.ttft_ms,
            "tenant": self.tenant,
            "qos": self.qos,
            "preemptions": self.preemptions,
            "done": self.state in TERMINAL,
        }
