"""Prefill worker: the compute-bound half of disaggregated serving.

The Gemma-on-TPU serving comparison (PAPERS.md) quantifies the asymmetry
this role exploits: prefill is compute-bound (one big batched forward over
the prompt), decode is memory-bound (one token per step, HBM-limited).
Mixing them on one replica makes every decode wave stall behind whichever
prompt is currently prefilling. A fleet can instead tag replicas
``role="prefill"`` (:class:`~maggy_tpu.serve.fleet.replica.ReplicaSpec`):
the router sends each SUBMIT's prompt to a prefill replica first, then
hands the resulting KV pack to a decode replica, which admits it without
running the prompt (``Engine.admit_from_kv``).

The handoff payload is :meth:`Engine.prefill_only`'s host-resident pack
(numpy leaves via ``jax.device_get`` — the same serialization surface the
checkpoint path uses); the decode replica re-materializes it with a device
put. For in-process replicas the pack moves by reference; a cross-host
fleet would ship the same bytes over the wire. ``req.prefilled`` and
``req.handoff`` trace events plus the ``serve.handoff_ms`` histogram make
the hop visible on each request's PR 7 trace lane.

A :class:`PrefillWorker` wraps a prefill-role replica's engine behind a
lock (prefill programs are single-threaded by engine contract). If every
prefill replica is down, the router falls back to plain dispatch — decode
replicas still own a full engine, so disaggregation degrades to the
classic path instead of an outage.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from maggy_tpu.serve.request import SamplingParams


class PrefillWorkerError(RuntimeError):
    """Prefill-side failure; the router falls back to plain dispatch."""


class PrefillWorker:
    """Router-owned prefill front over a ``role="prefill"`` replica."""

    def __init__(self, replica):
        self.replica = replica
        self._lock = threading.Lock()
        self.prefills = 0

    @property
    def index(self) -> int:
        return self.replica.index

    def alive(self) -> bool:
        return self.replica.alive() and self.replica.server is not None

    def prefill(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Run one SUBMIT payload's prompt on the prefill replica's engine
        and return the host-resident KV pack (``Engine.prefill_only``)."""
        if not self.alive():
            raise PrefillWorkerError(f"prefill replica {self.index} is down")
        params = SamplingParams(
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            max_new=int(payload.get("max_new", 16)),
            eos_id=int(payload.get("eos_id", -1)),
            seed=int(payload.get("seed", 0)),
        )
        engine = self.replica.server.scheduler.engine
        try:
            with self._lock:
                pack = engine.prefill_only(payload["prompt"], params)
        except Exception as e:  # noqa: BLE001 - surface as a worker failure, router falls back
            raise PrefillWorkerError(
                f"prefill on replica {self.index} failed: "
                f"{type(e).__name__}: {e}"
            ) from e
        self.prefills += 1
        return pack


def pick_worker(workers, cursor: int) -> Optional[PrefillWorker]:
    """Round-robin over live prefill workers (None when all are down)."""
    if not workers:
        return None
    for offset in range(len(workers)):
        w = workers[(cursor + offset) % len(workers)]
        if w.alive():
            return w
    return None
