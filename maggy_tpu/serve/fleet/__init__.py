"""Serving fleet: a router front-end over N engine replicas.

The scale-out layer above the single continuous-batching engine
(:mod:`maggy_tpu.serve`): each replica is a full engine+scheduler+RPC stack
on a disjoint device lease, and the :class:`Router` is the one public
address — same SUBMIT/POLL/CANCEL/SSTATS verbs, so clients and the monitor
are fleet-oblivious. The router load-balances with SLO-aware admission
control (shed or queue on projected TTFT), probes replica health into the
resilience quarantine machinery, requeues a dead replica's in-flight
requests to survivors, and respawns within a restart budget. See
docs/fleet.md.

    spec = ReplicaSpec(cfg, params, num_slots=4)
    router = launch_fleet(spec, replicas=2, slo_ttft_ms=2000)
    host, port = router.start(host="127.0.0.1")
    # ... ServeClient((host, port), router.secret) as usual
    router.stop()
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from maggy_tpu.serve.fleet.autoscale import (  # noqa: F401
    AutoscaleConfig,
    Autoscaler,
)
from maggy_tpu.serve.fleet.prefill import (  # noqa: F401
    PrefillWorker,
    PrefillWorkerError,
)
from maggy_tpu.serve.fleet.replica import (  # noqa: F401
    Replica,
    ReplicaSpec,
    build_replicas,
)
from maggy_tpu.serve.fleet.router import (  # noqa: F401
    Router,
    RouterConfig,
    projected_ttft_ms,
)

__all__ = [
    "AutoscaleConfig",
    "Autoscaler",
    "PrefillWorker",
    "PrefillWorkerError",
    "Replica",
    "ReplicaSpec",
    "Router",
    "RouterConfig",
    "build_replicas",
    "launch_fleet",
    "projected_ttft_ms",
]


def launch_fleet(
    spec: ReplicaSpec,
    replicas: int = 2,
    config: Optional[RouterConfig] = None,
    secret: Optional[str] = None,
    name: str = "maggy-fleet",
    host: str = "127.0.0.1",
    telemetry_recorder=None,
    autopilot=None,
    autoscale=None,
    prefill_replicas: int = 0,
    **config_kwargs,
) -> Router:
    """Build a router over ``replicas`` fresh in-process replicas (device
    leases carved like trial sub-slices). Call ``router.start()`` to serve;
    extra kwargs go to :class:`RouterConfig` (``slo_ttft_ms=...`` etc.);
    ``autopilot`` attaches an online controller to the router
    (docs/autotune.md "Continuous tuning"); ``autoscale`` (True or an
    :class:`AutoscaleConfig`) attaches the fleet autoscaler, which grows
    and shrinks the replica pool between its min/max bounds with
    drain-safe scale events (docs/fleet.md "Autoscaling").

    ``prefill_replicas > 0`` builds a DISAGGREGATED fleet (docs/fleet.md):
    ``replicas`` decode-role replicas plus that many prefill-role replicas —
    the router prefills each prompt on a prefill replica and hands the KV
    pack to a decode replica."""
    if config is None:
        config = RouterConfig(**config_kwargs)
    elif config_kwargs:
        raise ValueError("pass either config= or RouterConfig kwargs, not both")
    if spec.slo_ttft_ms is None and config.slo_ttft_ms is not None:
        # thread the fleet SLO down so each replica's scheduler counts
        # exact per-request attainment in its own SSTATS
        spec = dataclasses.replace(spec, slo_ttft_ms=config.slo_ttft_ms)
    fleet = build_replicas(
        dataclasses.replace(spec, role="decode") if prefill_replicas else spec,
        replicas,
        secret or "",
        host=host,
    )
    if prefill_replicas:
        prefill_spec = dataclasses.replace(spec, role="prefill")
        for i in range(prefill_replicas):
            fleet.append(
                Replica(replicas + i, prefill_spec, secret or "", host=host)
            )
    router = Router(
        fleet,
        config=config,
        secret=secret,
        name=name,
        telemetry_recorder=telemetry_recorder,
        autopilot=autopilot,
        autoscale=autoscale,
    )
    return router
